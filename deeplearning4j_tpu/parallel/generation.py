"""KV-cached autoregressive decoding for the TransformerLM.

The TPU-idiomatic inference path: one jitted ``decode_step`` whose shapes
never change (the KV cache is a fixed [B, max_len, H, K] buffer updated
with ``lax.dynamic_update_slice``), driven by ``lax.scan`` — so the whole
generation loop is a single XLA program, no per-token retrace, no O(S²)
recompute per emitted token.

The 2015 reference has no generative inference at all; this backs the
framework's LM story (including weights imported from HF GPT-2 via
`runtime.model_import.import_hf_gpt2`, whose optional attention biases are
honored here).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.parallel.kernels import mask_value
from deeplearning4j_tpu.parallel.paged_kernel import (
    paged_flash_attention,
    resolve_paged_kernel,
)
from deeplearning4j_tpu.parallel.transformer import (
    TransformerConfig,
    _layer_norm,
    _mlp,
    _moe,
    lm_head,
    out_proj,
    qkv_proj,
)


def init_cache(cfg: TransformerConfig, batch: int) -> dict:
    """Fixed-shape KV cache: one [B, max_len, H, K] pair per layer."""
    dt = jnp.dtype(cfg.dtype)
    shape = (batch, cfg.max_len, cfg.n_heads, cfg.head_dim)
    return {
        "k": jnp.zeros((cfg.n_layers,) + shape, dt),
        "v": jnp.zeros((cfg.n_layers,) + shape, dt),
        "pos": jnp.zeros((), jnp.int32),
    }


def _cached_attn(p, x, layer_k, layer_v, pos):
    """Single-position attention against the cache.

    x: [B, 1, d]; layer_k/v: [B, max_len, H, K] with positions < pos
    filled; returns (out [B,1,d], new_k, new_v).
    """
    q, k, v = qkv_proj(p, x)
    layer_k = lax.dynamic_update_slice(layer_k, k, (0, pos, 0, 0))
    layer_v = lax.dynamic_update_slice(layer_v, v, (0, pos, 0, 0))
    d = q.shape[-1]
    s = jnp.einsum("bqhk,bshk->bqhs", q, layer_k) / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    valid = jnp.arange(layer_k.shape[1]) <= pos          # [max_len]
    s = jnp.where(valid[None, None, None, :], s, mask_value(s.dtype))
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bqhs,bshk->bqhk", w, layer_v)
    return out_proj(p, o), layer_k, layer_v


def decode_step(cfg: TransformerConfig, params: dict, cache: dict,
                token: jax.Array) -> Tuple[jax.Array, dict]:
    """token: [B] int32 at position cache['pos'] -> (logits [B,V], cache)."""
    pos = cache["pos"]
    x = params["embed"][token][:, None, :] + lax.dynamic_slice_in_dim(
        params["pos"], pos, 1, axis=0)[None]
    ks, vs = [], []
    for i, layer in enumerate(params["layers"]):
        a, nk, nv = _cached_attn(layer["attn"],
                                 _layer_norm(layer["ln1"], x),
                                 cache["k"][i], cache["v"][i], pos)
        ks.append(nk)
        vs.append(nv)
        x = x + a
        h = _layer_norm(layer["ln2"], x)
        # Dense-masked MoE (capacity_factor=0): exact, no drops — matches
        # apply()'s inference default, preserving this module's
        # cache-path == full-recompute contract for MoE configs.
        x = x + (_moe(layer["moe"], h, top_k=cfg.moe_top_k)
                 if "moe" in layer else _mlp(layer["mlp"], h))
    x = _layer_norm(params["ln_f"], x)
    logits = jnp.einsum("bsd,dv->bsv", x, lm_head(params))[:, 0]
    new_cache = {"k": jnp.stack(ks), "v": jnp.stack(vs), "pos": pos + 1}
    return logits, new_cache


def _filter_top_k(logits, top_k: int):
    """Keep the top_k largest logits per row; mask the rest."""
    kth = lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits < kth, -1e30, logits)


def _filter_top_p(logits, top_p):
    """Nucleus filtering: keep the smallest set of tokens whose
    cumulative probability reaches top_p (the argmax always survives)."""
    sorted_l = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    probs = jax.nn.softmax(sorted_l, axis=-1)
    # token kept iff the mass BEFORE it is still below top_p
    keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
    cutoff = jnp.min(jnp.where(keep, sorted_l, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits < cutoff, -1e30, logits)


def _prefill(cfg, params, prompt):
    """Run the prompt through the decoder: (filled cache, last logits)."""
    cache = init_cache(cfg, prompt.shape[0])

    def body(cache, tok):
        logits, cache = decode_step(cfg, params, cache, tok)
        return cache, logits

    cache, logits = lax.scan(body, cache, prompt.T)
    return cache, logits[-1]                              # [B, V]


@functools.lru_cache(maxsize=32)
def _compiled_run(cfg: TransformerConfig, batch: int, max_new_tokens: int,
                  sampled: bool, top_k: int, top_p: float):
    """One jitted program per (config, batch, length, mode) — stable across
    generate() calls so repeated generation never retraces."""

    @jax.jit
    def run(params, prompt, rng, temperature):
        cache, last = _prefill(cfg, params, prompt)

        def pick(logits, key):
            if not sampled:
                return jnp.argmax(logits, axis=-1)
            logits = logits.astype(jnp.float32) / temperature
            if 0 < top_k < logits.shape[-1]:
                logits = _filter_top_k(logits, top_k)
            if top_p < 1.0:
                logits = _filter_top_p(logits, top_p)
            return jax.random.categorical(key, logits)

        def step(carry, key):
            cache, last_logits = carry
            tok = pick(last_logits, key).astype(jnp.int32)
            logits, cache = decode_step(cfg, params, cache, tok)
            return (cache, logits), tok

        keys = jax.random.split(rng, max_new_tokens)
        (_, _), toks = lax.scan(step, (cache, last), keys)
        return toks.T                                     # [B, new]

    return run


def _validate_prompt(cfg, prompt, max_new_tokens):
    """Shared generate()/beam_search() prompt checks -> [B, P] int32."""
    prompt = jnp.asarray(prompt, jnp.int32)
    _, plen = prompt.shape
    if plen < 1:
        raise ValueError("prompt must contain at least one token "
                         "(the first sampled token conditions on it)")
    if plen + max_new_tokens > cfg.max_len:
        raise ValueError(f"prompt({plen}) + new({max_new_tokens}) exceeds "
                         f"max_len({cfg.max_len})")
    return prompt


def generate(cfg: TransformerConfig, params: dict, prompt,
             max_new_tokens: int, temperature: float = 0.0,
             rng: Optional[jax.Array] = None, top_k: int = 0,
             top_p: float = 1.0) -> jax.Array:
    """prompt: [B, P] int -> [B, P + max_new_tokens] int32.

    temperature 0 = greedy; otherwise softmax sampling (rng required),
    optionally truncated to the top_k most likely tokens and/or the
    top_p nucleus.  The prefill and every decode step run inside ONE
    jitted lax.scan, compiled once per (config, batch, length, mode).
    """
    prompt = _validate_prompt(cfg, prompt, max_new_tokens)
    batch = prompt.shape[0]
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature>0) requires rng")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    sampled = temperature > 0
    # Greedy never reads top_k/top_p — normalize them out of the cache
    # key so varying them cannot retrace or churn identical programs.
    run = _compiled_run(cfg, batch, max_new_tokens, sampled,
                        int(top_k) if sampled else 0,
                        float(top_p) if sampled else 1.0)
    new = run(params, prompt, rng,
              jnp.asarray(max(temperature, 1e-6), jnp.float32))
    return jnp.concatenate([prompt, new], axis=1)


# ---------------------------------------------------------------------------
# Slot-based decode (continuous batching for the serving engine)
#
# `generate()` above runs ONE request (or one fixed batch) to completion:
# every row shares a single scalar position.  A serving process wants the
# opposite shape: a fixed pool of B_slots decode lanes over one
# [L, B_slots, max_len, H, K] KV cache, where each slot sits at its OWN
# position — finished sequences free their slot and queued prompts join
# mid-flight (prefill rides the same per-token step, teacher-forced).
# The step below is that primitive; serving/lm.py drives the loop.


def _slot_attn(p, x, layer_k, layer_v, pos):
    """Per-slot single-position attention: like `_cached_attn` but `pos`
    is a [B] vector — each row writes its k/v at its own position
    (vmapped `lax.dynamic_update_slice`) and masks its own history."""
    q, k, v = qkv_proj(p, x)                              # [B, 1, H, K]

    def write(buf, new, p_):                              # one slot's row
        return lax.dynamic_update_slice(buf, new, (p_, 0, 0))

    layer_k = jax.vmap(write)(layer_k, k, pos)
    layer_v = jax.vmap(write)(layer_v, v, pos)
    d = q.shape[-1]
    s = jnp.einsum("bqhk,bshk->bqhs", q, layer_k) / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    valid = jnp.arange(layer_k.shape[1])[None, :] <= pos[:, None]  # [B, S]
    s = jnp.where(valid[:, None, None, :], s, mask_value(s.dtype))
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bqhs,bshk->bqhk", w, layer_v)
    return out_proj(p, o), layer_k, layer_v


def init_slot_cache(cfg: TransformerConfig, slots: int) -> dict:
    """Slot KV cache: `init_cache` with a [B] per-slot position vector."""
    dt = jnp.dtype(cfg.dtype)
    shape = (slots, cfg.max_len, cfg.n_heads, cfg.head_dim)
    return {"k": jnp.zeros((cfg.n_layers,) + shape, dt),
            "v": jnp.zeros((cfg.n_layers,) + shape, dt),
            "pos": jnp.zeros((slots,), jnp.int32)}


def slot_decode_step(cfg: TransformerConfig, params: dict, cache: dict,
                     token: jax.Array) -> Tuple[jax.Array, dict]:
    """token: [B] int32, row b at position cache['pos'][b] (a [B] vector)
    -> (logits [B, V], cache with every pos advanced).

    Identical math to `decode_step` per row — a slot decoding alone
    produces the same logits as a batch-1 `generate()` at the same
    position — but rows no longer share a position, which is what lets
    requests at different depths share one dispatch."""
    pos = cache["pos"]
    x = (params["embed"][token][:, None, :]
         + jnp.take(params["pos"], pos, axis=0)[:, None, :])
    ks, vs = [], []
    for i, layer in enumerate(params["layers"]):
        a, nk, nv = _slot_attn(layer["attn"],
                               _layer_norm(layer["ln1"], x),
                               cache["k"][i], cache["v"][i], pos)
        ks.append(nk)
        vs.append(nv)
        x = x + a
        h = _layer_norm(layer["ln2"], x)
        x = x + (_moe(layer["moe"], h, top_k=cfg.moe_top_k)
                 if "moe" in layer else _mlp(layer["mlp"], h))
    x = _layer_norm(params["ln_f"], x)
    logits = jnp.einsum("bsd,dv->bsv", x, lm_head(params))[:, 0]
    new_cache = {"k": jnp.stack(ks), "v": jnp.stack(vs), "pos": pos + 1}
    return logits, new_cache


@functools.lru_cache(maxsize=8)
def _compiled_slot_step(cfg: TransformerConfig):
    """ONE jitted program per config for the whole serving lifetime: the
    slot count is baked into the cache shapes, `pos` is a traced vector,
    and the KV buffers are donated so the pool updates in place.

    Per-slot sampling happens on device: `temperature[b] == 0` rows take
    the argmax, sampled rows draw from `fold_in(PRNGKey(seed[b]),
    count[b])` — deterministic per request regardless of how requests
    interleave across dispatches."""

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def step(params, cache_k, cache_v, pos, token, temperature, seeds,
             counts):
        cache = {"k": cache_k, "v": cache_v, "pos": pos}
        logits, cache = slot_decode_step(cfg, params, cache, token)
        logits = logits.astype(jnp.float32)
        greedy = jnp.argmax(logits, axis=-1)
        keys = jax.vmap(lambda s, c: jax.random.fold_in(
            jax.random.PRNGKey(s), c))(seeds, counts)
        temp = jnp.maximum(temperature, 1e-6)[:, None]
        sampled = jax.vmap(jax.random.categorical)(keys, logits / temp)
        nxt = jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
        return nxt, cache["k"], cache["v"]

    return step


def make_slot_step(cfg: TransformerConfig):
    """Compiled slot-step entry point for `serving.lm.ContinuousLMServer`:
    fn(params, k, v, pos [B], token [B], temperature [B], seeds [B],
    counts [B]) -> (next_token [B], k, v)."""
    return _compiled_slot_step(cfg)


# ---------------------------------------------------------------------------
# Paged slot decode (block-table paged KV for the continuous LM pool)
#
# The dense slot cache above provisions `slots * max_len` KV positions
# whether or not any lane ever fills them — the serving-state memory
# ceiling.  The paged variant replaces it with ONE fixed pool of
# `[pages, page_size, H, K]` pages per layer plus a per-slot page list
# (`[slots, max_pages]` int32 block table) carried through the jitted
# step: a lane's logical position `t` lives at
# `pool[table[slot, t // page_size], t % page_size]`, so device capacity
# is sum-of-actual-lengths, pages are refcount-shared between lanes with
# a common prompt prefix (radix cache, `serving/paged.py`), and a prompt
# can feed up to `chunk` tokens per dispatch (chunked prefill) without a
# shape change.  Page 0 is the reserved NULL page: masked lanes and
# padding columns write there, and unallocated block-table entries point
# there — its contents are garbage by design and every read of it is
# masked.  One jitted program per (config, pages, page_size, chunk).


def pages_per_seq(cfg: TransformerConfig, page_size: int) -> int:
    """Block-table width: logical pages needed for one max_len lane."""
    return -(-int(cfg.max_len) // int(page_size))


def init_paged_cache(cfg: TransformerConfig, pages: int,
                     page_size: int) -> dict:
    """Paged KV pool: `pages` pages of `page_size` positions per layer
    (page 0 reserved as the null page)."""
    dt = jnp.dtype(cfg.dtype)
    shape = (int(pages), int(page_size), cfg.n_heads, cfg.head_dim)
    return {"k": jnp.zeros((cfg.n_layers,) + shape, dt),
            "v": jnp.zeros((cfg.n_layers,) + shape, dt)}


def _paged_attn(p, x, layer_k, layer_v, table, pos, n_feed,
                paged_kernel: bool = False):
    """Block-table paged attention for one layer.

    x: [B, C, d] (C = prefill chunk width; decode dispatches use C=1);
    layer_k/v: [P, ps, H, K] page pool; table: [B, MP] int32 page ids;
    pos: [B] start positions; n_feed: [B] real columns this dispatch.

    Each lane scatters its fed tokens' k/v into its OWN pages (padding
    columns and inactive lanes write the null page 0), then attends
    over its logical history.  Two history paths share that scatter:

    - ``paged_kernel=False`` — the gather ORACLE: materialize the full
      ``[B, MP*ps, H, K]`` history through the block table and run
      exactly the dense `_slot_attn` math over it; masked positions
      contribute exact zeros, so outputs are byte-identical to the
      dense pool.  Kept as the parity reference (and guarded against
      re-growth by dl4jlint PGD301 — this is the baselined occurrence).
    - ``paged_kernel=True`` — `paged_flash_attention` walks the block
      table INSIDE the kernel: no contiguous history buffer, K/V
      streamed page-by-page, beyond-``pos`` pages skipped, so HBM
      traffic scales with live pages instead of ``MP*ps``.  Identical
      math at every fed column (padding columns are never consumed).
    """
    q, k, v = qkv_proj(p, x)                              # [B, C, H, K]
    b, c, h, kd = q.shape
    pages, ps = layer_k.shape[0], layer_k.shape[1]
    mp = table.shape[1]
    j = jnp.arange(c)[None, :]                            # [1, C]
    wpos = pos[:, None] + j                               # [B, C] write pos
    real = j < n_feed[:, None]                            # [B, C]
    lpage = jnp.minimum(wpos // ps, mp - 1)               # logical page
    page = jnp.take_along_axis(table, lpage, axis=1)      # physical page
    page = jnp.where(real, page, 0)                       # padding -> null
    off = jnp.where(real, wpos % ps, 0)
    idx = (page * ps + off).reshape(-1)                   # [B*C] flat rows
    fk = layer_k.reshape(pages * ps, h, kd).at[idx].set(
        k.reshape(b * c, h, kd))
    fv = layer_v.reshape(pages * ps, h, kd).at[idx].set(
        v.reshape(b * c, h, kd))
    fk4 = fk.reshape(pages, ps, h, kd)
    fv4 = fv.reshape(pages, ps, h, kd)
    if paged_kernel:
        o = paged_flash_attention(q, fk4, fv4, table, pos, n_feed)
        return out_proj(p, o), fk4, fv4
    # gather each lane's logical history: [B, S, H, K], S = MP * ps
    gidx = (table[:, :, None] * ps
            + jnp.arange(ps)[None, None, :]).reshape(b, mp * ps)
    hk, hv = fk[gidx], fv[gidx]
    s = jnp.einsum("bqhk,bshk->bqhs", q, hk) / jnp.sqrt(
        jnp.asarray(kd, q.dtype))
    causal = jnp.arange(mp * ps)[None, None, :] <= wpos[:, :, None]
    s = jnp.where(causal[:, :, None, :], s,
                  mask_value(s.dtype))                    # [B, C, H, S]
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bqhs,bshk->bqhk", w, hv)
    return out_proj(p, o), fk4, fv4


def paged_forward(cfg: TransformerConfig, params: dict, cache: dict,
                  table: jax.Array, pos: jax.Array, n_feed: jax.Array,
                  tokens: jax.Array,
                  paged_kernel: bool = False) -> Tuple[jax.Array, dict]:
    """tokens: [B, C] int32, lane b feeding its first n_feed[b] columns
    at positions pos[b].. -> (logits [B, C, V] at EVERY fed column,
    cache with the fed k/v scattered into the page pool).

    Identical math to `slot_decode_step` per position — the chunk's own
    writes land in the pool before the gather, so intra-chunk causal
    attention rides the same masked-softmax path as the history.  The
    all-column logits are what the speculative verify step consumes
    (`make_spec_step`): column j scores the token that should FOLLOW
    fed token j."""
    c = tokens.shape[1]
    wpos = pos[:, None] + jnp.arange(c)[None, :]
    pidx = jnp.minimum(wpos, cfg.max_len - 1)             # clip padding
    x = params["embed"][tokens] + params["pos"][pidx]     # [B, C, d]
    ks, vs = [], []
    for i, layer in enumerate(params["layers"]):
        a, nk, nv = _paged_attn(layer["attn"],
                                _layer_norm(layer["ln1"], x),
                                cache["k"][i], cache["v"][i],
                                table, pos, n_feed,
                                paged_kernel=paged_kernel)
        ks.append(nk)
        vs.append(nv)
        x = x + a
        hh = _layer_norm(layer["ln2"], x)
        x = x + (_moe(layer["moe"], hh, top_k=cfg.moe_top_k)
                 if "moe" in layer else _mlp(layer["mlp"], hh))
    x = _layer_norm(params["ln_f"], x)
    logits = jnp.einsum("bcd,dv->bcv", x, lm_head(params))
    return logits, {"k": jnp.stack(ks), "v": jnp.stack(vs)}


def paged_decode_step(cfg: TransformerConfig, params: dict, cache: dict,
                      table: jax.Array, pos: jax.Array, n_feed: jax.Array,
                      tokens: jax.Array,
                      paged_kernel: bool = False) -> Tuple[jax.Array, dict]:
    """`paged_forward` with logits taken at each lane's LAST fed column
    (-> [B, V]) — the chunked-prefill/decode entry point."""
    logits, cache = paged_forward(cfg, params, cache, table, pos, n_feed,
                                  tokens, paged_kernel=paged_kernel)
    last = jnp.take_along_axis(
        logits, jnp.maximum(n_feed - 1, 0)[:, None, None], axis=1)[:, 0]
    return last, cache


@functools.lru_cache(maxsize=16)
def _compiled_paged_step(cfg: TransformerConfig, pages: int,
                         page_size: int, chunk: int,
                         paged_kernel: bool = False):
    """One jitted paged program per (config, pages, page_size, chunk):
    the pool shape and block-table width are baked in, the k/v buffers
    are donated, and sampling is the SAME device-side per-slot automaton
    as `_compiled_slot_step` (greedy/temperature, fold_in(seed, count))
    so paged and dense lanes sample byte-identically.  `paged_kernel`
    arrives pre-resolved to a bool (see `resolve_paged_kernel`) so the
    auto-detected default and an explicit matching flag share ONE cache
    entry — the compile ladder keeps its size either way."""

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def step(params, cache_k, cache_v, table, pos, n_feed, tokens,
             temperature, seeds, counts):
        cache = {"k": cache_k, "v": cache_v}
        logits, cache = paged_decode_step(cfg, params, cache, table, pos,
                                          n_feed, tokens,
                                          paged_kernel=paged_kernel)
        logits = logits.astype(jnp.float32)
        greedy = jnp.argmax(logits, axis=-1)
        keys = jax.vmap(lambda s, c: jax.random.fold_in(
            jax.random.PRNGKey(s), c))(seeds, counts)
        temp = jnp.maximum(temperature, 1e-6)[:, None]
        sampled = jax.vmap(jax.random.categorical)(keys, logits / temp)
        nxt = jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
        return nxt, cache["k"], cache["v"]

    return step


def make_paged_step(cfg: TransformerConfig, pages: int, page_size: int,
                    chunk: int, paged_kernel: bool | None = None):
    """Compiled paged-step entry for `serving.lm.ContinuousLMServer`:
    fn(params, k, v, table [B, MP], pos [B], n_feed [B], tokens [B, C],
    temperature [B], seeds [B], counts [B]) -> (next_token [B], k, v).

    `paged_kernel=None` auto-resolves (fused block-table kernel on TPU,
    gather oracle elsewhere; DL4J_TPU_PAGED_KERNEL overrides)."""
    return _compiled_paged_step(cfg, int(pages), int(page_size),
                                int(chunk),
                                resolve_paged_kernel(paged_kernel))


# ---------------------------------------------------------------------------
# Speculative verify (multi-token decode on the chunked-feed path)
#
# `paged_decode_step` already scores a [B, C] token chunk per lane in
# ONE wide dispatch — built for chunked prefill, where every fed token
# is ground truth.  Speculative decoding generalizes the same program
# shape to DECODE: a cheap drafter (serving/draft.py) proposes up to
# `draft_len` tokens per lane, the target model scores
# [last_committed, d_1..d_k] in one wide dispatch, and the accept rule
# runs IN-JIT — the longest draft prefix where the target's greedy
# argmax agrees, plus the target's own next token at the divergence
# point (the "bonus" token).  Greedy output is byte-identical to
# 1-token decode by construction: emitted token i is always
# argmax(target | committed history), whether it arrived as an accepted
# draft or as the bonus.  Rollback is free on the paged pool: rejected
# columns wrote k/v into the lane's OWN future pages (or the null
# page), positions the causal mask already hides — the host just
# advances `pos` by 1 + accepted instead of by n_feed, a pointer move,
# never a copy.  The step returns per-lane accepted counts so the host
# syncs ONCE per round, not per token.


def spec_verify_step(cfg: TransformerConfig, params: dict, cache: dict,
                     table: jax.Array, pos: jax.Array, n_feed: jax.Array,
                     n_draft: jax.Array, tokens: jax.Array,
                     paged_kernel: bool = False
                     ) -> Tuple[jax.Array, jax.Array, dict]:
    """tokens: [B, W] int32; lane b feeds its first n_feed[b] columns.
    Two lane shapes are supported, and the accept mask assumes them:
    a VERIFY lane feeds exactly one committed token followed by its
    drafts — [last_committed, d_1..d_k] with n_feed = k+1 and
    n_draft = k — and a TEACHER-FORCED lane (prefill chunk, plain
    decode, or padding) feeds any n_feed with n_draft = 0.  Shapes
    with more than one committed token ahead of drafts
    (n_feed > n_draft + 1 with n_draft > 0) are NOT supported: the
    draft window is hardwired to columns 1..n_draft.

    -> (bonus_logits [B, V] at each lane's divergence column,
        accepted [B] int32 draft tokens accepted, cache).

    Draft d_i is accepted iff every earlier draft was AND the target's
    greedy argmax after consuming through column i-1 equals d_i; the
    bonus logits are the target's distribution at the column AFTER the
    last accepted token — exactly the logits 1-token decode would have
    produced there, so greedy parity is byte-exact and a sampled lane
    (n_draft = 0) sees precisely its last-fed column."""
    logits, cache = paged_forward(cfg, params, cache, table, pos, n_feed,
                                  tokens, paged_kernel=paged_kernel)
    logits = logits.astype(jnp.float32)                    # [B, W, V]
    pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [B, W]
    w = tokens.shape[1]
    # column j in [1, W): draft position j is live iff j <= n_draft
    live = jnp.arange(1, w)[None, :] <= n_draft[:, None]   # [B, W-1]
    ok = (pred[:, :-1] == tokens[:, 1:]) & live
    # length of the initial all-True run = accepted draft count
    accepted = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
    # divergence column: the last committed feed column (n_feed-1-n_draft)
    # advanced by the accepted run; == n_feed-1 when n_draft == 0
    bonus_col = jnp.clip(n_feed - 1 - n_draft + accepted, 0, w - 1)
    blog = jnp.take_along_axis(
        logits, bonus_col[:, None, None], axis=1)[:, 0]    # [B, V]
    return blog, accepted.astype(jnp.int32), cache


@functools.lru_cache(maxsize=16)
def _compiled_spec_step(cfg: TransformerConfig, pages: int,
                        page_size: int, width: int,
                        paged_kernel: bool = False):
    """One jitted speculative-verify program per (config, pages,
    page_size, width): forward + in-jit accept/rollback + the SAME
    per-slot sampling automaton as `_compiled_paged_step` applied at
    the bonus column, so a sampled lane riding this wide dispatch with
    n_draft = 0 samples byte-identically to the 1-wide program."""

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def step(params, cache_k, cache_v, table, pos, n_feed, n_draft,
             tokens, temperature, seeds, counts):
        cache = {"k": cache_k, "v": cache_v}
        blog, accepted, cache = spec_verify_step(
            cfg, params, cache, table, pos, n_feed, n_draft, tokens,
            paged_kernel=paged_kernel)
        greedy = jnp.argmax(blog, axis=-1)
        keys = jax.vmap(lambda s, c: jax.random.fold_in(
            jax.random.PRNGKey(s), c))(seeds, counts)
        temp = jnp.maximum(temperature, 1e-6)[:, None]
        sampled = jax.vmap(jax.random.categorical)(keys, blog / temp)
        nxt = jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
        return nxt, accepted, cache["k"], cache["v"]

    return step


def make_spec_step(cfg: TransformerConfig, pages: int, page_size: int,
                   width: int, paged_kernel: bool | None = None):
    """Compiled speculative-verify entry for the LM pool:
    fn(params, k, v, table [B, MP], pos [B], n_feed [B], n_draft [B],
    tokens [B, W], temperature [B], seeds [B], counts [B])
    -> (bonus_token [B], accepted [B], k, v).  `paged_kernel=None`
    auto-resolves exactly as in `make_paged_step`."""
    return _compiled_spec_step(cfg, int(pages), int(page_size),
                               int(width),
                               resolve_paged_kernel(paged_kernel))


@functools.lru_cache(maxsize=16)
def _compiled_page_copy(cfg: TransformerConfig, pages: int,
                        page_size: int):
    """Copy-on-write primitive: duplicate ONE page (all layers, k and v)
    inside the donated pool.  Host-side admission calls this once per
    divergence page — a request whose prompt shares a cached prefix that
    ends mid-page copies that page and overwrites from the divergence
    offset, instead of re-prefilling the whole page."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def copy(cache_k, cache_v, src, dst):
        def dup(buf):
            page = lax.dynamic_slice_in_dim(buf, src, 1, axis=1)
            return lax.dynamic_update_slice_in_dim(buf, page, dst, axis=1)

        return dup(cache_k), dup(cache_v)

    return copy


def make_page_copy(cfg: TransformerConfig, pages: int, page_size: int):
    """Compiled page-copy entry: fn(k, v, src, dst) -> (k, v)."""
    return _compiled_page_copy(cfg, int(pages), int(page_size))


@functools.lru_cache(maxsize=16)
def _compiled_page_gather(cfg: TransformerConfig, pages: int,
                          page_size: int):
    """Export half of KV page shipping (serving/transfer.py): gather a
    lane's pages OUT of the pool by block-table row, fixed shape so the
    whole disaggregated serving lifetime runs one compiled program.  The
    pool is NOT donated — the exporting lane keeps serving from it (and
    the radix tree keeps the prefix for local reuse)."""

    @jax.jit
    def gather(cache_k, cache_v, table_row):
        # table_row: [MP] int32 physical page ids; entries past the
        # shipped count point at the null page and the host slices them
        # off before serialization
        return cache_k[:, table_row], cache_v[:, table_row]

    return gather


def make_page_gather(cfg: TransformerConfig, pages: int, page_size: int):
    """Compiled page-gather entry: fn(k, v, table_row [MP]) ->
    (pages_k [L, MP, ps, H, K], pages_v)."""
    return _compiled_page_gather(cfg, int(pages), int(page_size))


@functools.lru_cache(maxsize=16)
def _compiled_page_install(cfg: TransformerConfig, pages: int,
                           page_size: int):
    """Import half of KV page shipping: batched page install on top of
    the `make_page_copy` idea — scatter a shipped [L, MP, ps, H, K] page
    stack INTO the donated pool at the block-table row's physical ids,
    all pages in ONE dispatch.  Rows past `n` land on the reserved null
    page (whose contents are garbage by design), so the program shape
    never depends on how many pages actually shipped."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def install(cache_k, cache_v, pages_k, pages_v, table_row, n):
        mp = table_row.shape[0]
        dst = jnp.where(jnp.arange(mp) < n, table_row, 0)
        return cache_k.at[:, dst].set(pages_k), cache_v.at[:, dst].set(
            pages_v)

    return install


def make_page_install(cfg: TransformerConfig, pages: int, page_size: int):
    """Compiled page-install entry: fn(k, v, pages_k [L, MP, ps, H, K],
    pages_v, table_row [MP], n) -> (k, v)."""
    return _compiled_page_install(cfg, int(pages), int(page_size))


# ---------------------------------------------------------------------------
# Beam search (extension: the reference has no generative inference at all)

@functools.lru_cache(maxsize=16)
def _compiled_beam_run(cfg: TransformerConfig, batch: int, k: int,
                       max_new_tokens: int):
    """One jitted beam-search program per (config, batch, beams, length)."""

    @jax.jit
    def run(params, prompt):
        # Prefill once per INPUT row, then tile the cache to the beams.
        cache, logits = _prefill(cfg, params, prompt)
        last = jax.nn.log_softmax(logits.astype(jnp.float32))  # [B, V]

        def tile(a):  # [L, B, ...] -> [L, B*k, ...] beams contiguous per row
            return jnp.repeat(a, k, axis=1)

        cache = {"k": tile(cache["k"]), "v": tile(cache["v"]),
                 "pos": cache["pos"]}
        v = last.shape[-1]
        # Seed: only beam 0 live per row, so step 1 picks k DISTINCT tokens.
        scores = jnp.where(jnp.arange(k) == 0, 0.0, -1e30)  # [k]
        scores = jnp.tile(scores, (batch, 1))               # [B, k]
        logp = jnp.repeat(last, k, axis=0)                  # [B*k, V]
        toks0 = jnp.zeros((batch * k, max_new_tokens), jnp.int32)

        def step(carry, _):
            cache, scores, logp, toks, t = carry
            total = scores[:, :, None] + logp.reshape(batch, k, v)
            flat = total.reshape(batch, k * v)
            top_scores, top_idx = lax.top_k(flat, k)        # [B, k]
            parent = top_idx // v                           # beam index
            token = (top_idx % v).astype(jnp.int32)
            # Gather parent beams' caches and emitted-token histories.
            row = jnp.arange(batch)[:, None] * k + parent   # [B, k] flat idx
            flat_row = row.reshape(-1)
            cache = {"k": cache["k"][:, flat_row],
                     "v": cache["v"][:, flat_row], "pos": cache["pos"]}
            toks = toks[flat_row].at[:, t].set(token.reshape(-1))
            logits, cache = decode_step(cfg, params, cache,
                                        token.reshape(-1))
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return (cache, top_scores, logp, toks, t + 1), None

        (cache, scores, logp, toks, _), _ = lax.scan(
            step, (cache, scores, logp, toks0, jnp.zeros((), jnp.int32)),
            None, length=max_new_tokens)
        best = jnp.argmax(scores, axis=1)                   # [B]
        toks = toks.reshape(batch, k, max_new_tokens)
        return toks[jnp.arange(batch), best], scores[jnp.arange(batch), best]

    return run


def beam_search(cfg: TransformerConfig, params: dict, prompt,
                max_new_tokens: int, beam_size: int = 4):
    """Deterministic beam-search decoding over the KV-cached decoder.

    prompt [B, P] int -> (tokens [B, P + max_new_tokens] int32,
    summed log-prob scores [B] of the winning beams).  beam_size=1
    degenerates to greedy.  All beams decode exactly max_new_tokens
    tokens (no EOS handling), so every candidate has equal length and a
    GNMT-style length penalty would not change the ranking — none is
    offered.  The whole search — prefill, per-step top-k over
    (beam, token) pairs, parent cache gathers — runs inside ONE jitted
    lax.scan.
    """
    prompt = _validate_prompt(cfg, prompt, max_new_tokens)
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    run = _compiled_beam_run(cfg, prompt.shape[0], int(beam_size),
                             max_new_tokens)
    new, scores = run(params, prompt)
    return jnp.concatenate([prompt, new], axis=1), scores
