"""Synchronous data-parallel training over a device mesh.

Parity target: the reference's "iterative reduce" parameter averaging —
Spark `SparkDl4jMultiLayer.runIteration():182-202` (broadcast params → train
partitions → accumulator-sum → divide), the Akka IterativeReduce router, and
the YARN master (SURVEY §2.3 list item 1). Averaging parameters every
iteration with a common start is mathematically synchronous SGD with gradient
averaging, so the TPU-native form is: ONE jitted SPMD step, batch sharded
over the mesh's `data` axis, `lax.pmean` over ICI for the gradient exchange.
No driver, no broadcast, no accumulator — the collective is compiled into
the step.

Design notes (scaling-book recipe):
- params/updater-state replicated (pure DP); batch sharded on dim 0.
- per-shard RNG: fold in `lax.axis_index` so dropout masks differ per shard.
- the same code runs on 1 chip (mesh of 1) or a v5e-8 — tests run it on the
  8-device virtual CPU mesh (tests/conftest.py).
- the weight-update plane is ZeRO-1 sharded BY DEFAULT (`shard_update=True`;
  Xu et al., "Automatic Cross-Replica Sharding of Weight Update in
  Data-Parallel Training", arXiv:2004.13336): gradients reduce-scatter over
  the data axis, each replica updates its 1/N flat slice of the params and
  optimizer state, and the updated params all-gather back — bitwise equal
  to the replicated update for elementwise updaters, with per-replica
  optimizer memory divided by N (docs/performance.md "The weight-update
  sharding cost model").  `shard_update=False` keeps the replicated
  allreduce path as an A/B escape hatch.
- an async/local-SGD mode (`sync_every > 1`) covers the reference's Hogwild
  router semantics (SURVEY §2.3 item 2): replicas step locally and average
  params every N steps — parameter averaging as an *option*, not the default.
  Per-replica divergence is real state, so in this mode params/updater-state/
  layer-state are carried with a leading replica dimension sharded over the
  data axis (leaf shape [n_devices, ...]); the every-N average is an explicit
  `lax.pmean` over that axis.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
from deeplearning4j_tpu.parallel import partition as part_lib
from deeplearning4j_tpu.parallel.mesh import shard_map_compat


def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
    """Thin alias over the package's single jax-version shim
    (`mesh.shard_map_compat`); kept for its importers (hybrid,
    transformer) and the check_rep-style signature."""
    del check_rep  # replication checking is always off (see the shim)
    return shard_map_compat(f, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs)

from deeplearning4j_tpu.models.multi_layer_network import (
    MultiLayerNetwork,
    _as_batches,
    _maybe_reset,
)
from deeplearning4j_tpu.ops.updaters import (
    apply_updates,
    global_grad_norm,
    make_updater,
)
from deeplearning4j_tpu.parallel import mesh as mesh_lib
from deeplearning4j_tpu.precision import (
    grads_finite,
    init_scaler_state,
    shard_update_finite,
    unscale_grads,
    update_scaler_state,
    where_tree,
)


class DataParallelTrainer:
    """Wraps a MultiLayerNetwork with an SPMD data-parallel train step."""

    def __init__(self, net: MultiLayerNetwork, mesh=None, axis: str = "data",
                 sync_every: int = 1, shard_update: bool = True):
        self.net = net
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        self.axis = axis
        self.sync_every = sync_every
        self.shard_update = bool(shard_update)
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        if net.params is None:
            net.init()
        self._updater = make_updater(net.conf.conf.updater_config())
        # Precision plane: the net's policy rides into the SPMD step.
        # The dynamic loss scaler composes with BOTH synchronous update
        # planes (replicated allreduce and the default ZeRO-1 sharded
        # step — scale/unscale straddle the psum_scatter there); only
        # local-SGD is out, since diverged replicas would need
        # per-replica scaler automatons.
        if net.precision.loss_scale is not None and sync_every != 1:
            raise ValueError(
                "a loss-scaled precision policy (e.g. 'mixed') requires "
                "a synchronous DP path (sync_every == 1); local-SGD "
                "replicas would need per-replica scaler automatons")
        self._built_policy = net.precision
        self._step_fn = self._select_step()
        self._avg_fn = None
        self._chunk_step_fn = {}  # has_mask -> fused K-step program
        self._rep = None  # stacked (params, state, upd_state), local mode
        self._iteration = 0

    # ---- the SPMD step ----------------------------------------------------

    def _select_step(self):
        """ONE builder choice: local-SGD when sync_every > 1 (the
        sharded plane then lives in the periodic sync round — see
        `_averaged_rep`), else the ZeRO-1 sharded update (the default)
        or the replicated allreduce step (the `shard_update=False` A/B
        escape hatch)."""
        if self.sync_every != 1:
            return self._build_local_step()
        if self.shard_update:
            return self._build_sharded_update_step()
        return self._build_step()

    def _check_policy(self) -> None:
        """Rebuild the compiled SPMD steps when the net's precision
        policy changed since construction (`net.set_precision` /
        `fit(precision=...)`): the steps bake the compute dtype and the
        scaler mode in.  Same restrictions as the constructor."""
        if self.net.precision == self._built_policy:
            return
        if self.net.precision.loss_scale is not None and \
                self.sync_every != 1:
            raise ValueError(
                "a loss-scaled precision policy (e.g. 'mixed') requires "
                "a synchronous DP path (sync_every == 1); local-SGD "
                "replicas would need per-replica scaler automatons")
        self._built_policy = self.net.precision
        self._chunk_step_fn = {}
        # Trainer-held training state was built under the OLD policy and
        # must not leak through the change:
        if self._rep is not None:
            # local-SGD: fold outstanding per-replica drift into the net
            # (in the old dtype — the publish overwrites the cast
            # `set_precision` already applied), then re-apply the new
            # param dtype so the next step restacks cast masters.
            self._average_params()
            self._rep = None
            dtype = jnp.dtype(self.net.precision.param_dtype)
            self.net.params = jax.tree_util.tree_map(
                lambda a: a.astype(dtype)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                else a, self.net.params)
            if self.net.updater_state is not None:
                self.net.updater_state = self._updater.init(self.net.params)
        self._avg_fn = None  # compiled for the old dtype
        if self.shard_update and self.sync_every == 1:
            # Publish the live flat moments to the net's per-layer form
            # FIRST (with the old unravel template), then drop the
            # ravel/unravel cache — it bakes the param dtype in — so the
            # rebuilt step re-adopts the moments under the new policy.
            self.sync_updater_state_to_net()
            if hasattr(self, "_flat_cache"):
                del self._flat_cache
            self._opt_shard = None
        self._step_fn = self._select_step()

    def _build_step(self):
        net = self.net
        updater = self._updater
        axis = self.axis
        scfg = net.precision.loss_scale

        def shard_step(params, state, upd_state, sc_state, x, y, rng, mask,
                       lr_scale):
            # Different dropout/sampling per shard, same init everywhere.
            rng = jax.random.fold_in(rng, lax.axis_index(axis))

            if scfg is None:
                def lossfn(p):
                    return net._objective(p, state, x, y, rng, mask)

                (loss, new_state), grads = jax.value_and_grad(
                    lossfn, has_aux=True)(params)
            else:
                # Mixed precision: the per-shard loss is scaled BEFORE
                # differentiation; the pmean'd gradient is unscaled
                # after the collective, so an overflow on ANY shard is
                # visible to ALL replicas (pmean of inf is inf
                # everywhere) and they skip the update in lockstep —
                # no divergence, no extra collective.
                scale = sc_state["scale"]

                def lossfn(p):
                    loss, new_state = net._objective(p, state, x, y, rng,
                                                     mask)
                    return loss * scale.astype(loss.dtype), (loss, new_state)

                (_, (loss, new_state)), grads = jax.value_and_grad(
                    lossfn, has_aux=True)(params)
            # The collective: gradient allreduce over ICI. This single
            # line replaces Spark broadcast+accumulate, Akka
            # IterativeReduce, and the YARN master (SURVEY §3.2).
            grads = lax.pmean(grads, axis)
            loss = lax.pmean(loss, axis)
            if scfg is not None:
                grads = unscale_grads(grads, sc_state["scale"])
            gnorm = global_grad_norm(grads)
            new_state = jax.tree_util.tree_map(
                lambda s: lax.pmean(s, axis) if jnp.issubdtype(
                    jnp.asarray(s).dtype, jnp.floating) else s,
                new_state)
            updates, new_upd = updater.update(grads, upd_state, params)
            updates = net._apply_lr_multipliers(updates)
            updates = jax.tree_util.tree_map(lambda u: u * lr_scale,
                                             updates)
            new_params = apply_updates(params, updates)
            if scfg is None:
                return new_params, new_state, new_upd, sc_state, loss, gnorm
            finite = jnp.logical_and(grads_finite(grads),
                                     jnp.isfinite(loss))
            params = where_tree(finite, new_params, params)
            upd_state = where_tree(finite, new_upd, upd_state)
            new_state = where_tree(finite, new_state, state)
            sc_state = update_scaler_state(scfg, sc_state, finite)
            return params, new_state, upd_state, sc_state, loss, gnorm

        # ONE partition vocabulary (parallel/partition.py): replicated
        # params/state, batch-sharded data over the replica axis.
        pspec = part_lib.as_jax(part_lib.replicated())
        dspec = part_lib.as_jax(part_lib.sharded(self.axis))

        fn = shard_map(
            shard_step,
            mesh=self.mesh,
            in_specs=(pspec, pspec, pspec, pspec, dspec, dspec, pspec,
                      dspec, pspec),
            out_specs=(pspec, pspec, pspec, pspec, pspec, pspec),
            check_rep=False,
        )
        return jax.jit(fn)

    def _build_chunk_step(self, has_mask: bool, unroll: int = 1):
        """Fused K-steps-per-dispatch SPMD program (plain sync DP only):
        the per-step body of `_build_step` — per-shard weighted objective,
        gradient pmean over ICI, updater — scanned over a stacked [K, B,
        ...] chunk whose batch dim shards over the mesh's data axis.
        Per-step RNG reproduces the per-batch path exactly:
        fold_in(fold_in(PRNGKey(seed), iteration), axis_index).  Returns
        per-step loss / grad-norm vectors so the host syncs once per
        chunk.  unroll semantics as in
        MultiLayerNetwork._make_train_chunk (1 = bit-stable rolled
        scan)."""
        from deeplearning4j_tpu.models.multi_layer_network import (
            _CHUNK_UNROLL_CAP,
        )

        net = self.net
        updater = self._updater
        axis = self.axis
        scfg = net.precision.loss_scale

        def shard_chunk(params, state, upd_state, sc_state, xs, ys, ws,
                        masks, it0, lr_scale):
            base = jax.random.PRNGKey(net.conf.conf.seed)
            idx = lax.axis_index(axis)

            def body(carry, inp):
                if scfg is None:
                    params, state, upd = carry
                else:
                    params, state, upd, sc = carry
                if has_mask:
                    xi, yi, wi, mi, it = inp
                else:
                    (xi, yi, wi, it), mi = inp, None
                rng = jax.random.fold_in(jax.random.fold_in(base, it), idx)

                # Differentiate the UNNORMALIZED local weighted loss sum,
                # then psum numerator/denominator/gradient separately and
                # divide by the GLOBAL weight sum: padded tail rows may
                # land unevenly across shards (a whole shard can be pure
                # padding), and a pmean of per-shard weighted means would
                # weight such shards wrongly.  This form equals the
                # single-device weighted objective exactly.  Under a
                # loss-scaled policy the numerator is scaled before
                # differentiation and the psum'd gradient unscaled after
                # — overflow anywhere is inf everywhere post-psum, so
                # every replica skips the step in lockstep.
                def lossfn(p):
                    num, den, new_state = net._weighted_loss_sums(
                        p, state, xi, yi, rng, mi, wi)
                    num_d = (num if scfg is None
                             else num * sc["scale"].astype(num.dtype))
                    return num_d, (num, den, new_state)

                (_, (num, den, new_state)), grads = jax.value_and_grad(
                    lossfn, has_aux=True)(params)
                denom = jnp.maximum(lax.psum(den, axis), 1.0)
                grads = jax.tree_util.tree_map(
                    lambda g: lax.psum(g, axis) / denom, grads)
                if scfg is not None:
                    grads = unscale_grads(grads, sc["scale"])
                loss = lax.psum(num, axis) / denom
                if net._has_reg():
                    # replicated term: add its gradient once, post-psum
                    reg, reg_grads = jax.value_and_grad(net._reg_loss)(
                        params)
                    loss = loss + reg
                    grads = jax.tree_util.tree_map(
                        lambda g, r: g + r, grads, reg_grads)
                gnorm = global_grad_norm(grads)
                new_state = jax.tree_util.tree_map(
                    lambda s: lax.pmean(s, axis) if jnp.issubdtype(
                        jnp.asarray(s).dtype, jnp.floating) else s,
                    new_state)
                updates, new_upd = updater.update(grads, upd, params)
                updates = net._apply_lr_multipliers(updates)
                updates = jax.tree_util.tree_map(lambda u: u * lr_scale,
                                                 updates)
                new_params = apply_updates(params, updates)
                if scfg is None:
                    return (new_params, new_state, new_upd), (loss, gnorm)
                finite = jnp.logical_and(grads_finite(grads),
                                         jnp.isfinite(loss))
                params = where_tree(finite, new_params, params)
                upd = where_tree(finite, new_upd, upd)
                state = where_tree(finite, new_state, state)
                sc = update_scaler_state(scfg, sc, finite)
                return (params, state, upd, sc), (loss, gnorm)

            its = it0 + jnp.arange(xs.shape[0])
            inputs = ((xs, ys, ws, masks, its) if has_mask
                      else (xs, ys, ws, its))
            carry = ((params, state, upd_state) if scfg is None
                     else (params, state, upd_state, sc_state))
            carry, (losses, gnorms) = lax.scan(
                body, carry, inputs,
                unroll=min(int(xs.shape[0]), unroll, _CHUNK_UNROLL_CAP))
            if scfg is None:
                params, state, upd_state = carry
            else:
                params, state, upd_state, sc_state = carry
            return params, state, upd_state, sc_state, losses, gnorms

        pspec = P()
        cspec = P(None, self.axis)  # [K, B, ...]: shard the batch dim
        out_specs = (pspec, pspec, pspec, pspec, pspec, pspec)
        if has_mask:
            fn = jax.jit(shard_map(
                shard_chunk, mesh=self.mesh,
                in_specs=(pspec, pspec, pspec, pspec, cspec, cspec, cspec,
                          cspec, pspec, pspec),
                out_specs=out_specs, check_rep=False))
            return fn

        def no_mask(params, state, upd, sc, xs, ys, ws, it0, lr_scale):
            return shard_chunk(params, state, upd, sc, xs, ys, ws, None,
                               it0, lr_scale)

        fn = jax.jit(shard_map(
            no_mask, mesh=self.mesh,
            in_specs=(pspec, pspec, pspec, pspec, cspec, cspec, cspec,
                      pspec, pspec),
            out_specs=out_specs, check_rep=False))
        return lambda p, s, u, sc, xs, ys, ws, masks, it0, lr: fn(
            p, s, u, sc, xs, ys, ws, it0, lr)

    def fit_chunk_async(self, xs, ys, masks=None, weights=None,
                        unroll: int = 1):
        """K = xs.shape[0] SPMD optimizer steps in one dispatch (fused
        driver primitive; synchronous DP modes — the default ZeRO-1
        sharded plane threads its shard-local optimizer state through
        the scan carry; only local-SGD is out, its per-replica stacks
        carry state the scan cannot thread).  Returns per-step (losses,
        grad_norms) device vectors."""
        if self.sync_every != 1:
            raise NotImplementedError(
                "fit_chunk_async supports synchronous DP paths "
                "(sync_every == 1); use per-batch fit_batch_async for "
                "local-SGD")
        net = self.net
        self._check_policy()
        sh = jax.sharding.NamedSharding(self.mesh, P(None, self.axis))
        put = lambda a: None if a is None else jax.device_put(a, sh)  # noqa: E731
        xs = put(xs)
        ys = put(ys)
        masks = put(masks)
        k = int(xs.shape[0])
        if int(xs.shape[1]) % self.n_devices:
            raise ValueError(
                f"Global batch {int(xs.shape[1])} not divisible by "
                f"{self.n_devices} devices")
        weights = (jnp.ones(xs.shape[:2], jnp.float32) if weights is None
                   else jnp.asarray(weights, jnp.float32))
        weights = put(weights)
        key = (masks is not None, max(1, int(unroll)))
        step = self._chunk_step_fn.get(key)
        if step is None:
            build = (self._build_sharded_chunk_step if self.shard_update
                     else self._build_chunk_step)
            step = self._chunk_step_fn[key] = build(key[0], key[1])
        it0 = self._iteration
        scfg = net.precision.loss_scale
        if scfg is not None and net._scaler_state is None:
            net._scaler_state = init_scaler_state(scfg)
        sc_state = net._scaler_state if scfg is not None else {}
        if self.shard_update:
            (net.params, net.state, self._opt_shard, sc_state, losses,
             gnorms) = step(
                net.params, net.state, self._opt_shard, sc_state, xs, ys,
                weights, masks, jnp.asarray(it0, jnp.int32),
                jnp.asarray(net._lr_scale, jnp.float32))
            # trainer-owned sharded moments (see fit_batch_async)
            net.updater_state = None
            net._updater_state_owner = self
        else:
            (net.params, net.state, net.updater_state, sc_state, losses,
             gnorms) = step(
                net.params, net.state, net.updater_state, sc_state, xs, ys,
                weights, masks, jnp.asarray(it0, jnp.int32),
                jnp.asarray(net._lr_scale, jnp.float32))
        if scfg is not None:
            net._scaler_state = sc_state
        self._iteration += k
        net.last_grad_norm = gnorms[-1]
        net._fire_chunk_listeners(it0, k, losses)
        return losses, gnorms

    def stage_chunk(self, chunk):
        """Fused-driver prefetch hook: stage a HostChunk with the batch
        dim sharded over the mesh's data axis (one sharded host->device
        transfer on the producer thread instead of an asarray + reshard
        on the training thread)."""
        sh = jax.sharding.NamedSharding(self.mesh, P(None, self.axis))
        put = lambda a: None if a is None else jax.device_put(a, sh)  # noqa: E731
        return chunk._replace(xs=put(chunk.xs), ys=put(chunk.ys),
                              weights=put(chunk.weights),
                              masks=put(chunk.masks))

    def _sharded_updater(self):
        """The updater CORE for the flat 1/N shard: the pre-apply
        transforms (l1/l2/clip_value/clip_norm/unit_norm) are stripped
        from the config and re-applied manually by `_shard_pre_apply` —
        norm-based transforms need cross-replica reductions the flat
        shard cannot see, and letting `pre_apply` run on a shard would
        silently compute shard-local norms.  Decoupled weight_decay
        (adamw/lion) stays: it is elementwise in (u, p)."""
        import dataclasses

        ucfg = self.net.conf.conf.updater_config()
        core = dataclasses.replace(
            ucfg, l1=0.0, l2=0.0, clip_value=None, clip_norm=None,
            unit_norm=False)
        return make_updater(core)

    def _shard_pre_apply(self, ksh: int):
        """Shard-local mirror of `ops.updaters.pre_apply` over the flat
        1/N gradient slice, in the exact transform order (l2 → l1 →
        clip_value → clip_norm → unit_norm).  Elementwise transforms are
        bitwise-identical to the replicated path; the norm-based ones
        psum shard-partial sums of squares to the GLOBAL norms (equal up
        to summation grouping).  unit_norm's per-leaf norms come from a
        host-built leaf-id vector + segment_sum, so one segmented
        reduction serves every leaf the shard straddles.  Returns None
        when no transform is configured (skip the whole stage)."""
        ucfg = self.net.conf.conf.updater_config()
        axis = self.axis
        if not (ucfg.l1 or ucfg.l2 or ucfg.clip_value is not None
                or ucfg.clip_norm is not None or ucfg.unit_norm):
            return None
        leaf_ids = None
        n_leaves = 0
        if ucfg.unit_norm:
            leaves = jax.tree_util.tree_leaves(self.net.params)
            n_leaves = len(leaves)
            ids = np.concatenate([
                np.full(int(np.size(l)), i, np.int32)
                for i, l in enumerate(leaves)])
            # padding lanes get their own segment id: zero grads, and
            # their bogus norm never multiplies a real element
            leaf_ids = jnp.asarray(np.pad(
                ids, (0, self._flat_k - ids.shape[0]),
                constant_values=n_leaves))

        def pre(g, p, idx):
            if ucfg.l2:
                g = g + ucfg.l2 * p
            if ucfg.l1:
                g = g + ucfg.l1 * jnp.sign(p)
            if ucfg.clip_value is not None:
                g = jnp.clip(g, -ucfg.clip_value, ucfg.clip_value)
            if ucfg.clip_norm is not None:
                gnorm = jnp.sqrt(lax.psum(jnp.sum(jnp.square(g)), axis))
                g = g * jnp.minimum(1.0, ucfg.clip_norm / (gnorm + 1e-12))
            if ucfg.unit_norm:
                my_ids = lax.dynamic_slice_in_dim(leaf_ids, idx * ksh, ksh)
                sq = jax.ops.segment_sum(jnp.square(g), my_ids,
                                         num_segments=n_leaves + 1)
                norms = jnp.sqrt(lax.psum(sq, axis))
                g = g / (norms[my_ids] + 1e-12)
            return g

        return pre

    def _lr_mult_flat(self):
        """Per-layer lr multipliers as ONE flat per-element vector
        aligned with the raveled parameter order (padding lanes get 1.0)
        — the flat shard has no layer structure, but a sliced multiply
        against this vector is elementwise-identical to
        `net._apply_lr_multipliers` on the per-layer trees.  None when
        every multiplier is 1.0 (skip the multiply entirely)."""
        layers = self.net.conf.layers
        if all(lc.lr_multiplier == 1.0 for lc in layers):
            return None
        segs = [np.full(int(sum(np.size(l) for l in
                              jax.tree_util.tree_leaves(sub))),
                        lc.lr_multiplier, np.float32)
                for lc, sub in zip(layers, self.net.params)]
        vec = np.concatenate([s for s in segs if s.size]
                             or [np.zeros(0, np.float32)])
        return jnp.asarray(np.pad(vec, (0, self._flat_k - vec.shape[0]),
                                  constant_values=1.0))

    def _build_sharded_update_step(self):
        """ZeRO-1-style cross-replica weight-update sharding (Xu et al.,
        "Automatic Cross-Replica Sharding of Weight Update in
        Data-Parallel Training", arXiv:2004.13336) — the DEFAULT DP
        plane: gradients are `psum_scatter`'d over the data axis so each
        replica holds only its 1/N slice of the flat gradient, updates
        ITS slice of the parameters and optimizer state (which lives
        sharded between steps — the N-fold optimizer-memory saving),
        then `all_gather`s the updated parameters for the next forward.
        For elementwise updaters (all of ours) the result is
        bit-equivalent to the replicated update — psum_scatter +
        all_gather shares pmean's reduction tree, unlike psum + slice;
        it trades one reduce_scatter + one all_gather for the pmean and
        divides update FLOPs and optimizer HBM by N.

        Precision plane composition: under a loss-scaled policy the
        per-shard loss is scaled BEFORE differentiation and the 1/N
        gradient slice unscaled AFTER the collective (scale/unscale
        straddle the psum_scatter), with the finiteness verdict a
        cross-replica psum (`shard_update_finite`) so overflow skips
        stay in lockstep.  clip_norm/unit_norm psum shard-partial square
        norms to the global norms; per-layer lr_multiplier rides as a
        flat sliced vector."""
        from jax.flatten_util import ravel_pytree

        net = self.net
        updater = self._sharded_updater()
        axis = self.axis
        scfg = net.precision.loss_scale
        # Shard over the DATA axis only (a multi-axis mesh replicates the
        # opt state over its other axes, same as the params).
        n = int(self.mesh.shape[self.axis])
        k0, unravel = self._flat_meta()
        k = self._flat_k = ((k0 + n - 1) // n) * n  # padded flat length
        ksh = k // n
        pre = self._shard_pre_apply(ksh)
        mult = self._lr_mult_flat()

        def shard_step(params, state, upd_shard, sc_state, x, y, rng,
                       mask, lr_scale):
            idx = lax.axis_index(axis)
            rng = jax.random.fold_in(rng, idx)

            if scfg is None:
                def lossfn(p):
                    return net._objective(p, state, x, y, rng, mask)

                (loss, new_state), grads = jax.value_and_grad(
                    lossfn, has_aux=True)(params)
            else:
                scale = sc_state["scale"]

                def lossfn(p):
                    loss, new_state = net._objective(p, state, x, y, rng,
                                                     mask)
                    return loss * scale.astype(loss.dtype), (loss, new_state)

                (_, (loss, new_state)), grads = jax.value_and_grad(
                    lossfn, has_aux=True)(params)
            flat_g = jnp.pad(ravel_pytree(grads)[0], (0, k - k0))
            # mean-gradient SHARD: [k/n] per replica, not the full [k]
            g_shard = lax.psum_scatter(flat_g, axis, tiled=True) / n
            loss = lax.pmean(loss, axis)
            if scfg is not None:
                g_shard = unscale_grads(g_shard, sc_state["scale"])
                finite = shard_update_finite(g_shard, loss, axis)
            # global mean-grad norm from the shards (padding is zero)
            gnorm = jnp.sqrt(lax.psum(
                jnp.sum(jnp.square(g_shard.astype(jnp.float32))), axis))
            flat_p = jnp.pad(ravel_pytree(params)[0], (0, k - k0))
            p_shard = lax.dynamic_slice_in_dim(flat_p, idx * ksh, ksh)
            g2 = g_shard if pre is None else pre(g_shard, p_shard, idx)
            updates, new_upd = updater.update(
                {"p": g2}, upd_shard, {"p": p_shard})
            u = updates["p"]
            if mult is not None:
                u = u * lax.dynamic_slice_in_dim(
                    mult, idx * ksh, ksh).astype(u.dtype)
            u = u * lr_scale
            new_shard = apply_updates({"p": p_shard}, {"p": u})["p"]
            new_state = jax.tree_util.tree_map(
                lambda s: lax.pmean(s, axis) if jnp.issubdtype(
                    jnp.asarray(s).dtype, jnp.floating) else s,
                new_state)
            if scfg is not None:
                # Overflow: keep the OLD shard/moments/layer state and
                # let the automaton back off.  Every replica takes the
                # same branch — the verdict is a cross-replica psum —
                # and selecting on the shard BEFORE the gather means the
                # skipped step gathers back exactly the old params.
                new_shard = jnp.where(finite, new_shard, p_shard)
                new_upd = where_tree(finite, new_upd, upd_shard)
                new_state = where_tree(finite, new_state, state)
                sc_state = update_scaler_state(scfg, sc_state, finite)
            new_flat = lax.all_gather(new_shard, axis, tiled=True)[:k0]
            params = unravel(new_flat)
            return params, new_state, new_upd, sc_state, loss, gnorm

        pspec = part_lib.as_jax(part_lib.replicated())
        dspec = part_lib.as_jax(part_lib.sharded(self.axis))
        # Optimizer-state leaves over the padded flat vector shard over
        # the axis; scalar leaves (step counters) stay replicated.
        self._opt_shard = self._init_sharded_opt_state()
        sspec = jax.tree_util.tree_map(
            lambda a: part_lib.as_jax(self._opt_leaf_partition(a, k)),
            self._opt_shard)
        fn = shard_map(
            shard_step,
            mesh=self.mesh,
            in_specs=(pspec, pspec, sspec, pspec, dspec, dspec, pspec,
                      dspec, pspec),
            out_specs=(pspec, pspec, sspec, pspec, pspec, pspec),
            check_rep=False,
        )
        return jax.jit(fn)

    def _build_sharded_chunk_step(self, has_mask: bool, unroll: int = 1):
        """Fused K-steps-per-dispatch under the ZeRO-1 plane: the
        sharded per-step body of `_build_sharded_update_step` — weighted
        objective, psum_scatter to the 1/N gradient slice, shard-local
        optimizer step, all_gather — scanned over a stacked [K, B, ...]
        chunk.  The shard-local optimizer state (and scaler automaton)
        rides the scan CARRY, so K steps cost one dispatch and the
        moments never leave their shards.  Weighted-objective, RNG and
        unroll semantics exactly as `_build_chunk_step`."""
        from deeplearning4j_tpu.models.multi_layer_network import (
            _CHUNK_UNROLL_CAP,
        )
        from jax.flatten_util import ravel_pytree

        net = self.net
        updater = self._sharded_updater()
        axis = self.axis
        scfg = net.precision.loss_scale
        n = int(self.mesh.shape[self.axis])
        k0, unravel = self._flat_meta()
        k = self._flat_k = ((k0 + n - 1) // n) * n
        ksh = k // n
        pre = self._shard_pre_apply(ksh)
        mult = self._lr_mult_flat()

        def shard_chunk(params, state, upd_shard, sc_state, xs, ys, ws,
                        masks, it0, lr_scale):
            base = jax.random.PRNGKey(net.conf.conf.seed)
            idx = lax.axis_index(axis)

            def body(carry, inp):
                if scfg is None:
                    params, state, upd = carry
                else:
                    params, state, upd, sc = carry
                if has_mask:
                    xi, yi, wi, mi, it = inp
                else:
                    (xi, yi, wi, it), mi = inp, None
                rng = jax.random.fold_in(jax.random.fold_in(base, it), idx)

                # Same weighted-sum form as `_build_chunk_step` (padded
                # tail rows land unevenly across shards), with the psum
                # of the gradient replaced by a psum_scatter to this
                # replica's 1/N slice.
                def lossfn(p):
                    num, den, new_state = net._weighted_loss_sums(
                        p, state, xi, yi, rng, mi, wi)
                    num_d = (num if scfg is None
                             else num * sc["scale"].astype(num.dtype))
                    return num_d, (num, den, new_state)

                (_, (num, den, new_state)), grads = jax.value_and_grad(
                    lossfn, has_aux=True)(params)
                denom = jnp.maximum(lax.psum(den, axis), 1.0)
                flat_g = jnp.pad(ravel_pytree(grads)[0], (0, k - k0))
                g_shard = lax.psum_scatter(flat_g, axis, tiled=True) / denom
                if scfg is not None:
                    g_shard = unscale_grads(g_shard, sc["scale"])
                loss = lax.psum(num, axis) / denom
                if net._has_reg():
                    # replicated term: add THIS shard's slice of its
                    # gradient once, post-scatter
                    reg, reg_grads = jax.value_and_grad(net._reg_loss)(
                        params)
                    loss = loss + reg
                    flat_r = jnp.pad(ravel_pytree(reg_grads)[0],
                                     (0, k - k0))
                    g_shard = g_shard + lax.dynamic_slice_in_dim(
                        flat_r, idx * ksh, ksh)
                if scfg is not None:
                    finite = shard_update_finite(g_shard, loss, axis)
                gnorm = jnp.sqrt(lax.psum(
                    jnp.sum(jnp.square(g_shard.astype(jnp.float32))),
                    axis))
                flat_p = jnp.pad(ravel_pytree(params)[0], (0, k - k0))
                p_shard = lax.dynamic_slice_in_dim(flat_p, idx * ksh, ksh)
                g2 = g_shard if pre is None else pre(g_shard, p_shard, idx)
                updates, new_upd = updater.update(
                    {"p": g2}, upd, {"p": p_shard})
                u = updates["p"]
                if mult is not None:
                    u = u * lax.dynamic_slice_in_dim(
                        mult, idx * ksh, ksh).astype(u.dtype)
                u = u * lr_scale
                new_shard = apply_updates({"p": p_shard}, {"p": u})["p"]
                new_state = jax.tree_util.tree_map(
                    lambda s: lax.pmean(s, axis) if jnp.issubdtype(
                        jnp.asarray(s).dtype, jnp.floating) else s,
                    new_state)
                if scfg is not None:
                    new_shard = jnp.where(finite, new_shard, p_shard)
                    new_upd = where_tree(finite, new_upd, upd)
                    new_state = where_tree(finite, new_state, state)
                    sc = update_scaler_state(scfg, sc, finite)
                new_params = unravel(
                    lax.all_gather(new_shard, axis, tiled=True)[:k0])
                if scfg is None:
                    return (new_params, new_state, new_upd), (loss, gnorm)
                return (new_params, new_state, new_upd, sc), (loss, gnorm)

            its = it0 + jnp.arange(xs.shape[0])
            inputs = ((xs, ys, ws, masks, its) if has_mask
                      else (xs, ys, ws, its))
            carry = ((params, state, upd_shard) if scfg is None
                     else (params, state, upd_shard, sc_state))
            carry, (losses, gnorms) = lax.scan(
                body, carry, inputs,
                unroll=min(int(xs.shape[0]), unroll, _CHUNK_UNROLL_CAP))
            if scfg is None:
                params, state, upd_shard = carry
            else:
                params, state, upd_shard, sc_state = carry
            return params, state, upd_shard, sc_state, losses, gnorms

        pspec = P()
        cspec = P(None, self.axis)  # [K, B, ...]: shard the batch dim
        if getattr(self, "_opt_shard", None) is None:
            self._opt_shard = self._init_sharded_opt_state()
        sspec = jax.tree_util.tree_map(
            lambda a: part_lib.as_jax(self._opt_leaf_partition(a, k)),
            self._opt_shard)
        out_specs = (pspec, pspec, sspec, pspec, pspec, pspec)
        if has_mask:
            fn = jax.jit(shard_map(
                shard_chunk, mesh=self.mesh,
                in_specs=(pspec, pspec, sspec, pspec, cspec, cspec, cspec,
                          cspec, pspec, pspec),
                out_specs=out_specs, check_rep=False))
            return fn

        def no_mask(params, state, upd, sc, xs, ys, ws, it0, lr_scale):
            return shard_chunk(params, state, upd, sc, xs, ys, ws, None,
                               it0, lr_scale)

        fn = jax.jit(shard_map(
            no_mask, mesh=self.mesh,
            in_specs=(pspec, pspec, sspec, pspec, cspec, cspec, cspec,
                      pspec, pspec),
            out_specs=out_specs, check_rep=False))
        return lambda p, s, u, sc, xs, ys, ws, masks, it0, lr: fn(
            p, s, u, sc, xs, ys, ws, it0, lr)

    def _flat_meta(self):
        from jax.flatten_util import ravel_pytree

        if not hasattr(self, "_flat_cache"):
            flat, unravel = ravel_pytree(self.net.params)
            self._flat_cache = (int(flat.shape[0]), unravel)
        return self._flat_cache

    def _opt_leaf_partition(self, leaf, k: int) -> part_lib.PartitionSpec:
        """Partition of one sharded-optimizer-state leaf: the padded
        flat [k] moments shard over the replica axis; scalar leaves
        (step counters) replicate."""
        if np.shape(leaf) == (k,):
            return part_lib.zero1(self.axis, size=k)
        return part_lib.replicated()

    def train_state_partition(self) -> dict:
        """ONE `parallel.partition` description of where this trainer's
        training state lives across the replica axis — the spec the
        elastic checkpoint plane records in each snapshot manifest:

        - plain sync DP: params/updater replicated (every replica holds
          the full tree);
        - shard_update (ZeRO-1): the live optimizer state is flat
          moments sharded dim-0 over the data axis — but what
          CHECKPOINTS see is the published per-layer form
          (device-count independent), so the published spec is
          replicated and the live layout is reported under
          ``live_updater``;
        - local-SGD: the per-replica stack is transient (re-stacked
          from the published average on restore), so the published
          spec is replicated too.
        """
        rep = part_lib.replicated()
        out = {"params": rep, "updater": rep,
               "replicas": self.n_devices, "axis": self.axis}
        if self.shard_update and getattr(self, "_opt_shard", None) is not None:
            k = getattr(self, "_flat_k", None)
            out["live_updater"] = jax.tree_util.tree_map(
                lambda a: self._opt_leaf_partition(a, k), self._opt_shard)
        return out

    def checkpoint_partition(self) -> dict:
        """What the resilience supervisor passes to `save_checkpoint`:
        the partition spec of the published trees plus the shard count
        (one shard file per replica, so save IO scales with the
        fleet)."""
        spec = self.train_state_partition()
        return {"shards": self.n_devices,
                "spec": {"params": spec["params"],
                         "updater": spec["updater"]}}

    def resume(self, directory) -> "int | None":
        """Elastic crash-safe resume: restore the newest GOOD checkpoint
        under `directory` into this trainer — whatever replica count
        saved it.  Checksums are verified; corrupt steps are skipped
        (logged) in favor of the previous good one
        (`runtime.checkpoint.load_checkpoint` semantics); the saved
        full-tree state is adopted through `restore_train_state`, which
        rebuilds this trainer's mode-specific carriers (sharded moments,
        local-SGD stacks) for THIS mesh size — the N→M restore.
        Returns the restored step, or None when the directory holds no
        checkpoint yet (fresh start)."""
        from deeplearning4j_tpu.runtime.checkpoint import (
            resume_train_state,
        )

        return resume_train_state(directory, self)

    @staticmethod
    def _is_p_dict(node):
        return isinstance(node, dict) and set(node) == {"p"}

    def _init_sharded_opt_state(self):
        """Optimizer state over the padded flat parameter vector, laid out
        sharded over the data axis (each device holds 1/N of every flat
        moment).

        If `net.updater_state` holds a per-layer state with trained
        moments (the form `finalize()` publishes and checkpoints save —
        device-count independent), ADOPT it by raveling each moment tree
        into the flat layout, so resume keeps the moments even on a
        different mesh size."""
        from jax.flatten_util import ravel_pytree
        from jax.sharding import NamedSharding

        k0, _ = self._flat_meta()
        n = int(self.mesh.shape[self.axis])
        k = self._flat_k = ((k0 + n - 1) // n) * n
        flat0 = jnp.pad(ravel_pytree(self.net.params)[0], (0, k - k0))
        state = self._updater.init({"p": flat0})
        existing = self.net.updater_state
        if existing is not None and (
                jax.tree_util.tree_structure(existing)
                == jax.tree_util.tree_structure(
                    self._updater.init(self.net.params))):
            # per-layer moments -> padded flat moments, position-matched
            # against the flat template via the single-key {"p": .} dicts
            # init({"p": flat}) wraps every moment tree in.
            def adopt(flat_node, layer_node):
                if self._is_p_dict(flat_node):
                    vec = ravel_pytree(layer_node)[0]
                    return {"p": jnp.pad(vec, (0, k - vec.shape[0]))}
                return jnp.asarray(layer_node)  # scalar leaves (step)

            state = jax.tree_util.tree_map(
                adopt, state, existing, is_leaf=self._is_p_dict)
        sh = NamedSharding(self.mesh, P(self.axis))
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.asarray(a), sh)
            if np.ndim(a) == 1 and np.shape(a) == (k,) else jnp.asarray(a),
            state)

    def sync_updater_state_to_net(self) -> None:
        """Publish the sharded optimizer state back to `net.updater_state`
        in the net's own per-layer form (device-count independent) — what
        checkpoints should save.  Called by `finalize()`; cheap enough to
        call at any checkpoint boundary, too expensive for every step."""
        if not self.shard_update or getattr(self, "_opt_shard", None) is None:
            return
        k0, unravel = self._flat_meta()

        def publish(node):
            if self._is_p_dict(node):
                return unravel(jnp.asarray(node["p"])[:k0])
            return node

        self.net.updater_state = jax.tree_util.tree_map(
            publish, self._opt_shard, is_leaf=self._is_p_dict)

    def _build_local_step(self):
        """Local-SGD step: each replica holds ITS OWN params slice (leading
        replica dim sharded over the data axis) and applies its own gradient
        with no collective; divergence is representable, unlike declaring
        unsynced buffers replicated."""
        net = self.net
        updater = self._updater
        axis = self.axis

        def local_step(rep_params, rep_state, rep_upd, x, y, rng, mask,
                       lr_scale):
            # Each shard sees leaves of shape [1, ...]: this replica's slot.
            params = jax.tree_util.tree_map(lambda a: a[0], rep_params)
            state = jax.tree_util.tree_map(lambda a: a[0], rep_state)
            upd_state = jax.tree_util.tree_map(lambda a: a[0], rep_upd)
            rng = jax.random.fold_in(rng, lax.axis_index(axis))

            def lossfn(p):
                return net._objective(p, state, x, y, rng, mask)

            (loss, new_state), grads = jax.value_and_grad(
                lossfn, has_aux=True)(params)
            # mean of per-replica local grad norms (no global gradient
            # exists between syncs in local-SGD mode)
            gnorm = lax.pmean(global_grad_norm(grads), axis)
            updates, upd_state = updater.update(grads, upd_state, params)
            updates = net._apply_lr_multipliers(updates)
            updates = jax.tree_util.tree_map(lambda u: u * lr_scale,
                                             updates)
            params = apply_updates(params, updates)
            loss = lax.pmean(loss, axis)

            def restack(t):
                return jax.tree_util.tree_map(
                    lambda a: jnp.asarray(a)[None], t)

            return (restack(params), restack(new_state), restack(upd_state),
                    loss, gnorm)

        # per-replica stacked state: leading replica dim over the axis
        rspec = part_lib.as_jax(part_lib.sharded(self.axis, dim=0))
        dspec = part_lib.as_jax(part_lib.sharded(self.axis))
        fn = shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(rspec, rspec, rspec, dspec, dspec, P(), dspec, P()),
            out_specs=(rspec, rspec, rspec, P(), P()),
            check_rep=False,
        )
        return jax.jit(fn)

    def _stack(self, tree):
        """[n_devices, ...] copies of every leaf, sharded over the axis."""
        n = self.n_devices
        sh = mesh_lib.batch_sharded(self.mesh, self.axis)
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(
                np.broadcast_to(np.asarray(a), (n,) + np.shape(a)).copy(), sh),
            tree)

    # ---- public API -------------------------------------------------------

    def fit_batch_async(self, x, y, mask=None):
        """One SPMD step over the global batch (dim 0 must be divisible by
        the mesh's data-axis size); returns the loss as a DEVICE array
        without synchronizing, so back-to-back steps pipeline (mirror of
        MultiLayerNetwork.fit_batch_async).  sync_every==1: synchronous
        gradient allreduce.  sync_every>1: local step per replica, params
        averaged every N steps (net.params reflects the average at sync
        points).  Listeners force a host sync only when registered."""
        net = self.net
        self._check_policy()
        x = np.asarray(x)
        y = np.asarray(y)
        if x.shape[0] % self.n_devices:
            raise ValueError(
                f"Global batch {x.shape[0]} not divisible by "
                f"{self.n_devices} devices")
        rng = jax.random.fold_in(
            jax.random.PRNGKey(net.conf.conf.seed), self._iteration)
        xs = mesh_lib.shard_batch(self.mesh, jnp.asarray(x), self.axis)
        ys = mesh_lib.shard_batch(self.mesh, jnp.asarray(y), self.axis)
        ms = (None if mask is None
              else mesh_lib.shard_batch(self.mesh, jnp.asarray(mask), self.axis))
        scale = jnp.asarray(net._lr_scale, jnp.float32)
        if self.sync_every != 1:
            if self._rep is None:
                self._rep = tuple(self._stack(t) for t in
                                  (net.params, net.state, net.updater_state))
            p, s, u = self._rep
            p, s, u, loss, net.last_grad_norm = self._step_fn(
                p, s, u, xs, ys, rng, ms, scale)
            self._rep = (p, s, u)
        elif self.shard_update:
            scfg = net.precision.loss_scale
            if scfg is not None and net._scaler_state is None:
                net._scaler_state = init_scaler_state(scfg)
            sc_state = net._scaler_state if scfg is not None else {}
            (net.params, net.state, self._opt_shard, sc_state, loss,
             net.last_grad_norm) = self._step_fn(
                net.params, net.state, self._opt_shard, sc_state, xs, ys,
                rng, ms, scale)
            if scfg is not None:
                net._scaler_state = sc_state
            # The TRAINER owns the (sharded) optimizer state while this
            # mode runs: the net's copy is cleared (so direct
            # net.fit_batch restarts with fresh moments instead of a
            # structure-mismatch crash) and the trainer registers itself
            # as the owner, so save_model/checkpoint paths
            # (runtime.checkpoint.published_updater_state) pull the
            # sharded moments ON DEMAND at checkpoint boundaries — no
            # per-step publish cost, no finalize() needed for a
            # mid-run checkpoint to keep trained moments.
            net.updater_state = None
            net._updater_state_owner = self
        else:
            scfg = net.precision.loss_scale
            if scfg is not None and net._scaler_state is None:
                net._scaler_state = init_scaler_state(scfg)
            sc_state = net._scaler_state if scfg is not None else {}
            (net.params, net.state, net.updater_state, sc_state, loss,
             net.last_grad_norm) = self._step_fn(
                net.params, net.state, net.updater_state, sc_state, xs, ys,
                rng, ms, scale)
            if scfg is not None:
                net._scaler_state = sc_state
        self._iteration += 1
        if self.sync_every > 1 and self._iteration % self.sync_every == 0:
            self._average_params()
        due = net._due_listeners(self._iteration)
        if due:
            loss_f = float(loss)
            for listener in due:
                listener(self._iteration, loss_f)
        return loss

    def fit_batch(self, x, y, mask=None) -> float:
        """fit_batch_async + host sync on the loss."""
        return float(self.fit_batch_async(x, y, mask))

    def fit(self, data, epochs: int = 1,
            chunk_size: "int | None" = None,
            prefetch: int = 2, chunk_unroll: int = 1
            ) -> "DataParallelTrainer":
        """`chunk_size` routes the loop through the fused multi-step
        driver (runtime/fused.py): K SPMD steps per dispatch, chunks
        device-staged pre-sharded on a background thread.  Padding keeps
        tail batches at the group batch size, so ragged tails that the
        per-batch path rejects (batch % devices != 0) train fine chunked.
        Synchronous modes only (including the default ZeRO-1 plane);
        local-SGD falls back to the per-batch loop."""
        if chunk_size is not None and self.sync_every == 1:
            from deeplearning4j_tpu.runtime.fused import FusedTrainingDriver

            FusedTrainingDriver(self, chunk_size=chunk_size,
                                prefetch=prefetch,
                                unroll=chunk_unroll).fit(data, epochs=epochs)
            self.finalize()
            return self
        for _ in range(epochs):
            for x, y, mask in _as_batches(data):
                self.fit_batch(x, y, mask)
            _maybe_reset(data)
        self.finalize()  # publish trainer-held state back to the net
        return self

    def _averaged_rep(self):
        """Average over the replica axis of the stacked per-replica
        state (float updater/layer state averaged too); pure — does not
        touch self._rep.  Under the default shard_update the parameter
        average IS the sharded master step of the local-SGD sync round:
        each replica reduces and re-emits only its 1/N flat slice
        (psum_scatter + all_gather — bitwise equal to the pmean it
        replaces, same reduction tree), so the sync round's bandwidth
        and FLOPs shard even though the between-sync moments stay local
        and replicated."""
        if self._avg_fn is None:
            axis = self.axis

            def avg_tree(t):
                return jax.tree_util.tree_map(
                    lambda a: lax.pmean(a, axis) if jnp.issubdtype(
                        a.dtype, jnp.floating) else a, t)

            if self.shard_update:
                from jax.flatten_util import ravel_pytree

                n = int(self.mesh.shape[self.axis])
                k0, unravel = self._flat_meta()
                k = ((k0 + n - 1) // n) * n

                def avg(p, s, u):
                    local = jax.tree_util.tree_map(lambda a: a[0], p)
                    flat = jnp.pad(ravel_pytree(local)[0], (0, k - k0))
                    shard = lax.psum_scatter(flat, axis, tiled=True) / n
                    avg_p = unravel(
                        lax.all_gather(shard, axis, tiled=True)[:k0])
                    avg_p = jax.tree_util.tree_map(
                        lambda a: a[None], avg_p)
                    return avg_p, avg_tree(s), avg_tree(u)

                self._avg_fn = jax.jit(shard_map(
                    avg, mesh=self.mesh, in_specs=(P(self.axis),) * 3,
                    out_specs=(P(self.axis),) * 3, check_rep=False))
            else:
                self._avg_fn = jax.jit(shard_map(
                    lambda p, s, u: (avg_tree(p), avg_tree(s), avg_tree(u)),
                    mesh=self.mesh, in_specs=(P(self.axis),) * 3,
                    out_specs=(P(self.axis),) * 3, check_rep=False))
        return self._avg_fn(*self._rep)

    def _publish_rep(self, rep) -> None:
        """Write one replica-averaged copy to the net (replica 0's slot —
        all equal after _averaged_rep)."""
        p, s, u = rep
        unstack = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)  # noqa: E731
        self.net.params = unstack(p)
        self.net.state = unstack(s)
        self.net.updater_state = unstack(u)

    def _average_params(self) -> None:
        """Every-N parameter averaging for the local-SGD/Hogwild-parity mode
        (the reference's HogWildWorkRouter semantics): the replicas restart
        the next round identical."""
        if self._rep is None:
            return
        self._rep = self._averaged_rep()
        self._publish_rep(self._rep)

    def publish_train_state(self) -> None:
        """Publish a CHECKPOINTABLE snapshot to net.params/state/
        updater_state without perturbing training: local-SGD mode writes
        the replica average to the net but leaves the per-replica `_rep`
        untouched (no extra sync point is injected into the schedule);
        shard_update publishes the sharded moments.  The resilience
        supervisor calls this before every checkpoint so mid-sync-window
        checkpoints carry current (not last-sync) parameters."""
        if self.sync_every > 1 and self._rep is not None:
            self._publish_rep(self._averaged_rep())
        self.sync_updater_state_to_net()

    def set_lr_scale(self, scale: float) -> None:
        """Rollback-backoff hook (see MultiLayerNetwork.set_lr_scale);
        the trainer reads the net's scale each step, so both paths stay
        in sync."""
        self.net.set_lr_scale(scale)

    def restore_train_state(self, step: int, params, updater_state=None,
                            net_state=None) -> None:
        """Adopt checkpointed training state into BOTH the net and the
        trainer's mode-specific carriers — the supervisor's
        rollback/resume entry point.

        - plain sync DP: the net's replicated state IS the training state;
        - local-SGD (sync_every > 1): the stacked per-replica copy is
          dropped and re-stacked from the restored net state at the next
          step (per-replica drift since the checkpoint is not a thing
          worth preserving across a rollback);
        - shard_update: the sharded optimizer state is REPARTITIONED
          from the restored per-layer moments (device-count independent
          — the N→M elastic restore), never installed replicated over a
          sharded step.  `net.updater_state` stays populated (callers
          may hand the net elsewhere after a rollback); the first
          trainer step re-takes ownership."""
        net = self.net
        net.restore_train_state(step, params, updater_state, net_state)
        self._iteration = int(step)
        self._rep = None
        if self.shard_update and self.sync_every == 1:
            self._opt_shard = self._init_sharded_opt_state()

    def finalize(self) -> None:
        """Publish trainer-held state back to the net: averages any
        outstanding per-replica drift (local-SGD mode) and converts the
        sharded optimizer state to the net's per-layer form
        (shard_update mode).  Call before checkpointing or handing the
        net to other training paths; no-op for the plain sync path."""
        if self.sync_every > 1 and self._rep is not None:
            self._average_params()
        self.sync_updater_state_to_net()
        if getattr(self.net, "_updater_state_owner", None) is self:
            self.net._updater_state_owner = None

    def train_state_bytes(self, x=None, mask=None) -> int:
        """PER-REPLICA training-state residency under this trainer's
        update plane: the default ZeRO-1 plane divides the flat
        optimizer/parameter/gradient extents by the data-axis size
        (docs/performance.md "The weight-update sharding cost model");
        the replicated escape hatch and local-SGD report the full
        footprint."""
        from deeplearning4j_tpu.precision.policy import train_state_bytes

        shards = (self.n_devices
                  if self.shard_update and self.sync_every == 1 else 1)
        return train_state_bytes(self.net, x, mask, shards=shards)

    def scaling_report(self) -> dict:
        if self.sync_every != 1:
            collective = f"param-average every {self.sync_every}"
            if self.shard_update:
                collective += " (sharded sync round)"
        elif self.shard_update:
            collective = "psum_scatter+all_gather (zero-1 weight update)"
        else:
            collective = "pmean"
        return {
            "devices": self.n_devices,
            "mesh": dict(zip(self.mesh.axis_names, self.mesh.devices.shape)),
            "collective": collective,
        }
