"""Synchronous data-parallel training over a device mesh.

Parity target: the reference's "iterative reduce" parameter averaging —
Spark `SparkDl4jMultiLayer.runIteration():182-202` (broadcast params → train
partitions → accumulator-sum → divide), the Akka IterativeReduce router, and
the YARN master (SURVEY §2.3 list item 1). Averaging parameters every
iteration with a common start is mathematically synchronous SGD with gradient
averaging, so the TPU-native form is: ONE jitted SPMD step, batch sharded
over the mesh's `data` axis, `lax.pmean` over ICI for the gradient exchange.
No driver, no broadcast, no accumulator — the collective is compiled into
the step.

Design notes (scaling-book recipe):
- params/updater-state replicated (pure DP); batch sharded on dim 0.
- per-shard RNG: fold in `lax.axis_index` so dropout masks differ per shard.
- the same code runs on 1 chip (mesh of 1) or a v5e-8 — tests run it on the
  8-device virtual CPU mesh (tests/conftest.py).
- an async/local-SGD mode (`sync_every > 1`) covers the reference's Hogwild
  router semantics (SURVEY §2.3 item 2): replicas step locally and average
  params every N steps — parameter averaging as an *option*, not the default.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        # jax>=0.8 renamed check_rep -> check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_rep)
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from deeplearning4j_tpu.models.multi_layer_network import (
    MultiLayerNetwork,
    _as_batches,
    _maybe_reset,
)
from deeplearning4j_tpu.ops.updaters import apply_updates, make_updater
from deeplearning4j_tpu.parallel import mesh as mesh_lib


class DataParallelTrainer:
    """Wraps a MultiLayerNetwork with an SPMD data-parallel train step."""

    def __init__(self, net: MultiLayerNetwork, mesh=None, axis: str = "data",
                 sync_every: int = 1):
        self.net = net
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        self.axis = axis
        self.sync_every = sync_every
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        if net.params is None:
            net.init()
        self._updater = make_updater(net.conf.conf.updater_config())
        self._step_fn = self._build_step()
        self._iteration = 0

    # ---- the SPMD step ----------------------------------------------------

    def _build_step(self):
        net = self.net
        updater = self._updater
        axis = self.axis
        do_sync = self.sync_every == 1

        def shard_step(params, state, upd_state, x, y, rng, mask):
            # Different dropout/sampling per shard, same init everywhere.
            rng = jax.random.fold_in(rng, lax.axis_index(axis))

            def lossfn(p):
                return net._objective(p, state, x, y, rng, mask)

            (loss, new_state), grads = jax.value_and_grad(
                lossfn, has_aux=True)(params)
            if do_sync:
                # The collective: gradient allreduce over ICI. This single
                # line replaces Spark broadcast+accumulate, Akka
                # IterativeReduce, and the YARN master (SURVEY §3.2).
                grads = lax.pmean(grads, axis)
            loss = lax.pmean(loss, axis)
            new_state = jax.tree_util.tree_map(
                lambda s: lax.pmean(s, axis) if jnp.issubdtype(
                    jnp.asarray(s).dtype, jnp.floating) else s,
                new_state)
            updates, upd_state = updater.update(grads, upd_state, params)
            params = apply_updates(params, updates)
            return params, new_state, upd_state, loss

        pspec = P()          # replicated params/state
        dspec = P(self.axis)  # batch-sharded data

        fn = shard_map(
            shard_step,
            mesh=self.mesh,
            in_specs=(pspec, pspec, pspec, dspec, dspec, pspec, dspec),
            out_specs=(pspec, pspec, pspec, pspec),
            check_rep=False,
        )
        return jax.jit(fn)

    # ---- public API -------------------------------------------------------

    def fit_batch(self, x, y, mask=None) -> float:
        """One synchronous SPMD step over the global batch (dim 0 must be
        divisible by the mesh's data-axis size)."""
        net = self.net
        x = np.asarray(x)
        y = np.asarray(y)
        if x.shape[0] % self.n_devices:
            raise ValueError(
                f"Global batch {x.shape[0]} not divisible by "
                f"{self.n_devices} devices")
        rng = jax.random.fold_in(
            jax.random.PRNGKey(net.conf.conf.seed), self._iteration)
        xs = mesh_lib.shard_batch(self.mesh, jnp.asarray(x), self.axis)
        ys = mesh_lib.shard_batch(self.mesh, jnp.asarray(y), self.axis)
        ms = (None if mask is None
              else mesh_lib.shard_batch(self.mesh, jnp.asarray(mask), self.axis))
        net.params, net.state, net.updater_state, loss = self._step_fn(
            net.params, net.state, net.updater_state, xs, ys, rng, ms)
        self._iteration += 1
        if self.sync_every > 1 and self._iteration % self.sync_every == 0:
            self._average_params()
        loss_f = float(loss)
        for listener in net._listeners:
            listener(self._iteration, loss_f)
        return loss_f

    def fit(self, data, epochs: int = 1) -> "DataParallelTrainer":
        for _ in range(epochs):
            for x, y, mask in _as_batches(data):
                self.fit_batch(x, y, mask)
            _maybe_reset(data)
        return self

    def _average_params(self) -> None:
        """Explicit parameter averaging for the local-SGD/Hogwild-parity mode
        (the reference's every-N averaging, kept for A/B comparisons)."""
        # With sync_every>1 grads are applied locally; params have drifted
        # per-replica inside the (replicated-spec but unsynced) buffers only
        # if check_rep allowed it. For safety re-average through pmean.
        mesh = self.mesh
        axis = self.axis

        avg = jax.jit(shard_map(
            lambda p: jax.tree_util.tree_map(lambda a: lax.pmean(a, axis), p),
            mesh=mesh, in_specs=P(), out_specs=P(), check_rep=False))
        self.net.params = avg(self.net.params)

    def scaling_report(self) -> dict:
        return {
            "devices": self.n_devices,
            "mesh": dict(zip(self.mesh.axis_names, self.mesh.devices.shape)),
            "collective": "pmean" if self.sync_every == 1 else
                          f"param-average every {self.sync_every}",
        }
