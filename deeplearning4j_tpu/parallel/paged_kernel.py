"""Pallas paged-attention decode kernel: the block-table walk fused
into flash attention (ROADMAP item 6, kernel plane round 2).

The gather oracle in ``generation._paged_attn`` pays a full-history
bandwidth tax per layer per dispatch: it materializes every lane's
logical history as a contiguous ``[B, MP*ps, H, K]`` buffer
(``hk, hv = fk[gidx]``) before running dense masked softmax — ``MP*ps``
rows of HBM traffic per lane whether the lane holds 3 live pages or 30.
``paged_flash_attention`` removes the buffer entirely: the kernel takes
the page pool ``[P, ps, H, K]``, the per-lane block table ``[B, MP]``,
``pos`` and ``n_feed`` directly, prefetches the page ids as scalars
(``pltpu.PrefetchScalarGridSpec``) so the BlockSpec index maps can
resolve *physical* page addresses before each grid step's DMA, and
streams K/V one page at a time through a FlashAttention-style online
softmax accumulator (PAPERS.md 2205.14135; fused-epilogue discipline
per 1808.05567).  Pages past a lane's frontier — beyond-``pos`` pages,
which is where every null/unallocated block-table entry lives — are
skipped: their grid steps clamp the index map onto the lane's last live
page (no new DMA) and ``pl.when`` guards out the compute, so both
bandwidth and FLOPs scale with *live* pages, not ``MP*ps``.

Chunked feeds (C > 1: chunked prefill and the speculative verify
dispatch) ride the same kernel: query column ``c`` sits at write
position ``pos + c`` and the in-kernel mask admits keys at
``t <= pos + c`` — bitwise the same causal semantics as the oracle's
masked softmax, including intra-chunk attention (the chunk's own k/v
were scattered into the pool before the kernel runs).

Like ``kernels.flash_attention``, ``interpret=None`` auto-detects:
compiled on TPU, Pallas interpret mode elsewhere — so the tier-1 parity
sweep (tests/test_kernels.py, ``paged_kernel`` marker) exercises the
real kernel everywhere the suite runs.  Whether the *serving* paths use
the kernel at all is the separate ``paged_kernel_enabled()`` policy
below, mirroring ``flash_enabled()``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.parallel.kernels import (
    REP,
    _CompilerParams,
    _resolve_interpret,
    mask_value,
)


def paged_kernel_enabled() -> bool:
    """Policy for the paged decode/prefill/verify dispatches: the fused
    block-table kernel on TPU by default, the gather oracle elsewhere;
    opt in/out anywhere with DL4J_TPU_PAGED_KERNEL=1/0.  (Parity tests
    opt IN on CPU — the kernel then runs in interpret mode.)"""
    import os

    flag = os.environ.get("DL4J_TPU_PAGED_KERNEL")
    if flag is not None:
        return flag.lower() in ("1", "true", "yes")
    return jax.default_backend() == "tpu"


def resolve_paged_kernel(paged_kernel) -> bool:
    """Normalize the ``paged_kernel=`` switch BEFORE it reaches any
    compile-ladder cache key: ``None`` resolves through the policy
    above, anything else coerces to bool — so auto-detect and an
    explicit matching flag hit the SAME cached program."""
    if paged_kernel is None:
        return paged_kernel_enabled()
    return bool(paged_kernel)


def _paged_attn_kernel(table_ref, pos_ref, nf_ref, q_ref, k_ref, v_ref,
                       o_ref, m_acc, l_acc, acc, *, scale, ps, c, mp,
                       neg):
    """Grid program: one (lane, head, logical_page) triple, the page
    dimension sequential (online-softmax accumulation in VMEM scratch).

    table_ref/pos_ref/nf_ref are the scalar-prefetch operands — already
    resident when the body runs, and consumed by the K/V index maps to
    turn logical page ``lp`` into a physical pool address.  q_ref
    ``[1, C, 1, K]`` is revisited across the page steps; k_ref/v_ref
    ``[1, ps, 1, K]`` is THIS lane's page ``lp`` (or a clamped repeat of
    its last live page on dead steps — same block index, so the
    pipeline issues no new DMA).  Row stats live lane-replicated
    ``[C, REP]`` (see kernels.REP) so every scratch block stays
    sublane-tileable.
    """
    b, lp = pl.program_id(0), pl.program_id(2)

    @pl.when(lp == 0)
    def _init():
        m_acc[...] = jnp.full_like(m_acc, neg)
        l_acc[...] = jnp.zeros_like(l_acc)
        acc[...] = jnp.zeros_like(acc)

    # The lane's frontier: its last written position this dispatch.
    # Pages strictly past it are fully masked — skip them (this is also
    # where every null block-table entry of a live lane lives).
    wmax = pos_ref[b] + jnp.maximum(nf_ref[b], 1) - 1

    @pl.when(lp * ps <= wmax)
    def _page():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * scale   # [C, K]
        k_blk = k_ref[0, :, 0, :].astype(jnp.float32)       # [ps, K]
        v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [C, ps]
        # key t = lp*ps + col is visible to query column c iff
        # t <= pos + c — the oracle's causal mask, intra-chunk included
        t = lp * ps + jax.lax.broadcasted_iota(jnp.int32, (c, ps), 1)
        wpos = pos_ref[b] + jax.lax.broadcasted_iota(
            jnp.int32, (c, ps), 0)
        live = t <= wpos
        s = jnp.where(live, s, neg)
        m = m_acc[:, :1]                                    # [C, 1]
        blk_m = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(m, blk_m)
        p = jnp.where(live, jnp.exp(s - new_m), 0.0)
        scale_old = jnp.exp(m - new_m)
        new_l = l_acc[:, :1] * scale_old + jnp.sum(
            p, axis=1, keepdims=True)
        acc[...] = acc[...] * scale_old + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [C, K]
        m_acc[...] = jnp.broadcast_to(new_m, (c, REP))
        l_acc[...] = jnp.broadcast_to(new_l, (c, REP))

    @pl.when(lp == mp - 1)
    def _flush():
        l = l_acc[:, :1]
        o_ref[0, :, 0, :] = (acc[...] / jnp.maximum(l, 1e-30)).astype(
            o_ref.dtype)


def paged_flash_attention(q, k_pages, v_pages, table, pos, n_feed=None,
                          interpret: bool | None = None) -> jax.Array:
    """Fused block-table paged attention.

    q: [B, C, H, K] queries (C = feed width; decode dispatches use 1);
    k_pages/v_pages: [P, ps, H, K] page pool AFTER this dispatch's
    scatter (the chunk's own k/v are already in their pages);
    table: [B, MP] int32 physical page ids per logical page;
    pos: [B] int32 start positions; n_feed: [B] int32 real columns
    (None = every column fed).  Returns [B, C, H, K] in q.dtype.

    Matches the gather oracle exactly at every column ``< n_feed``;
    padding columns (never consumed — `paged_decode_step` indexes
    column ``n_feed - 1``, the verify step at most that) attend only
    through the lane's frontier page rather than the oracle's full
    ``pos + c`` horizon.
    """
    b, c, h, kd = q.shape
    ps = k_pages.shape[1]
    mp = table.shape[1]
    table = jnp.asarray(table, jnp.int32)
    pos = jnp.asarray(pos, jnp.int32)
    n_feed = (jnp.full((b,), c, jnp.int32) if n_feed is None
              else jnp.asarray(n_feed, jnp.int32))
    scale = 1.0 / (kd ** 0.5)
    neg = float(jnp.finfo(jnp.float32).min / 2)

    def _page_map(bi, hi, lp, tbl, pos_, nf):
        # Clamp dead grid steps onto the lane's last live logical page:
        # the repeated block index means the pipeline re-uses the
        # already-resident page instead of DMAing a dead one.
        wmax = pos_[bi] + jnp.maximum(nf[bi], 1) - 1
        live_lp = jnp.minimum(lp, wmax // ps)
        return (tbl[bi, live_lp], 0, hi, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, h, mp),
        in_specs=[
            pl.BlockSpec((1, c, 1, kd),
                         lambda bi, hi, lp, tbl, pos_, nf: (bi, 0, hi, 0)),
            pl.BlockSpec((1, ps, 1, kd), _page_map),
            pl.BlockSpec((1, ps, 1, kd), _page_map),
        ],
        out_specs=pl.BlockSpec(
            (1, c, 1, kd),
            lambda bi, hi, lp, tbl, pos_, nf: (bi, 0, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((c, REP), jnp.float32),    # running max
            pltpu.VMEM((c, REP), jnp.float32),    # running denominator
            pltpu.VMEM((c, kd), jnp.float32),     # output accumulator
        ],
    )
    kernel = functools.partial(_paged_attn_kernel, scale=scale, ps=ps,
                               c=c, mp=mp, neg=neg)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, c, h, kd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_resolve_interpret(interpret),
    )(table, pos, n_feed, q, k_pages, v_pages)


def paged_hbm_bytes(n_layers: int, lanes: int, live_pages: int,
                    max_pages: int, page_size: int, n_heads: int,
                    head_dim: int, itemsize: int,
                    kernel: bool) -> int:
    """Modeled K/V HBM bytes one decode dispatch reads (the cost model
    in docs/performance.md): the gather path touches every block-table
    row — ``MP * ps`` pool rows per lane per layer — while the kernel
    reads only the lane's live pages.  Both read k AND v (the factor
    2); q/output/params traffic is identical across the paths and
    excluded."""
    rows = (live_pages if kernel else max_pages) * page_size
    return 2 * n_layers * lanes * rows * n_heads * head_dim * itemsize
