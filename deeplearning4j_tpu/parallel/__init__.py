from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
from deeplearning4j_tpu.parallel.generation import beam_search, generate
from deeplearning4j_tpu.parallel.mesh import make_mesh

__all__ = ["make_mesh", "DataParallelTrainer", "generate", "beam_search"]
