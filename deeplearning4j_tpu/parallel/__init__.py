from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer

__all__ = ["make_mesh", "DataParallelTrainer"]
