from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
from deeplearning4j_tpu.parallel.generation import beam_search, generate
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.partition import (
    PartitionSpec,
    gather_tree,
    replicated,
    reshard,
    shard_tree,
    sharded,
)

__all__ = ["make_mesh", "DataParallelTrainer", "generate", "beam_search",
           "PartitionSpec", "replicated", "sharded", "reshard",
           "shard_tree", "gather_tree"]
