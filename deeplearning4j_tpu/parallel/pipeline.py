"""Pipeline parallelism: layers sharded over a `stage` mesh axis.

Out-of-reference extension (nothing in the 2015 reference pipelines layers
across devices — SURVEY §2.3 item 3). GPipe-style schedule expressed the
TPU way: stage parameters are STACKED on a leading dim sharded over the
`stage` axis, every device runs the same shard_map program, and activations
hop stage→stage with `lax.ppermute` inside a `lax.scan` over
M + P - 1 ticks. The whole schedule — bubbles and all — is one compiled
XLA program; `jax.grad` differentiates straight through the scan+ppermute
for the backward pipeline.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe_apply(stage_fn: Callable, stage_params, x_microbatches: jax.Array,
                axis_name: str) -> jax.Array:
    """Run microbatches through the stage pipeline.

    stage_fn(local_params, x) -> y, same activation shape in and out.
    stage_params: LOCAL stage's params (leading stage dim already consumed
    by shard_map's in_spec, i.e. leaves are [1, ...]; indexed [0] here).
    x_microbatches: [M, mb, ...] — every stage sees all microbatches
    (replicated); only stage 0 consumes them.
    Returns [M, mb, ...] outputs (valid on the LAST stage; other stages
    return zeros — callers typically psum or select).
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    local_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    act_shape = x_microbatches.shape[1:]

    def tick(carry, t):
        incoming, outputs = carry
        # stage 0 injects microbatch t (clamped; validity handled below)
        mb = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        x_in = jnp.where(stage == 0, mb, incoming)
        y = stage_fn(local_params, x_in)
        # last stage banks its result for ticks where it holds microbatch
        # t - (n_stages - 1)
        out_idx = t - (n_stages - 1)
        valid = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        outputs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(out_idx, 0, m - 1), axis=0),
            lambda o: o,
            outputs)
        nxt = lax.ppermute(y, axis_name, perm)
        return (nxt, outputs), None

    init = (jnp.zeros(act_shape, x_microbatches.dtype),
            jnp.zeros((m,) + act_shape, x_microbatches.dtype))
    (_, outputs), _ = lax.scan(
        tick, init, jnp.arange(m + n_stages - 1))
    # broadcast the last stage's outputs to every stage so downstream code
    # (loss) is uniform SPMD
    last = lax.psum(
        jnp.where(stage == n_stages - 1, 1.0, 0.0) * outputs, axis_name)
    return last
