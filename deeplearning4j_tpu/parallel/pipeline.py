"""Pipeline parallelism: layers sharded over a `stage` mesh axis.

Out-of-reference extension (nothing in the 2015 reference pipelines layers
across devices — SURVEY §2.3 item 3). GPipe-style schedule expressed the
TPU way: stage parameters are STACKED on a leading dim sharded over the
`stage` axis, every device runs the same shard_map program, and activations
hop stage→stage with `lax.ppermute` inside a `lax.scan` over
M + P - 1 ticks. The whole schedule — bubbles and all — is one compiled
XLA program; `jax.grad` differentiates straight through the scan+ppermute
for the backward pipeline.

Memory discipline (VERDICT r3 #5): microbatches are NOT replicated to
every stage. Each stage holds only its blocked 1/P share of the inputs
and banks only its share of the outputs — O(M/P · mb) persistent per
device plus O(mb) transients. At tick t the owner of microbatch t
broadcasts it with a masked psum (stage 0 consumes it); the last stage's
result is broadcast the same way and banked by the owner of that output
slot. Bubble ticks skip the stage computation entirely via `lax.cond`
(a real runtime branch under XLA — fill/drain ticks cost a no-op, not a
garbage forward).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def zero1_flat_update(transform, opt_local, flat_g, flat_p,
                      axis_name: str, n: int, idx, k0: int):
    """One ZeRO-1 weight-update round over `axis_name` on a FLAT plane
    (arXiv 2004.13336) — the shard_map-side twin of
    `DataParallelTrainer._build_sharded_update_step`, shared by the
    pipeline trainer's stage and io update planes.

    flat_g / flat_p: the local replica's full flat gradient / parameter
    vector, already padded to `padded_extent(k0, n)` (padding lanes zero)
    and already carrying any pre-reduction scaling (e.g. the pipeline's
    1/n_stages factor).  opt_local: the transform state over this
    replica's {"p": [pe // n]} slice.  The reduce happens as
    `psum_scatter(flat_g)/n` — bitwise the same reduction tree as
    `pmean` — each replica steps only its slice, and `all_gather` (with
    the padding stripped) rebuilds the full vector.

    Returns (new_flat_p [k0], new_opt_local).
    """
    from deeplearning4j_tpu.ops.updaters import apply_updates

    ksh = flat_g.shape[0] // n
    g_sh = lax.psum_scatter(flat_g, axis_name, tiled=True) / n
    p_sh = lax.dynamic_slice_in_dim(flat_p, idx * ksh, ksh)
    up, opt_local = transform.update({"p": g_sh}, opt_local, {"p": p_sh})
    new_sh = apply_updates({"p": p_sh}, up)["p"]
    return lax.all_gather(new_sh, axis_name, tiled=True)[:k0], opt_local


def gpipe_apply(stage_fn: Callable, stage_params, x_local: jax.Array,
                axis_name: str, n_microbatches: int,
                remat_stage: bool = True) -> jax.Array:
    """Run the microbatch pipeline over this stage's LOCAL input share.

    stage_fn(local_params, x) -> y, same activation shape in and out.
    stage_params: LOCAL stage's params (leading stage dim already consumed
    by shard_map's in_spec, i.e. leaves are [1, ...]; indexed [0] here).
    x_local: [K, mb, ...] — this stage's blocked share of the
    n_microbatches real microbatches, K = ceil(M / P); stage s owns
    global microbatches [s*K, (s+1)*K). Slots past n_microbatches are
    padding and are never injected into the pipeline.
    Returns [K, mb, ...]: this stage's share of the outputs in the same
    blocked layout (padding slots stay zero).

    remat_stage (default True): rematerialize the per-tick stage forward
    in the backward pass (jax.checkpoint) — the scan then stashes only
    each tick's O(mb) input instead of every intermediate inside
    stage_fn, the standard GPipe memory discipline.
    """
    # Under shard_map, psum of a literal is the axis size as a concrete
    # int at trace time — usable for static perm lists and scan lengths.
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    k = x_local.shape[0]
    m = n_microbatches
    if k * n_stages < m:
        raise ValueError(
            f"x_local holds {k} slots/stage x {n_stages} stages "
            f"< {m} microbatches; pad each stage's share to "
            f"ceil(M/P) slots")
    local_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    act_shape = x_local.shape[1:]
    run_stage = (jax.checkpoint(stage_fn) if remat_stage else stage_fn)

    def tick(carry, t):
        incoming, outputs = carry
        # Owner of microbatch t broadcasts it (masked psum — O(mb)
        # transient on every stage, consumed by stage 0).
        owner = t // k
        mine = lax.dynamic_index_in_dim(
            x_local, jnp.clip(t % k, 0, k - 1), axis=0, keepdims=False)
        inject = jnp.logical_and(stage == owner, t < m)
        mb_t = lax.psum(jnp.where(inject, mine, jnp.zeros_like(mine)),
                        axis_name)
        x_in = jnp.where(stage == 0, mb_t, incoming)
        # Stage s holds real data only for ticks s <= t < s + m; bubble
        # ticks skip the forward entirely (runtime branch).
        active = jnp.logical_and(t >= stage, t < stage + m)
        y = lax.cond(active,
                     lambda a: run_stage(local_params, a),
                     lambda a: a, x_in)
        # The last stage's result is microbatch out_idx = t - (P - 1);
        # broadcast it and let the owner of that output slot bank it.
        out_idx = t - (n_stages - 1)
        emit = jnp.logical_and(stage == n_stages - 1,
                               jnp.logical_and(out_idx >= 0, out_idx < m))
        y_out = lax.psum(jnp.where(emit, y, jnp.zeros_like(y)), axis_name)
        bank = jnp.logical_and(stage == out_idx // k,
                               jnp.logical_and(out_idx >= 0, out_idx < m))
        outputs = lax.cond(
            bank,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y_out, jnp.clip(out_idx % k, 0, k - 1), axis=0),
            lambda o: o,
            outputs)
        nxt = lax.ppermute(y, axis_name, perm)
        return (nxt, outputs), None

    init = (jnp.zeros(act_shape, x_local.dtype),
            jnp.zeros((k,) + act_shape, x_local.dtype))
    (_, outputs), _ = lax.scan(
        tick, init, jnp.arange(m + n_stages - 1))
    return outputs
