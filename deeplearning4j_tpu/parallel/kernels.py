"""Pallas TPU kernels for the hot ops.

The framework's device tier is XLA; Pallas covers the spots where manual
VMEM scheduling beats the fusion XLA picks (SURVEY §7 "Native components":
attention is the FLOP/HBM-critical op of the transformer flagship).

`flash_attention(q, k, v, causal)` — fused online-softmax attention:
one Q block resident in VMEM while K/V stream through, running (m, l, acc)
accumulators — O(S) memory instead of materializing the [S, S] score
matrix in HBM. Backward is a custom VJP that recomputes scores densely in
plain jnp (correctness-first; a fused backward kernel is a further
optimization).

Off-TPU (tests, CPU meshes) the same kernel runs in Pallas interpret mode,
so numerics are validated everywhere the suite runs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def flash_enabled() -> bool:
    """Policy for the transformer's single-device attention path: the
    Pallas kernel on TPU by default; opt in/out anywhere with
    DL4J_TPU_FLASH=1/0."""
    import os

    flag = os.environ.get("DL4J_TPU_FLASH")
    if flag is not None:
        return flag.lower() in ("1", "true", "yes")
    return jax.default_backend() == "tpu"


def _pick_block(s: int, target: int = 128) -> int:
    """Largest divisor of s that is <= target (block sizes must tile S)."""
    b = min(s, target)
    while s % b:
        b -= 1
    return b


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, bq, bk,
                 n_kv_blocks):
    """Grid program: one (batch*head, q_block) pair.

    q_ref [bq, d]; k_ref/v_ref [s, d] (whole sequence for this bh);
    o_ref [bq, d].
    """
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale       # [bq, d]
    d = q.shape[-1]

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        if causal:
            k_pos = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        blk_m = jnp.max(s, axis=1)                        # [bq]
        new_m = jnp.maximum(m, blk_m)
        p = jnp.exp(s - new_m[:, None])
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        scale_old = jnp.exp(m - new_m)
        l = l * scale_old + jnp.sum(p, axis=1)
        acc = acc * scale_old[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, d]
        return new_m, l, acc

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, n_kv_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, interpret: bool) -> jax.Array:
    b, s, h, d = q.shape
    bq = _pick_block(s)
    bk = _pick_block(s)
    n_kv_blocks = s // bk
    scale = 1.0 / (d ** 0.5)

    # [B,S,H,D] -> [B*H, S, D]
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    qf, kf, vf = fold(q), fold(k), fold(v)

    kernel = functools.partial(
        _attn_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
        n_kv_blocks=n_kv_blocks)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _dense_grads(q, k, v, causal, g):
    """Standard attention backward in plain jnp (dense recompute)."""
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, :, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bqhk,bqhd->bkhd", p, g)
    dp = jnp.einsum("bqhd,bkhd->bqhk", g, v)
    ds = p * (dp - jnp.sum(p * dp, axis=-1, keepdims=True))
    dq = jnp.einsum("bqhk,bkhd->bqhd", ds, k) * scale
    dk = jnp.einsum("bqhk,bqhd->bkhd", ds, q) * scale
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = True,
                    interpret: bool | None = None):
    """Fused attention [B,S,H,D] -> [B,S,H,D]. interpret=None auto-detects
    (compiled on TPU, interpreter elsewhere)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, interpret)


def _fa_fwd(q, k, v, causal, interpret):
    return flash_attention(q, k, v, causal, interpret), (q, k, v)


def _fa_bwd(causal, interpret, residuals, g):
    q, k, v = residuals
    return _dense_grads(q, k, v, causal, g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
