"""Pallas TPU kernels for the hot ops.

The framework's device tier is XLA; Pallas covers the spots where manual
VMEM scheduling beats the fusion XLA picks (SURVEY §7 "Native components":
attention is the FLOP/HBM-critical op of the transformer flagship).

`flash_attention(q, k, v, causal)` — fused online-softmax attention:
one Q block resident in VMEM while K/V stream through, running (m, l, acc)
accumulators — O(S) memory instead of materializing the [S, S] score
matrix in HBM. The forward also emits the per-row logsumexp; the backward
is the FlashAttention-2 scheme: two fused kernels (dK/dV with K-block
resident and Q/dO streaming, dQ with Q-block resident and K/V streaming)
that recompute P = exp(S - lse) blockwise, so training memory stays O(S)
too. Causal blocks that are fully masked are skipped via dynamic loop
bounds. Set DL4J_TPU_FLASH_BWD=0 to fall back to the dense-recompute
backward (kept for A/B benchmarking).

Off-TPU (tests, CPU meshes) the same kernel runs in Pallas interpret mode,
so numerics are validated everywhere the suite runs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams (~0.6); accept both so
# the kernels import on either side of the rename.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

NEG_INF = -1e30


def mask_value(dtype) -> jax.Array:
    """Finite large-negative mask constant for `dtype` softmax scores.

    The hardcoded ``-1e30`` the masked-softmax paths used overflows to
    ``-inf`` in fp16 (max ~6.5e4), so a fully masked row becomes
    ``softmax(-inf - (-inf)) = NaN`` and poisons every downstream read.
    ``finfo.min / 2`` is representable in every float dtype and still
    underflows to exactly 0 through ``exp(s - max)``, so masked
    positions contribute nothing while fully-masked rows stay finite.
    """
    return jnp.asarray(jnp.finfo(jnp.dtype(dtype)).min / 2, dtype)

# Softmax row-stats (lse, delta) cross the pallas_call boundary in
# LANE-REPLICATED form [B*H, S, REP]: Mosaic tiles VMEM blocks (8, 128)
# over the last two dims, so a compact [B*H, S] array can never be
# blocked per-(batch*head) row — the size-1 sublane dim is illegal.
# Replicating each scalar across the 128 lanes keeps every stat block
# (bq, 128)-shaped and sublane-aligned with the [bq, bk] score tiles it
# corrects, so the kernels never transpose.  (Same layout the TPU
# flash-attention literature uses for its l/m residuals.)
REP = 128


def flash_enabled() -> bool:
    """Policy for the transformer's single-device attention path: the
    Pallas kernel on TPU by default; opt in/out anywhere with
    DL4J_TPU_FLASH=1/0."""
    import os

    flag = os.environ.get("DL4J_TPU_FLASH")
    if flag is not None:
        return flag.lower() in ("1", "true", "yes")
    return jax.default_backend() == "tpu"


def _pick_block(s: int, target: int = None, kind: str = "q") -> int:
    """Largest divisor of s that is <= target (block sizes must tile S).
    Tunable per-axis via DL4J_TPU_FLASH_BQ / DL4J_TPU_FLASH_BK (the VMEM
    residency/occupancy trade-off differs per chip generation)."""
    import os

    if target is None:
        env = os.environ.get(f"DL4J_TPU_FLASH_B{kind.upper()}")
        target = 128
        if env:
            if int(env) <= 0:
                raise ValueError(
                    f"DL4J_TPU_FLASH_B{kind.upper()}={env}: block size "
                    f"target must be a positive integer")
            target = int(env)
    b = min(s, target)
    while s % b:
        b -= 1
    return b


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, bq,
                 bk, n_kv_blocks):
    """Grid program: one (batch*head, q_block) pair.

    q_ref [bq, d]; k_ref/v_ref [s, d] (whole sequence for this bh);
    o_ref [bq, d]; lse_ref [bq, REP] (lane-replicated logsumexp of the
    scaled scores, consumed by the fused backward).

    All row stats are kept 2-D [bq, 1] (keepdims reductions) so every
    intermediate is a sublane vector Mosaic can tile.
    """
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale       # [bq, d]
    d = q.shape[-1]

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def body(j, carry):
        m, l, acc = carry                                 # [bq,1]x2,[bq,d]
        k_blk = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        if causal:
            k_pos = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        blk_m = jnp.max(s, axis=1, keepdims=True)         # [bq, 1]
        new_m = jnp.maximum(m, blk_m)
        p = jnp.exp(s - new_m)
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        scale_old = jnp.exp(m - new_m)
        l = l * scale_old + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * scale_old + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, d]
        return new_m, l, acc

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    # Causal: kv blocks past this q block are fully masked — skip them.
    n_blocks = jnp.minimum(
        n_kv_blocks, (qi * bq + bq + bk - 1) // bk) if causal else n_kv_blocks
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to(
        m + jnp.log(jnp.maximum(l, 1e-30)), (bq, REP))


def _fold(x, b, s, h, d):
    """[B,S,H,D] -> [B*H, S, D]"""
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, b, s, h, d):
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_forward(q, k, v, causal: bool, interpret: bool):
    """Returns (out [B,S,H,D], lse [B*H, S]).

    The kernel emits lse lane-replicated [B*H, S, REP] (see REP above);
    the compact [B*H, S] view handed to callers (ring attention, the
    fused backward's residuals) is lane 0.
    """
    b, s, h, d = q.shape
    bq = _pick_block(s, kind="q")
    bk = _pick_block(s, kind="k")
    n_kv_blocks = s // bk
    scale = 1.0 / (d ** 0.5)

    qf, kf, vf = (_fold(x, b, s, h, d) for x in (q, k, v))

    kernel = functools.partial(
        _attn_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
        n_kv_blocks=n_kv_blocks)
    out, lse_rep = pl.pallas_call(
        kernel,
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, bq, REP), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, REP), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(qf, kf, vf)
    return _unfold(out, b, s, h, d), lse_rep[..., 0]


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *,
                scale, causal, bq, bk, n_q_blocks):
    """Grid program: (batch*head, kv_block, q_block), q innermost.

    The K/V block is revisited across the inner q steps while Q/dO and
    the row stats stream through as (bq, ·) blocks — every block is
    DMA-sized by the grid, so VMEM use is independent of S.  dK/dV
    accumulate in f32 VMEM scratch (persistent across the sequential
    inner steps) and flush once on the last q step.
    """
    j, i = pl.program_id(1), pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # Causal: this (q, kv) block pair touches the triangle iff the last
    # q position reaches the first k position.
    live = (i * bq + bq - 1 >= j * bk) if causal else True

    @pl.when(live)
    def _compute():
        k_blk = k_ref[0].astype(jnp.float32)              # [bk, d]
        v_blk = v_ref[0].astype(jnp.float32)
        q_blk = q_ref[0].astype(jnp.float32)              # [bq, d]
        do_blk = do_ref[0].astype(jnp.float32)
        lse_blk = lse_ref[:, :1]                          # [bq, 1]
        delta_blk = delta_ref[:, :1]
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_blk)                          # [bq, bk]
        dv_acc[...] += jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, d]
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        ds = p * (dp - delta_blk)
        dk_acc[...] += jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, d]

    @pl.when(i == n_q_blocks - 1)
    def _flush():
        dk_ref[0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, bq, bk, n_kv_blocks):
    """Grid program: (batch*head, q_block, kv_block), kv innermost; the
    Q block is revisited while K/V stream through.  Same scratch-
    accumulate-flush scheme as _dkv_kernel."""
    qi, jb = pl.program_id(1), pl.program_id(2)

    @pl.when(jb == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = (jb * bk <= qi * bq + bq - 1) if causal else True

    @pl.when(live)
    def _compute():
        q_blk = q_ref[0].astype(jnp.float32)              # [bq, d]
        do_blk = do_ref[0].astype(jnp.float32)
        lse_blk = lse_ref[:, :1]                          # [bq, 1]
        delta_blk = delta_ref[:, :1]
        k_blk = k_ref[0].astype(jnp.float32)              # [bk, d]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = jb * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_blk)
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        ds = p * (dp - delta_blk)
        dq_acc[...] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, d]

    @pl.when(jb == n_kv_blocks - 1)
    def _flush():
        dq_ref[0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal: bool, interpret: bool):
    b, s, h, d = q.shape
    of = _fold(o, b, s, h, d)
    gf = _fold(g, b, s, h, d)
    # delta_i = sum_d dO_i * O_i — the softmax-jacobian row correction
    # (FlashAttention-2 eq. 4); cheap elementwise, XLA fuses it.
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    return _bwd_block(q, k, v, g, lse, delta, causal, interpret)


def _bwd_block(q, k, v, g, lse, delta, causal: bool, interpret: bool):
    """(dq, dk, dv) for one attention block given the Q-side row stats.

    q/k/v/g: [B,S,H,D]; lse/delta: [B*H, S] float32.  Used both by the
    single-device VJP and (per ring step, with the GLOBAL lse/delta) by
    ring attention's distributed backward.
    """
    b, s, h, d = q.shape
    bq = _pick_block(s, kind="q")
    bk = _pick_block(s, kind="k")
    scale = 1.0 / (d ** 0.5)

    qf, kf, vf, gf = (_fold(x, b, s, h, d) for x in (q, k, v, g))
    # Lane-replicate the compact row stats for the kernels (see REP).
    lse_rep = jnp.broadcast_to(lse[:, :, None], (b * h, s, REP))
    delta_rep = jnp.broadcast_to(delta[:, :, None], (b * h, s, REP))

    dkf, dvf = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, n_q_blocks=s // bq),
        grid=(b * h, s // bk, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, i, 0)),    # q
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),    # k
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),    # v
            pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, i, 0)),    # do
            pl.BlockSpec((None, bq, REP), lambda bh, j, i: (bh, i, 0)),
            pl.BlockSpec((None, bq, REP), lambda bh, j, i: (bh, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((b * h, s, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, s, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        # Inner q dim is sequential (scratch accumulation); outer two are
        # independent, letting Mosaic pipeline/parallelize them.
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, gf, lse_rep, delta_rep)

    dqf = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, n_kv_blocks=s // bk),
        grid=(b * h, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, jb: (bh, qi, 0)),  # q
            pl.BlockSpec((1, bk, d), lambda bh, qi, jb: (bh, jb, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda bh, qi, jb: (bh, jb, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda bh, qi, jb: (bh, qi, 0)),  # do
            pl.BlockSpec((None, bq, REP), lambda bh, qi, jb: (bh, qi, 0)),
            pl.BlockSpec((None, bq, REP), lambda bh, qi, jb: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, jb: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, gf, lse_rep, delta_rep)

    return tuple(_unfold(x, b, s, h, d) for x in (dqf, dkf, dvf))


def _dense_grads(q, k, v, causal, g):
    """Standard attention backward in plain jnp (dense recompute)."""
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, :, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bqhk,bqhd->bkhd", p, g)
    dp = jnp.einsum("bqhd,bkhd->bqhk", g, v)
    ds = p * (dp - jnp.sum(p * dp, axis=-1, keepdims=True))
    dq = jnp.einsum("bqhk,bkhd->bqhd", ds, k) * scale
    dk = jnp.einsum("bqhk,bqhd->bkhd", ds, q) * scale
    return dq, dk, dv


def _flash_bwd_enabled() -> bool:
    import os

    return os.environ.get("DL4J_TPU_FLASH_BWD", "1").lower() in (
        "1", "true", "yes")


def _resolve_interpret(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = True,
                    interpret: bool | None = None):
    """Fused attention [B,S,H,D] -> [B,S,H,D]. interpret=None auto-detects
    (compiled on TPU, interpreter elsewhere)."""
    out, _ = _flash_forward(q, k, v, causal, _resolve_interpret(interpret))
    return out


def _fa_fwd(q, k, v, causal, interpret):
    out, lse = _flash_forward(q, k, v, causal, _resolve_interpret(interpret))
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, interpret, residuals, g):
    q, k, v, o, lse = residuals
    if not _flash_bwd_enabled():
        return _dense_grads(q, k, v, causal, g)
    return _flash_backward(q, k, v, o, lse, g, causal,
                           _resolve_interpret(interpret))


flash_attention.defvjp(_fa_fwd, _fa_bwd)
