"""Pallas TPU kernels for the hot ops.

The framework's device tier is XLA; Pallas covers the spots where manual
VMEM scheduling beats the fusion XLA picks (SURVEY §7 "Native components":
attention is the FLOP/HBM-critical op of the transformer flagship).

`flash_attention(q, k, v, causal)` — fused online-softmax attention:
one Q block resident in VMEM while K/V stream through, running (m, l, acc)
accumulators — O(S) memory instead of materializing the [S, S] score
matrix in HBM. The forward also emits the per-row logsumexp; the backward
is the FlashAttention-2 scheme: two fused kernels (dK/dV with K-block
resident and Q/dO streaming, dQ with Q-block resident and K/V streaming)
that recompute P = exp(S - lse) blockwise, so training memory stays O(S)
too. Causal blocks that are fully masked are skipped via dynamic loop
bounds. Set DL4J_TPU_FLASH_BWD=0 to fall back to the dense-recompute
backward (kept for A/B benchmarking).

Off-TPU (tests, CPU meshes) the same kernel runs in Pallas interpret mode,
so numerics are validated everywhere the suite runs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def flash_enabled() -> bool:
    """Policy for the transformer's single-device attention path: the
    Pallas kernel on TPU by default; opt in/out anywhere with
    DL4J_TPU_FLASH=1/0."""
    import os

    flag = os.environ.get("DL4J_TPU_FLASH")
    if flag is not None:
        return flag.lower() in ("1", "true", "yes")
    return jax.default_backend() == "tpu"


def _pick_block(s: int, target: int = None, kind: str = "q") -> int:
    """Largest divisor of s that is <= target (block sizes must tile S).
    Tunable per-axis via DL4J_TPU_FLASH_BQ / DL4J_TPU_FLASH_BK (the VMEM
    residency/occupancy trade-off differs per chip generation)."""
    import os

    if target is None:
        env = os.environ.get(f"DL4J_TPU_FLASH_B{kind.upper()}")
        target = 128
        if env:
            if int(env) <= 0:
                raise ValueError(
                    f"DL4J_TPU_FLASH_B{kind.upper()}={env}: block size "
                    f"target must be a positive integer")
            target = int(env)
    b = min(s, target)
    while s % b:
        b -= 1
    return b


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, bq,
                 bk, n_kv_blocks):
    """Grid program: one (batch*head, q_block) pair.

    q_ref [bq, d]; k_ref/v_ref [s, d] (whole sequence for this bh);
    o_ref [bq, d]; lse_ref [bq] (logsumexp of the scaled scores, consumed
    by the fused backward).
    """
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale       # [bq, d]
    d = q.shape[-1]

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        if causal:
            k_pos = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        blk_m = jnp.max(s, axis=1)                        # [bq]
        new_m = jnp.maximum(m, blk_m)
        p = jnp.exp(s - new_m[:, None])
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        scale_old = jnp.exp(m - new_m)
        l = l * scale_old + jnp.sum(p, axis=1)
        acc = acc * scale_old[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, d]
        return new_m, l, acc

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    # Causal: kv blocks past this q block are fully masked — skip them.
    n_blocks = jnp.minimum(
        n_kv_blocks, (qi * bq + bq + bk - 1) // bk) if causal else n_kv_blocks
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(jnp.maximum(l, 1e-30))


def _fold(x, b, s, h, d):
    """[B,S,H,D] -> [B*H, S, D]"""
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, b, s, h, d):
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_forward(q, k, v, causal: bool, interpret: bool):
    """Returns (out [B,S,H,D], lse [B*H, S])."""
    b, s, h, d = q.shape
    bq = _pick_block(s, kind="q")
    bk = _pick_block(s, kind="k")
    n_kv_blocks = s // bk
    scale = 1.0 / (d ** 0.5)

    qf, kf, vf = (_fold(x, b, s, h, d) for x in (q, k, v))

    kernel = functools.partial(
        _attn_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
        n_kv_blocks=n_kv_blocks)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq), lambda bh, qi: (bh, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return _unfold(out, b, s, h, d), lse


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, bq, bk, n_q_blocks):
    """Grid program: one (batch*head, kv_block) pair; K/V block resident,
    Q/dO/lse/delta stream through in bq-sized blocks."""
    j = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)              # [bk, d]
    v_blk = v_ref[0].astype(jnp.float32)
    d = k_blk.shape[-1]
    k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        lse_blk = lse_ref[0, pl.ds(i * bq, bq)]
        delta_blk = delta_ref[0, pl.ds(i * bq, bq)]
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_blk[:, None])                 # [bq, bk]
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, d]
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        ds = p * (dp - delta_blk[:, None])
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, d]
        return dk, dv

    # Causal: q blocks strictly before this kv block are fully masked.
    start = (j * bk) // bq if causal else 0
    zeros = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, n_q_blocks, body, (zeros, zeros))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, causal, bq, bk, n_kv_blocks):
    """Grid program: one (batch*head, q_block) pair; Q block resident,
    K/V stream through."""
    qi = pl.program_id(1)
    q_blk = q_ref[0].astype(jnp.float32)              # [bq, d]
    do_blk = do_ref[0].astype(jnp.float32)
    lse_blk = lse_ref[0]
    delta_blk = delta_ref[0]
    d = q_blk.shape[-1]
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def body(jb, dq):
        k_blk = k_ref[0, pl.ds(jb * bk, bk), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(jb * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            k_pos = jb * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_blk[:, None])
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        ds = p * (dp - delta_blk[:, None])
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, d]

    # Causal: kv blocks past this q block are fully masked.
    n_blocks = jnp.minimum(
        n_kv_blocks, (qi * bq + bq + bk - 1) // bk) if causal else n_kv_blocks
    dq = jax.lax.fori_loop(0, n_blocks, body,
                           jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal: bool, interpret: bool):
    b, s, h, d = q.shape
    of = _fold(o, b, s, h, d)
    gf = _fold(g, b, s, h, d)
    # delta_i = sum_d dO_i * O_i — the softmax-jacobian row correction
    # (FlashAttention-2 eq. 4); cheap elementwise, XLA fuses it.
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    return _bwd_block(q, k, v, g, lse, delta, causal, interpret)


def _bwd_block(q, k, v, g, lse, delta, causal: bool, interpret: bool):
    """(dq, dk, dv) for one attention block given the Q-side row stats.

    q/k/v/g: [B,S,H,D]; lse/delta: [B*H, S] float32.  Used both by the
    single-device VJP and (per ring step, with the GLOBAL lse/delta) by
    ring attention's distributed backward.
    """
    b, s, h, d = q.shape
    bq = _pick_block(s, kind="q")
    bk = _pick_block(s, kind="k")
    scale = 1.0 / (d ** 0.5)

    qf, kf, vf, gf = (_fold(x, b, s, h, d) for x in (q, k, v, g))

    dkv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, n_q_blocks=s // bq),
        grid=(b * h, s // bk),
        in_specs=[
            pl.BlockSpec((1, s, d), lambda bh, j: (bh, 0, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),  # v
            pl.BlockSpec((1, s, d), lambda bh, j: (bh, 0, 0)),   # do
            pl.BlockSpec((1, s), lambda bh, j: (bh, 0)),         # lse
            pl.BlockSpec((1, s), lambda bh, j: (bh, 0)),         # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((b * h, s, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, s, d), v.dtype)],
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)
    dkf, dvf = dkv

    dqf = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, n_kv_blocks=s // bk),
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),  # q
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),    # k
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),    # v
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),  # do
            pl.BlockSpec((1, bq), lambda bh, qi: (bh, qi)),        # lse
            pl.BlockSpec((1, bq), lambda bh, qi: (bh, qi)),        # delta
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    return tuple(_unfold(x, b, s, h, d) for x in (dqf, dkf, dvf))


def _dense_grads(q, k, v, causal, g):
    """Standard attention backward in plain jnp (dense recompute)."""
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, :, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bqhk,bqhd->bkhd", p, g)
    dp = jnp.einsum("bqhd,bkhd->bqhk", g, v)
    ds = p * (dp - jnp.sum(p * dp, axis=-1, keepdims=True))
    dq = jnp.einsum("bqhk,bkhd->bqhd", ds, k) * scale
    dk = jnp.einsum("bqhk,bqhd->bkhd", ds, q) * scale
    return dq, dk, dv


def _flash_bwd_enabled() -> bool:
    import os

    return os.environ.get("DL4J_TPU_FLASH_BWD", "1").lower() in (
        "1", "true", "yes")


def _resolve_interpret(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = True,
                    interpret: bool | None = None):
    """Fused attention [B,S,H,D] -> [B,S,H,D]. interpret=None auto-detects
    (compiled on TPU, interpreter elsewhere)."""
    out, _ = _flash_forward(q, k, v, causal, _resolve_interpret(interpret))
    return out


def _fa_fwd(q, k, v, causal, interpret):
    out, lse = _flash_forward(q, k, v, causal, _resolve_interpret(interpret))
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, interpret, residuals, g):
    q, k, v, o, lse = residuals
    if not _flash_bwd_enabled():
        return _dense_grads(q, k, v, causal, g)
    return _flash_backward(q, k, v, o, lse, g, causal,
                           _resolve_interpret(interpret))


flash_attention.defvjp(_fa_fwd, _fa_bwd)
