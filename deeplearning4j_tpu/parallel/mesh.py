"""Device-mesh helpers.

The reference's distribution fabric was Spark broadcast/accumulators, Akka
actors over Hazelcast maps, and YARN Avro RPC (SURVEY §2.3) — all moving full
dense parameter vectors through a central master, O(workers x params). The
TPU-native fabric is a `jax.sharding.Mesh` over the chips: gradient exchange
becomes `lax.pmean` over ICI, compiled into the step function itself; there
is no master and no parameter server.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel import partition as part_lib


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = ("data",),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over the available devices.

    Default: 1-D data-parallel mesh over all devices. For hybrid
    parallelism pass e.g. shape=(4, 2), axis_names=("data", "model").
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"Mesh shape {shape} needs {int(np.prod(shape))} devices, "
            f"have {len(devices)}")
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def named_sharding(mesh: Mesh, spec) -> NamedSharding:
    """NamedSharding from either spec vocabulary — the package's
    `partition.PartitionSpec` or a raw `jax.sharding.PartitionSpec`."""
    return NamedSharding(mesh, part_lib.as_jax_leaf(spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return named_sharding(mesh, part_lib.replicated())


def batch_sharded(mesh: Mesh, axis: str = "data") -> NamedSharding:
    return named_sharding(mesh, part_lib.sharded(axis))


def shard_batch(mesh: Mesh, tree, axis: str = "data"):
    """Place host arrays so dim 0 shards over the mesh's data axis."""
    sh = batch_sharded(mesh, axis)
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sh) if a is not None else None, tree,
        is_leaf=lambda a: a is None)


def replicate(mesh: Mesh, tree):
    sh = replicated(mesh)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """`jax.shard_map` across jax versions (the keyword for disabling
    replication checking was renamed check_rep -> check_vma in jax 0.8);
    single shim shared by every shard_map user in the package."""
    try:
        from jax import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def round_batch_to_mesh(batch_size: int, mesh: Mesh) -> int:
    """Smallest batch >= batch_size divisible into equal shards over the
    mesh's devices."""
    n = mesh.devices.size
    return ((batch_size + n - 1) // n) * n


def sparse_allgather_step(mesh: Optional[Mesh], deltas_fn, apply_fn,
                          n_state: int, n_sharded: int, n_scalar: int = 0,
                          with_key: bool = False):
    """Data-parallel harness for sparse embedding updates (shared by
    Word2Vec and GloVe `mesh=`): builds ``step(*state, *scalars,
    *sharded[, key]) -> (*new_state, loss)`` where

    - ``deltas_fn(same args) -> (loss, aux)`` computes per-shard sparse
      pieces (aux: any pytree of [B, ...] arrays — row indices, deltas),
    - ``apply_fn(*state, *scalars, aux) -> new_state tuple`` scatters
      them into the replicated state.

    mesh=None applies directly.  With a mesh, the trailing ``n_sharded``
    args shard over the FIRST axis, loss is psum'd, aux is all_gathered
    (tiled — O(B) comms, never a dense table), and every replica applies
    the identical scatter, so replicated state never diverges.  with_key
    folds the axis index into a trailing PRNG key."""

    def single(*args):
        lead = args[:n_state + n_scalar]
        loss, aux = deltas_fn(*args)
        return (*apply_fn(*lead, aux), loss)

    if mesh is None:
        return single
    axis = mesh.axis_names[0]

    def sharded(*args):
        if with_key:
            *rest, key = args
            key = jax.random.fold_in(key, jax.lax.axis_index(axis))
            args = (*rest, key)
        lead = args[:n_state + n_scalar]
        loss, aux = deltas_fn(*args)
        loss = jax.lax.psum(loss, axis)
        aux = jax.tree_util.tree_map(
            lambda a: jax.lax.all_gather(a, axis, tiled=True), aux)
        return (*apply_fn(*lead, aux), loss)

    in_specs = ((P(),) * (n_state + n_scalar) + (P(axis),) * n_sharded
                + ((P(),) if with_key else ()))
    return shard_map_compat(sharded, mesh=mesh, in_specs=in_specs,
                            out_specs=P())
