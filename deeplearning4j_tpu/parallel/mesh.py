"""Device-mesh helpers.

The reference's distribution fabric was Spark broadcast/accumulators, Akka
actors over Hazelcast maps, and YARN Avro RPC (SURVEY §2.3) — all moving full
dense parameter vectors through a central master, O(workers x params). The
TPU-native fabric is a `jax.sharding.Mesh` over the chips: gradient exchange
becomes `lax.pmean` over ICI, compiled into the step function itself; there
is no master and no parameter server.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = ("data",),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over the available devices.

    Default: 1-D data-parallel mesh over all devices. For hybrid
    parallelism pass e.g. shape=(4, 2), axis_names=("data", "model").
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"Mesh shape {shape} needs {int(np.prod(shape))} devices, "
            f"have {len(devices)}")
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "data") -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def shard_batch(mesh: Mesh, tree, axis: str = "data"):
    """Place host arrays so dim 0 shards over the mesh's data axis."""
    sh = batch_sharded(mesh, axis)
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sh) if a is not None else None, tree,
        is_leaf=lambda a: a is None)


def replicate(mesh: Mesh, tree):
    sh = replicated(mesh)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)
