"""Ring attention: exact attention over a sequence sharded across devices.

Out-of-reference extension (SURVEY §5 "long-context: absent" — the 2015
reference loops an LSTM over time on one device, `GravesLSTM.java:108`).
For the TPU framework long context is first-class: the sequence dimension
is sharded over a mesh axis, each device holds a Q/K/V block, and K/V
blocks rotate around the ring via `lax.ppermute` while a running
flash-attention-style (m, l, o) accumulator keeps the softmax exact —
O(S/P) memory per device, compute overlapping communication on ICI.

Pattern follows the public blockwise/ring attention formulation (Liu et al.
ring attention; PAPERS.md) — no reference code involved.
"""

from __future__ import annotations


from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """One Q-block vs one KV-block. q:[B,Sq,H,D] k,v:[B,Sk,H,D]
    mask:[Sq,Sk] bool (True = attend). Returns (scores-max m:[B,Sq,H],
    sumexp l:[B,Sq,H], out o:[B,Sq,H,D])."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k,
                   precision=lax.Precision.HIGHEST) / jnp.sqrt(
                       jnp.asarray(d, q.dtype))
    s = jnp.where(mask[None, :, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    # rows with no attendable key: exp(NEG_INF - NEG_INF) = 1 per key —
    # mask them back out so l counts only real keys.
    p = jnp.where(mask[None, :, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bqhk,bkhd->bqhd", p, v,
                   precision=lax.Precision.HIGHEST)
    return m, l, o


def attention(q, k, v, causal: bool = True):
    """Plain single-device attention [B,S,H,D] — the unsharded baseline."""
    sq, sk = q.shape[1], k.shape[1]
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool))
    else:
        mask = jnp.ones((sq, sk), bool)
    m, l, o = _block_attn(q, k, v, mask)
    return o / jnp.maximum(l, 1e-30)[..., None]


def ring_attention(q, k, v, axis_name: Optional[str], causal: bool = True):
    """Attention with the S dimension sharded over `axis_name`.

    Call inside shard_map: q/k/v are the LOCAL blocks [B, S_local, H, D].
    Requires equal S_local per device. axis_name=None falls back to the
    dense single-device path.
    """
    if axis_name is None:
        return attention(q, k, v, causal)

    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    s_local = q.shape[1]
    b, _, h, dh = q.shape

    # positions are global: block i covers [i*s_local, (i+1)*s_local)
    q_pos = my_idx * s_local + jnp.arange(s_local)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(carry, _):
        kv, kv_idx, m, l, o = carry
        k_blk, v_blk = kv
        k_pos = kv_idx * s_local + jnp.arange(s_local)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((s_local, s_local), bool)
        bm, bl, bo = _block_attn(q, k_blk, v_blk, mask)
        new_m = jnp.maximum(m, bm)
        # rescale both accumulators onto the new max
        scale_old = jnp.exp(m - new_m)
        scale_new = jnp.exp(bm - new_m)
        l = l * scale_old + bl * scale_new
        o = o * scale_old[..., None] + bo * scale_new[..., None]
        # rotate KV around the ring (overlaps with next block's compute)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        kv_idx = lax.ppermute(kv_idx, axis_name, perm)
        return ((k_nxt, v_nxt), kv_idx, new_m, l, o), None

    init = (
        (k, v),
        my_idx,
        jnp.full((b, s_local, h), NEG_INF, q.dtype),
        jnp.zeros((b, s_local, h), q.dtype),
        jnp.zeros((b, s_local, h, dh), q.dtype),
    )
    (_, _, _, l, o), _ = lax.scan(body, init, None, length=axis_size)
    return o / jnp.maximum(l, 1e-30)[..., None]
