"""Ring attention: exact attention over a sequence sharded across devices.

Out-of-reference extension (SURVEY §5 "long-context: absent" — the 2015
reference loops an LSTM over time on one device, `GravesLSTM.java:108`).
For the TPU framework long context is first-class: the sequence dimension
is sharded over a mesh axis, each device holds a Q/K/V block, and K/V
blocks rotate around the ring via `lax.ppermute` while a running
flash-attention-style (m, l, o) accumulator keeps the softmax exact —
O(S/P) memory per device, compute overlapping communication on ICI.

Two inner-block engines:
- `ring_attention` — plain-jnp blockwise softmax (reference formulation,
  autodiff backward; materializes [S/P, S/P] scores per block).
- `ring_flash_attention` — the Pallas flash kernels per block with a
  custom distributed VJP: the backward is a SECOND ring pass that rotates
  (K, V, dK, dV) while each device folds in its local Q/dO contribution
  using the saved global logsumexp — O(S/P) memory end to end, forward
  AND backward.

Pattern follows the public blockwise/ring attention formulation (Liu et al.
ring attention; PAPERS.md) — no reference code involved.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """One Q-block vs one KV-block. q:[B,Sq,H,D] k,v:[B,Sk,H,D]
    mask:[Sq,Sk] bool (True = attend). Returns (scores-max m:[B,Sq,H],
    sumexp l:[B,Sq,H], out o:[B,Sq,H,D])."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k,
                   precision=lax.Precision.HIGHEST) / jnp.sqrt(
                       jnp.asarray(d, q.dtype))
    s = jnp.where(mask[None, :, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    # rows with no attendable key: exp(NEG_INF - NEG_INF) = 1 per key —
    # mask them back out so l counts only real keys.
    p = jnp.where(mask[None, :, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bqhk,bkhd->bqhd", p, v,
                   precision=lax.Precision.HIGHEST)
    return m, l, o


def attention(q, k, v, causal: bool = True):
    """Plain single-device attention [B,S,H,D] — the unsharded baseline."""
    sq, sk = q.shape[1], k.shape[1]
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool))
    else:
        mask = jnp.ones((sq, sk), bool)
    m, l, o = _block_attn(q, k, v, mask)
    return o / jnp.maximum(l, 1e-30)[..., None]


def ring_attention(q, k, v, axis_name: Optional[str], causal: bool = True):
    """Attention with the S dimension sharded over `axis_name`.

    Call inside shard_map: q/k/v are the LOCAL blocks [B, S_local, H, D].
    Requires equal S_local per device. axis_name=None falls back to the
    dense single-device path.
    """
    if axis_name is None:
        return attention(q, k, v, causal)

    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    s_local = q.shape[1]
    b, _, h, dh = q.shape

    # positions are global: block i covers [i*s_local, (i+1)*s_local)
    q_pos = my_idx * s_local + jnp.arange(s_local)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(carry, _):
        kv, kv_idx, m, l, o = carry
        k_blk, v_blk = kv
        k_pos = kv_idx * s_local + jnp.arange(s_local)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((s_local, s_local), bool)
        bm, bl, bo = _block_attn(q, k_blk, v_blk, mask)
        new_m = jnp.maximum(m, bm)
        # rescale both accumulators onto the new max
        scale_old = jnp.exp(m - new_m)
        scale_new = jnp.exp(bm - new_m)
        l = l * scale_old + bl * scale_new
        o = o * scale_old[..., None] + bo * scale_new[..., None]
        # rotate KV around the ring (overlaps with next block's compute)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        kv_idx = lax.ppermute(kv_idx, axis_name, perm)
        return ((k_nxt, v_nxt), kv_idx, new_m, l, o), None

    init = (
        (k, v),
        my_idx,
        jnp.full((b, s_local, h), NEG_INF, q.dtype),
        jnp.zeros((b, s_local, h), q.dtype),
        jnp.zeros((b, s_local, h, dh), q.dtype),
    )
    (_, _, _, l, o), _ = lax.scan(body, init, None, length=axis_size)
    return o / jnp.maximum(l, 1e-30)[..., None]


# ---------------------------------------------------------------------------
# Pallas-backed ring attention with distributed backward
# ---------------------------------------------------------------------------

def _fold_rows(x):
    """[B,S,H] -> [B*H, S] (the row-stat layout the kernels consume)."""
    return x.transpose(0, 2, 1).reshape(-1, x.shape[1])


def _flash_block_fwd(q, k, v, causal, interpret):
    """One q-block vs one kv-block through the Pallas forward.
    Returns (o [B,S,H,D] normalized, lse [B,S,H] float32)."""
    from deeplearning4j_tpu.parallel import kernels as _k

    o, lse = _k._flash_forward(q, k, v, causal, interpret)
    b, s, h, _ = q.shape
    return o, lse.reshape(b, h, s).transpose(0, 2, 1)


def _flash_block_bwd(q, k, v, g, lse, delta, causal, interpret):
    """(dq, dk, dv) for one block pair; lse/delta are the GLOBAL Q-side
    row stats [B,S,H]."""
    from deeplearning4j_tpu.parallel import kernels as _k

    return _k._bwd_block(q, k, v, g, _fold_rows(lse), _fold_rows(delta),
                         causal, interpret)


def _ring_cases(causal, my_idx, kv_idx):
    """0 = fully masked (skip), 1 = diagonal (causal mask), 2 = full."""
    if not causal:
        return jnp.int32(2)
    return jnp.sign(my_idx - kv_idx).astype(jnp.int32) + 1


def _ring_flash_fwd_pass(q, k, v, axis_name, causal, interpret):
    axis_size = lax.psum(1, axis_name)
    # Non-causal rings never branch on block position, so don't emit
    # axis_index at all: the partition-id HLO it lowers to is rejected by
    # the SPMD partitioner when XLA keeps the shard_map body outlined
    # (observed on CPU meshes), and an unused carry doesn't DCE it.
    my_idx = lax.axis_index(axis_name) if causal else jnp.int32(0)
    b, s_local, h, _ = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(carry, _):
        k_blk, v_blk, kv_idx, o, lse = carry

        def skip(_):
            return jnp.zeros_like(o), jnp.full_like(lse, NEG_INF)

        def diag(_):
            return _flash_block_fwd(q, k_blk, v_blk, True, interpret)

        def full(_):
            return _flash_block_fwd(q, k_blk, v_blk, False, interpret)

        if causal:
            bo, blse = lax.switch(_ring_cases(causal, my_idx, kv_idx),
                                  [skip, diag, full], None)
        else:
            bo, blse = full(None)
        # lse-weighted combine of normalized outputs (numerically stable:
        # weights are exp of non-positive numbers).
        new_lse = jnp.logaddexp(lse, blse)
        w_old = jnp.exp(lse - new_lse)
        w_new = jnp.exp(blse - new_lse)
        o = o * w_old[..., None] + bo * w_new[..., None]
        k_n = lax.ppermute(k_blk, axis_name, perm)
        v_n = lax.ppermute(v_blk, axis_name, perm)
        i_n = lax.ppermute(kv_idx, axis_name, perm)
        return (k_n, v_n, i_n, o, new_lse), None

    init = (k, v, my_idx, jnp.zeros_like(q),
            jnp.full((b, s_local, h), NEG_INF, jnp.float32))
    (_, _, _, o, lse), _ = lax.scan(body, init, None, length=axis_size)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention(q, k, v, axis_name: Optional[str],
                         causal: bool = True,
                         interpret: bool | None = None):
    """Ring attention with the Pallas flash kernels as the inner block.

    Call inside shard_map with q/k/v the LOCAL sequence blocks
    [B, S_local, H, D]. axis_name=None falls back to the single-device
    flash kernel.
    """
    from deeplearning4j_tpu.parallel import kernels as _k

    if axis_name is None:
        return _k.flash_attention(q, k, v, causal, interpret)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, _ = _ring_flash_fwd_pass(q, k, v, axis_name, causal, interpret)
    return out


def _rfa_fwd(q, k, v, axis_name, causal, interpret):
    from deeplearning4j_tpu.parallel import kernels as _k

    if axis_name is None:
        out, lse = _k._flash_forward(q, k, v, causal,
                                     _k._resolve_interpret(interpret))
        b, s, h, _ = q.shape
        return out, (q, k, v, out, lse.reshape(b, h, s).transpose(0, 2, 1))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, lse = _ring_flash_fwd_pass(q, k, v, axis_name, causal, interpret)
    return out, (q, k, v, out, lse)


def _rfa_bwd(axis_name, causal, interpret, residuals, g):
    q, k, v, o, lse = residuals
    if axis_name is None:
        from deeplearning4j_tpu.parallel import kernels as _k

        return _k._flash_backward(q, k, v, o, _fold_rows(lse), g, causal,
                                  _k._resolve_interpret(interpret))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    # Global softmax-jacobian row correction, once per backward.
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    def body(carry, _):
        k_blk, v_blk, dk_blk, dv_blk, kv_idx, dq = carry
        zeros = (jnp.zeros_like(q), jnp.zeros_like(k_blk),
                 jnp.zeros_like(v_blk))

        def skip(_):
            return zeros

        def diag(_):
            return _flash_block_bwd(q, k_blk, v_blk, g, lse, delta, True,
                                    interpret)

        def full(_):
            return _flash_block_bwd(q, k_blk, v_blk, g, lse, delta, False,
                                    interpret)

        dqc, dkc, dvc = lax.switch(_ring_cases(causal, my_idx, kv_idx),
                                   [skip, diag, full], None)
        # dq accumulates locally; dK/dV accumulate ON the rotating block,
        # so after a full circle each block carries every device's
        # contribution and is back home.
        dq = dq + dqc
        dk_blk = dk_blk + dkc
        dv_blk = dv_blk + dvc
        rot = lambda x: lax.ppermute(x, axis_name, perm)  # noqa: E731
        return (rot(k_blk), rot(v_blk), rot(dk_blk), rot(dv_blk),
                rot(kv_idx), dq), None

    init = (k, v, jnp.zeros_like(k), jnp.zeros_like(v), my_idx,
            jnp.zeros_like(q))
    (_, _, dk, dv, _, dq), _ = lax.scan(body, init, None, length=axis_size)
    return dq, dk, dv


ring_flash_attention.defvjp(_rfa_fwd, _rfa_bwd)
