"""Transformer LM designed for hybrid mesh parallelism.

Out-of-reference extension (SURVEY §2.3 item 3: TP/SP/EP are absent from
the 2015 reference; the task brief makes them first-class here).

Parallelization strategy — the scaling-book recipe, with a deliberate
split between the two JAX mechanisms:

- dp/tp/ep ride **GSPMD**: the model is written over FULL arrays; parameters
  are placed with `param_specs` (heads/hidden/experts sharded over the
  `model` axis, everything else replicated) and activations carry
  `with_sharding_constraint` hints. XLA's SPMD partitioner inserts the
  forward AND backward collectives — which is what makes `jax.grad`
  correct without any hand-rolled psum bookkeeping.
- sp (sequence/context parallelism) is the one place XLA cannot infer the
  algorithm: exact long-context attention needs the ring schedule. That
  inner function — and only it — runs under `shard_map`
  (`ring_attention.py`), whose ppermute transpose is exact, so `jax.grad`
  taken OUTSIDE the shard_map stays correct.

`apply(cfg, params, tokens)` with mesh=None is the identical single-chip
model; tests assert step-for-step equivalence between the two.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.data_parallel import shard_map
from deeplearning4j_tpu.parallel.ring_attention import attention, ring_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 64
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 256
    n_experts: int = 0          # 0 = dense MLP; >0 = MoE
    # Experts per token: 1 = Switch, 2 = GShard-style top-2 (gate weights
    # renormalized over the chosen experts).
    moe_top_k: int = 1
    # Per-expert buffer size as a multiple of tokens/n_experts (Switch
    # Transformer capacity factor).  >0: capacity-based dispatch — each
    # expert computes ONLY its gathered buffer, so MoE FLOPs scale with
    # this factor, not with n_experts.  0: dense-masked compute (every
    # expert sees every token; exact, no drops — the dispatch oracle).
    moe_capacity_factor: float = 1.25
    # Switch load-balancing auxiliary loss weight: aux = E * sum_e f_e*P_e
    # (f_e = dispatch fraction, P_e = mean router prob).  Without it
    # top-1 routing collapses onto few experts and capacity dispatch
    # drops most tokens; lm_loss adds moe_aux_weight * mean-over-layers.
    moe_aux_weight: float = 0.01
    max_len: int = 512
    dtype: str = "float32"
    attn_bias: bool = False     # GPT-2-style q/k/v/o projection biases
    # GPT-2-style weight tying: the LM head is embed.T (no separate head
    # parameter) — at GPT-2-small scale this is the difference between
    # 124M and 163M params.
    tie_embeddings: bool = False
    # Rematerialize each transformer block in the backward pass
    # (jax.checkpoint): activation memory drops from O(L*B*S*d) to the
    # block boundaries, the standard trade for long-context training.
    remat: bool = False

    def __post_init__(self):
        if self.n_experts and not (1 <= self.moe_top_k <= self.n_experts):
            raise ValueError(
                f"moe_top_k={self.moe_top_k} must be in [1, "
                f"n_experts={self.n_experts}]")

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


@dataclass(frozen=True)
class MeshAxes:
    """Which mesh axis carries which parallelism dimension."""

    data: str = "data"
    seq: str = "seq"
    model: str = "model"


def init_params(cfg: TransformerConfig, key: jax.Array) -> dict:
    """Full (unsharded) parameter tree; place with `param_specs`."""
    dt = jnp.dtype(cfg.dtype)
    d, h, dh, f = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    keys = iter(jax.random.split(key, 4 + 8 * cfg.n_layers))

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, dt) / jnp.sqrt(
            jnp.asarray(fan_in, dt)))

    layers = []
    for _ in range(cfg.n_layers):
        layer = {
            "ln1": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
            "ln2": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
            "attn": {
                "wq": dense(next(keys), (d, h, dh), d),
                "wk": dense(next(keys), (d, h, dh), d),
                "wv": dense(next(keys), (d, h, dh), d),
                "wo": dense(next(keys), (h, dh, d), d),
            },
        }
        if cfg.attn_bias:
            layer["attn"].update(
                bq=jnp.zeros((h, dh), dt), bk=jnp.zeros((h, dh), dt),
                bv=jnp.zeros((h, dh), dt), bo=jnp.zeros((d,), dt))
        if cfg.n_experts:
            e = cfg.n_experts
            layer["moe"] = {
                "gate": dense(next(keys), (d, e), d),
                "w1": dense(next(keys), (e, d, f), d),
                "b1": jnp.zeros((e, f), dt),
                "w2": dense(next(keys), (e, f, d), f),
                "b2": jnp.zeros((e, d), dt),
            }
        else:
            layer["mlp"] = {
                "w1": dense(next(keys), (d, f), d),
                "b1": jnp.zeros((f,), dt),
                "w2": dense(next(keys), (f, d), f),
                "b2": jnp.zeros((d,), dt),
            }
        layers.append(layer)
    # Tied configs: the embedding IS the output projection, so it must
    # carry the head's 1/sqrt(d) scale or initial logits blow up to
    # std ~sqrt(d) (initial loss ~70 instead of ln V).  The first block
    # layer-norms its input, so the smaller input-embedding scale is
    # otherwise inert.
    out = {
        "embed": dense(next(keys), (cfg.vocab_size, d),
                       d if cfg.tie_embeddings else 1),
        "pos": dense(next(keys), (cfg.max_len, d), 1) * 0.02,
        "ln_f": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        out["head"] = dense(next(keys), (d, cfg.vocab_size), d)
    return out


def param_specs(cfg: TransformerConfig, model_axis: Optional[str]) -> dict:
    """PartitionSpec tree: tp dims sharded over the model axis, rest
    replicated. wq/wk/wv/wo shard the HEAD dim; mlp the HIDDEN dim; moe
    the EXPERT dim (expert parallelism rides the model axis)."""
    t = model_axis
    layer_spec = {
        "ln1": {"scale": P(), "bias": P()},
        "ln2": {"scale": P(), "bias": P()},
        "attn": {"wq": P(None, t, None), "wk": P(None, t, None),
                 "wv": P(None, t, None), "wo": P(t, None, None)},
    }
    if cfg.attn_bias:
        layer_spec["attn"].update(bq=P(t, None), bk=P(t, None),
                                  bv=P(t, None), bo=P())
    if cfg.n_experts:
        layer_spec["moe"] = {"gate": P(), "w1": P(t, None, None),
                             "b1": P(t, None), "w2": P(t, None, None),
                             "b2": P(t, None)}
    else:
        layer_spec["mlp"] = {"w1": P(None, t), "b1": P(t),
                             "w2": P(t, None), "b2": P()}
    out = {
        "embed": P(),
        "pos": P(),
        "ln_f": {"scale": P(), "bias": P()},
        "layers": [dict(layer_spec) for _ in range(cfg.n_layers)],
    }
    if not cfg.tie_embeddings:
        out["head"] = P()
    return out


def lm_head(params: dict) -> jax.Array:
    """The [d, V] output projection: the explicit head param, or embed.T
    under GPT-2-style weight tying.  Single source of truth for every
    scoring path (apply, decode)."""
    return (params["head"] if "head" in params
            else params["embed"].T)


def gpt2_small(max_len: int = 1024, dtype: str = "bfloat16"
               ) -> TransformerConfig:
    """GPT-2-small-class flagship config: ~124M params with tied
    embeddings (vocab rounded to 50304 for lane-128 tiling), per-block
    remat for long-sequence training.  The scale target of VERDICT r4
    demand #2."""
    return TransformerConfig(
        vocab_size=50304, d_model=768, n_heads=12, n_layers=12,
        d_ff=3072, max_len=max_len, dtype=dtype, attn_bias=True,
        tie_embeddings=True, remat=True)


def gpt2_medium(max_len: int = 1024, dtype: str = "bfloat16"
                ) -> TransformerConfig:
    """GPT-2-medium-class config: ~355M params (1024/16/24), same
    recipe as `gpt2_small` (tied embeddings, lane-128 vocab, remat)."""
    return TransformerConfig(
        vocab_size=50304, d_model=1024, n_heads=16, n_layers=24,
        d_ff=4096, max_len=max_len, dtype=dtype, attn_bias=True,
        tie_embeddings=True, remat=True)


def gpt2_large(max_len: int = 1024, dtype: str = "bfloat16"
               ) -> TransformerConfig:
    """GPT-2-large-class config: ~774M params (1280/20/36).  At this
    scale single-chip training needs accum+remat headroom; the dp/sp/tp
    mesh trainers are the intended path."""
    return TransformerConfig(
        vocab_size=50304, d_model=1280, n_heads=20, n_layers=36,
        d_ff=5120, max_len=max_len, dtype=dtype, attn_bias=True,
        tie_embeddings=True, remat=True)


def _layer_norm(p, x, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * p["scale"] + p["bias"]


def qkv_proj(p, x):
    """[B,S,d] -> q,k,v [B,S,H,K] incl. optional GPT-2-style biases.
    Shared by the training forward and the KV-cached decode path."""
    q = jnp.einsum("bsd,dhk->bshk", x, p["wq"])
    k = jnp.einsum("bsd,dhk->bshk", x, p["wk"])
    v = jnp.einsum("bsd,dhk->bshk", x, p["wv"])
    if "bq" in p:
        q, k, v = q + p["bq"], k + p["bk"], v + p["bv"]
    return q, k, v


def out_proj(p, o):
    """[B,S,H,K] attention output -> [B,S,d] incl. optional bias."""
    out = jnp.einsum("bshk,hkd->bsd", o, p["wo"])
    if "bo" in p:
        out = out + p["bo"]
    return out


def _attn(p, x, mesh: Optional[Mesh], axes: MeshAxes, causal: bool):
    """x:[B,S,d] full arrays. Ring attention under shard_map when a mesh is
    given (seq axis shards S); plain attention otherwise."""
    q, k, v = qkv_proj(p, x)
    if mesh is None:
        from deeplearning4j_tpu.parallel import kernels

        if kernels.flash_enabled():
            o = kernels.flash_attention(q, k, v, causal)
        else:
            o = attention(q, k, v, causal=causal)
    else:
        from deeplearning4j_tpu.parallel import kernels
        from deeplearning4j_tpu.parallel.ring_attention import (
            ring_flash_attention,
        )

        # Pallas inner block on TPU (fused fwd+bwd, O(S/P) memory);
        # plain-jnp blockwise ring elsewhere.
        inner = (ring_flash_attention if kernels.flash_enabled()
                 else ring_attention)
        spec = P(axes.data, axes.seq, axes.model, None)
        ring = shard_map(
            lambda q, k, v: inner(q, k, v, axes.seq, causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_rep=False)
        o = ring(q, k, v)
    return out_proj(p, o)


def _mlp(p, x):
    h = jax.nn.gelu(jnp.einsum("bsd,df->bsf", x, p["w1"]) + p["b1"])
    return jnp.einsum("bsf,fd->bsd", h, p["w2"]) + p["b2"]


def _router_weights(probs, top_k):
    """(top_idx, weights) [..., k].  k=1: the Switch top-1 router prob
    itself; k>1: GShard-style renormalization over the chosen experts."""
    top_p, top_idx = lax.top_k(probs, top_k)
    if top_k > 1:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    return top_idx, top_p


def _moe_dense(p, x, top_k: int = 1):
    """Top-k MoE, dense-masked compute: every expert sees every token and
    the combine weight zeroes non-routed pairs — exact (no capacity
    drops) but O(n_experts) FLOPs.  Kept as the correctness ORACLE for
    `_moe_dispatch` and as the exact inference path; select with
    cfg.moe_capacity_factor = 0."""
    logits = jnp.einsum("bsd,de->bse", x, p["gate"])
    gate_w = jax.nn.softmax(logits, axis=-1)                   # [B,S,E]
    e = p["w1"].shape[0]
    top_idx, w = _router_weights(gate_w, top_k)                # [B,S,k]
    combine = jnp.sum(
        w[..., None] * jax.nn.one_hot(top_idx, e, dtype=x.dtype), axis=-2)
    h = jax.nn.gelu(jnp.einsum("bsd,edf->ebsf", x, p["w1"])
                    + p["b1"][:, None, None, :])
    y = jnp.einsum("ebsf,efd->ebsd", h, p["w2"]) + p["b2"][:, None, None, :]
    return jnp.einsum("ebsd,bse->bsd", y, combine)


def _moe_dispatch(p, x, capacity_factor: float,
                  mesh: Optional[Mesh] = None,
                  axes: MeshAxes = MeshAxes(), top_k: int = 1):
    """Capacity-based top-k dispatch (Switch routing at k=1, GShard-style
    top-2 at k=2; Switch Transformer, Fedus et al. 2021 / GShard, Lepikhin
    et al. 2020 — public formulations): the N*k (token, expert)
    assignments are scattered into a static [E, C, d] buffer with
    C = ceil(capacity_factor * N * k / E), each expert computes ONLY its
    buffer, outputs gather back weighted by the router weight and sum
    over a token's k assignments.  Expert FLOPs therefore scale with the
    capacity factor, NOT with n_experts.  Assignments past an expert's
    capacity (token-major priority: a token's second choice ranks after
    its first) contribute nothing — identity via the surrounding
    residual, the standard drop rule.

    Static shapes throughout (scatter/gather via `.at[]` / advanced
    indexing), so the routing is jit/GSPMD-clean; with a mesh the buffer
    is sharded over the model axis on E, placing each expert's compute
    on its owner (XLA inserts the token all-to-all)."""
    B, S, d = x.shape
    E = p["w1"].shape[0]
    N = B * S
    A = N * top_k                    # total (token, expert) assignments
    C = max(1, min(A, int(math.ceil(capacity_factor * A / E))))  # static
    xf = x.reshape(N, d)
    logits = xf @ p["gate"]                                    # [N,E]
    gate_w = jax.nn.softmax(logits, axis=-1)
    top_idx, top_w = _router_weights(gate_w, top_k)            # [N,k]
    e_flat = top_idx.reshape(-1)                               # [A]
    w_flat = top_w.reshape(-1)
    onehot = jax.nn.one_hot(e_flat, E, dtype=jnp.int32)
    # 0-based slot of each assignment within its expert's buffer
    # (token-major priority), C and above = overflow.
    slot = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=1) - 1
    keep = (slot < C).astype(x.dtype)                          # [A]
    slot = jnp.clip(slot, 0, C - 1)
    x_rep = jnp.repeat(xf, top_k, axis=0)                      # [A, d]
    buf = jnp.zeros((E, C, d), x.dtype).at[e_flat, slot].add(
        x_rep * keep[:, None])

    def constrain(a):
        if mesh is None:
            return a
        return lax.with_sharding_constraint(
            a, NamedSharding(mesh, P(axes.model, None, None)))

    buf = constrain(buf)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", buf, p["w1"])
                    + p["b1"][:, None, :])
    y = jnp.einsum("ecf,efd->ecd", h, p["w2"]) + p["b2"][:, None, :]
    y = constrain(y)
    # Each kept assignment owns its slot exclusively; dropped ones read a
    # foreign slot but are zeroed by `keep`.
    out = y[e_flat, slot] * (w_flat * keep)[:, None]           # [A, d]
    return jnp.sum(out.reshape(N, top_k, d), axis=1).reshape(B, S, d)


def _moe(p, x, capacity_factor: float = 0.0,
         mesh: Optional[Mesh] = None, axes: MeshAxes = MeshAxes(),
         top_k: int = 1):
    """MoE block: capacity-based dispatch when capacity_factor > 0
    (the FLOP-saving default), dense-masked oracle otherwise."""
    if capacity_factor > 0:
        return _moe_dispatch(p, x, capacity_factor, mesh, axes, top_k)
    return _moe_dense(p, x, top_k)


def _moe_aux_loss(p, x):
    """Switch Transformer load-balancing loss (Fedus et al. 2021,
    eq. 4): E * sum_e f_e * P_e over the router's top-1 assignment.
    Minimized (=1) at a uniform assignment; differentiable through P_e."""
    logits = jnp.einsum("bsd,de->bse", x, p["gate"])
    e = p["w1"].shape[0]
    probs = jax.nn.softmax(logits, axis=-1)               # [B,S,E]
    choice = jnp.argmax(logits, axis=-1)                  # [B,S]
    f = jnp.mean(jax.nn.one_hot(choice, e, dtype=x.dtype), axis=(0, 1))
    pbar = jnp.mean(probs, axis=(0, 1))
    return e * jnp.sum(f * pbar)


def apply(cfg: TransformerConfig, params: dict, tokens: jax.Array,
          mesh: Optional[Mesh] = None, axes: MeshAxes = MeshAxes(),
          causal: bool = True, train: bool = False,
          return_aux: bool = False):
    """tokens:[B,S] int32 -> logits [B,S,V]. Pass mesh to parallelize.

    MoE routing: `train=True` (the lm_loss path) uses capacity-based
    dispatch — FLOP-saving but drops overflow tokens, so logits can
    depend on batch composition.  The inference default is the exact
    dense-masked path, keeping scoring deterministic per sequence and
    bit-compatible with the KV-cached `generation.decode_step`.
    `return_aux=True` additionally returns the mean-over-layers Switch
    load-balancing loss (0 for dense configs)."""

    def constrain(a):
        if mesh is None:
            return a
        return lax.with_sharding_constraint(
            a, NamedSharding(mesh, P(axes.data, axes.seq, None)))

    cf = cfg.moe_capacity_factor if train else 0.0

    def block(layer, x):
        x = x + _attn(layer["attn"], _layer_norm(layer["ln1"], x),
                      mesh, axes, causal)
        x = constrain(x)
        h = _layer_norm(layer["ln2"], x)
        if "moe" in layer:
            x = x + _moe(layer["moe"], h, cf, mesh, axes, cfg.moe_top_k)
            aux = _moe_aux_loss(layer["moe"], h)
        else:
            x = x + _mlp(layer["mlp"], h)
            aux = jnp.zeros((), x.dtype)
        return constrain(x), aux

    if cfg.remat:
        block = jax.checkpoint(block)
    x = params["embed"][tokens] + params["pos"][None, :tokens.shape[1], :]
    x = constrain(x)
    auxs = []
    for layer in params["layers"]:
        x, aux = block(layer, x)
        auxs.append(aux)
    x = _layer_norm(params["ln_f"], x)
    logits = jnp.einsum("bsd,dv->bsv", x, lm_head(params))
    if return_aux:
        return logits, jnp.mean(jnp.stack(auxs))
    return logits


def lm_loss(cfg: TransformerConfig, params: dict, tokens: jax.Array,
            targets: jax.Array, mesh: Optional[Mesh] = None,
            axes: MeshAxes = MeshAxes()) -> jax.Array:
    """Mean next-token cross-entropy over the full batch (training mode:
    MoE layers route with capacity-based dispatch + the Switch
    load-balancing auxiliary loss weighted by cfg.moe_aux_weight)."""
    use_aux = bool(cfg.n_experts) and cfg.moe_aux_weight > 0
    out = apply(cfg, params, tokens, mesh, axes, train=True,
                return_aux=use_aux)
    logits, aux = out if use_aux else (out, None)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    loss = jnp.mean(nll)
    if use_aux:
        loss = loss + cfg.moe_aux_weight * aux.astype(loss.dtype)
    return loss
