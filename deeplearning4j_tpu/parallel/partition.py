"""ONE partition-spec vocabulary for the replica axis — and the pure
redistribution primitive built on it.

Before this module every parallel tier spelled its own placement:
`data_parallel.py` wrote inline `P()`/`P("data")` pairs, `mesh.py` had
`replicated`/`batch_sharded` wrappers, `hybrid.py` took raw
`jax.sharding.PartitionSpec` trees.  Checkpoints could not describe HOW a
saved tree was laid out, so a job that died on N replicas could only be
resurrected on exactly N.  This module is the shared foundation
("Automatic Cross-Replica Sharding of Weight Update", arXiv 2004.13336,
motivates one spec for params/updater placement; "Memory-efficient array
redistribution", arXiv 2112.01075, is the N→M primitive):

- `PartitionSpec(axis, dim, size)` — how one pytree leaf relates to the
  replica axis: `axis=None` means replicated (every replica holds the
  full leaf); otherwise tensor dimension `dim` is split across mesh axis
  `axis`, with `size` recording the TRUE global extent along `dim` (the
  pre-padding length, so padded equal shards can be joined bitwise).
- `split_leaf`/`join_leaf` — equal-size splitting with padded-remainder
  handling, and its exact inverse.
- `reshard(tree, spec, n_from, n_to)` — the pure gather→re-split
  redistribution: leaves carried as length-`n_from` shard lists come
  back as length-`n_to` shard lists, bitwise-identical at the full-tree
  level for any N→M.
- `as_jax`/`as_jax_leaf` — bridge to `jax.sharding.PartitionSpec` so the
  SPMD trainers consult THIS vocabulary instead of ad-hoc `P` literals.
- `spec_to_json`/`spec_from_json` — the serialized form checkpoint
  manifests record, so a restore knows the saved topology's layout.

Host-side and dependency-light on purpose: `reshard` runs on numpy
arrays during checkpoint restore, long before any device mesh exists.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import PartitionSpec as JaxP

PyTree = Any

# The keypath vocabulary shared with runtime/checkpoint.py: manifests
# record leaves under these keys and `_spec_leaves` resolves specs
# against them, so there is exactly ONE rendering of a pytree path.
KEYPATH_SEP = "//"


def _path_piece(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def keypath(path) -> str:
    """One pytree keypath (from `tree_flatten_with_path`) rendered as
    the canonical `//`-joined string."""
    return KEYPATH_SEP.join(_path_piece(p) for p in path)


@dataclasses.dataclass(frozen=True)
class PartitionSpec:
    """How one leaf is placed across the replica axis.

    ``axis=None`` (the default): replicated — every replica holds the
    full leaf.  Otherwise tensor dimension ``dim`` is split across mesh
    axis ``axis``; ``size`` is the true global extent along ``dim``
    before any padding (None = unknown/unpadded)."""

    axis: Optional[str] = None
    dim: Optional[int] = None
    size: Optional[int] = None

    @property
    def is_replicated(self) -> bool:
        return self.axis is None or self.dim is None

    def to_json(self) -> dict:
        return {"axis": self.axis, "dim": self.dim, "size": self.size}

    @staticmethod
    def from_json(d: dict) -> "PartitionSpec":
        return PartitionSpec(axis=d.get("axis"), dim=d.get("dim"),
                             size=d.get("size"))


def replicated() -> PartitionSpec:
    return PartitionSpec()


def sharded(axis: str = "data", dim: int = 0,
            size: Optional[int] = None) -> PartitionSpec:
    return PartitionSpec(axis=axis, dim=int(dim), size=size)


def zero1(axis: str = "data", size: Optional[int] = None) -> PartitionSpec:
    """The ZeRO-1 weight-update layout (arXiv 2004.13336): a FLAT
    [padded_extent(k0, n)] moment/master vector split dim-0 over the
    replica axis, each replica owning exactly one 1/N slice.  `size`
    records the PADDED flat length (already a multiple of the axis
    size), so split/join round-trips are trivially exact.  Identical
    placement to `sharded(axis, dim=0, size=size)` — the dedicated name
    is the vocabulary word every ZeRO consumer (DP trainer, pipeline DP
    axis, checkpoint manifests) shares."""
    return sharded(axis, dim=0, size=size)


def is_partition_spec(obj) -> bool:
    return isinstance(obj, PartitionSpec)


def as_jax(spec: PartitionSpec) -> JaxP:
    """The `jax.sharding.PartitionSpec` equivalent of one leaf spec."""
    if spec.is_replicated:
        return JaxP()
    return JaxP(*([None] * spec.dim + [spec.axis]))


def as_jax_leaf(obj) -> JaxP:
    """Normalize either vocabulary (ours or jax's) to a jax spec — the
    seam `hybrid.place_params` consults so spec trees may mix both."""
    if isinstance(obj, JaxP):
        return obj
    if isinstance(obj, PartitionSpec):
        return as_jax(obj)
    raise TypeError(f"not a partition spec: {type(obj).__name__}")


# ---------------------------------------------------------------------------
# leaf-level split/join (padded-remainder handling)

def padded_extent(size: int, n: int) -> int:
    """Smallest multiple of `n` >= `size` (the per-shard extent is
    `padded_extent(size, n) // n`)."""
    if n <= 0:
        raise ValueError(f"shard count must be >= 1, got {n}")
    return ((int(size) + n - 1) // n) * n


def split_leaf(arr, n: int, dim: int = 0) -> List[np.ndarray]:
    """Split `arr` into `n` EQUAL-shaped pieces along `dim`, zero-padding
    the remainder (SPMD replicas need uniform shapes).  `join_leaf` with
    the true size is the exact inverse."""
    arr = np.asarray(arr)
    if arr.ndim == 0:
        raise ValueError("cannot split a 0-d leaf; mark it replicated")
    size = arr.shape[dim]
    total = padded_extent(size, n)
    if total != size:
        pad = [(0, 0)] * arr.ndim
        pad[dim] = (0, total - size)
        arr = np.pad(arr, pad)
    return [np.ascontiguousarray(piece)
            for piece in np.split(arr, n, axis=dim)]


def join_leaf(shards: Sequence[np.ndarray], dim: int = 0,
              size: Optional[int] = None) -> np.ndarray:
    """Concatenate shards along `dim` and strip trailing padding down to
    the true `size` (None = shards were unpadded)."""
    full = np.concatenate([np.asarray(s) for s in shards], axis=dim)
    if size is not None and full.shape[dim] != size:
        if full.shape[dim] < size:
            raise ValueError(
                f"shards join to extent {full.shape[dim]} along dim "
                f"{dim}, smaller than the recorded size {size}")
        full = np.take(full, np.arange(int(size)), axis=dim)
    return full


def _is_shard_list(x) -> bool:
    return (isinstance(x, (list, tuple)) and len(x) > 0
            and all(isinstance(a, (np.ndarray, np.generic))
                    or hasattr(a, "__array__") for a in x))


def _spec_leaves(tree: PyTree, spec) -> PyTree:
    """Resolve `spec` to a pytree matching `tree`: a single
    PartitionSpec broadcasts over every leaf; a flat {keypath:
    PartitionSpec} map (the `spec_from_json` form) is looked up per
    leaf keypath (missing keys raise); anything else is assumed to be a
    structurally matching spec pytree."""
    if isinstance(spec, PartitionSpec):
        return jax.tree_util.tree_map(lambda _: spec, tree,
                                      is_leaf=_is_shard_list)
    if (isinstance(spec, dict) and spec
            and all(isinstance(k, str) and is_partition_spec(v)
                    for k, v in spec.items())):
        # flat keypath map (what a checkpoint manifest deserializes to)
        flat, treedef = jax.tree_util.tree_flatten_with_path(
            tree, is_leaf=_is_shard_list)
        leaves = []
        for path, _leaf in flat:
            key = keypath(path)
            ps = spec.get(key)
            if ps is None:
                raise ValueError(
                    f"partition spec has no entry for leaf {key!r} "
                    f"(known: {sorted(spec)[:8]}...)")
            leaves.append(ps)
        return jax.tree_util.tree_unflatten(treedef, leaves)
    return spec


def shard_tree(tree: PyTree, spec, n: int) -> PyTree:
    """Split every leaf of `tree` into a length-`n` shard list per its
    spec (replicated leaves become `n` references to the same array)."""
    spec_tree = _spec_leaves(tree, spec)

    def split(leaf, ps: PartitionSpec):
        arr = np.asarray(leaf)
        if ps.is_replicated or arr.ndim == 0:
            return [arr] * n
        return split_leaf(arr, n, ps.dim)

    return jax.tree_util.tree_map(split, tree, spec_tree)


def gather_tree(tree: PyTree, spec) -> PyTree:
    """Inverse of `shard_tree`: join every shard-list leaf back into the
    full array (replicated leaves take shard 0)."""
    spec_tree = _spec_leaves(tree, spec)

    def join(shards, ps: PartitionSpec):
        if not _is_shard_list(shards):
            return np.asarray(shards)
        if ps.is_replicated:
            return np.asarray(shards[0])
        return join_leaf(shards, ps.dim, ps.size)

    return jax.tree_util.tree_map(join, tree, spec_tree,
                                  is_leaf=_is_shard_list)


def reshard(tree: PyTree, spec, n_from: int, n_to: int) -> PyTree:
    """The pure redistribution primitive (arXiv 2112.01075): a tree
    whose leaves are length-`n_from` shard lists (the layout a checkpoint
    saved on `n_from` replicas restores to) comes back with
    length-`n_to` shard lists — gather along each leaf's spec'd dim
    (stripping padding via the spec's true size), then re-split padded
    for `n_to`.  Bitwise-identical at the full-tree level: gathering the
    result reproduces the original arrays exactly, for any N→M
    (including N→1 and 1→M).  Replicated leaves are never copied, just
    re-referenced `n_to` times.

    `spec` is a single `PartitionSpec` (broadcast over every leaf) or a
    matching pytree of them.  Bare-array leaves are treated as the
    already-gathered full value."""
    if n_from < 1 or n_to < 1:
        raise ValueError(f"replica counts must be >= 1, got "
                         f"{n_from}→{n_to}")
    spec_tree = _spec_leaves(tree, spec)

    def redistribute(shards, ps: PartitionSpec):
        if _is_shard_list(shards):
            if len(shards) != n_from:
                raise ValueError(
                    f"leaf carries {len(shards)} shards, expected "
                    f"n_from={n_from}")
            full = (np.asarray(shards[0]) if ps.is_replicated
                    else join_leaf(shards, ps.dim, ps.size))
        else:
            full = np.asarray(shards)
        if ps.is_replicated or full.ndim == 0:
            return [full] * n_to
        return split_leaf(full, n_to, ps.dim)

    return jax.tree_util.tree_map(redistribute, tree, spec_tree,
                                  is_leaf=_is_shard_list)


# ---------------------------------------------------------------------------
# serialization (the checkpoint-manifest form)

def spec_to_json(spec) -> Dict[str, dict]:
    """Flatten a spec (single PartitionSpec or pytree of them) to the
    JSON form checkpoint manifests record: keypath -> leaf-spec dict,
    with the single-spec broadcast stored under "*"."""
    if isinstance(spec, PartitionSpec):
        return {"*": spec.to_json()}
    flat = jax.tree_util.tree_flatten_with_path(
        spec, is_leaf=is_partition_spec)[0]
    return {keypath(path): leaf.to_json() for path, leaf in flat}


def spec_from_json(d: Dict[str, dict]):
    """Inverse of `spec_to_json`: "*" gives back the broadcast single
    spec; otherwise a flat {keypath: PartitionSpec} map, which
    `reshard`/`shard_tree`/`gather_tree` resolve per leaf keypath (see
    `_spec_leaves`) — so a manifest-recorded spec drives a reshard
    directly."""
    if set(d) == {"*"}:
        return PartitionSpec.from_json(d["*"])
    return {k: PartitionSpec.from_json(v) for k, v in d.items()}
