"""Request tracing: where did this one slow request spend its time.

Every request carries an ``X-Request-Id`` (client-supplied or minted at
the first hop) that propagates across the fleet — the ``FleetRouter``
forwards it on failover resubmission, so a replica killed mid-storm
yields ONE trace whose spans name both the failed and the succeeding
replica, and the replica-side serving planes record their own spans
under the SAME id (queue wait, dispatch, device compute).

- `new_request_id()` — 16-hex-char id.
- `span(name, t0, t1, **attrs)` — one completed span (perf_counter
  seconds; monotonic and process-wide comparable).
- `TraceRecorder` — bounded ring buffer of completed traces (oldest
  evicted), queried by ``recent()``/``find()`` and served at
  ``GET /trace/recent``.
- `chrome_trace(traces)` — Chrome trace-event JSON (Perfetto-loadable:
  load the array in https://ui.perfetto.dev or chrome://tracing).  Each
  trace renders as one track (tid = hash of its request id) of "X"
  (complete) events; ``jax.monitoring`` compile events attached by the
  serving planes appear as ``xla_compile`` spans inside the request
  that paid for them.

Stdlib-only, like the rest of obs/.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import zlib
from typing import Dict, List, Optional

# Request ids are a random per-process prefix plus an atomic counter:
# unique across processes (64 random bits) and ~50x cheaper than
# uuid4() — the id mint sits on the serving hot path, where the bench
# `obs` row budgets the whole observability plane at 3%.
_ID_PREFIX = os.urandom(8).hex()
_ID_COUNTER = itertools.count()


def new_request_id() -> str:
    return f"{_ID_PREFIX}{next(_ID_COUNTER):08x}"


def span(name: str, t0: float, t1: float, **attrs) -> Dict:
    """One completed span: perf_counter start/duration + free attrs."""
    s = {"name": str(name), "t0_s": float(t0),
         "dur_s": max(0.0, float(t1) - float(t0))}
    if attrs:
        s["attrs"] = {k: v for k, v in attrs.items() if v is not None}
    return s


def trace(request_id: str, kind: str, spans: List[Dict],
          status: str = "ok", **attrs) -> Dict:
    """One completed trace.  ``spans`` are `span()` dicts; ``status`` is
    "ok" or an error word ("error", "timeout", "shed", ...)."""
    spans = sorted(spans, key=lambda s: s["t0_s"])
    t0 = spans[0]["t0_s"] if spans else time.perf_counter()
    t1 = max((s["t0_s"] + s["dur_s"] for s in spans), default=t0)
    out = {"request_id": str(request_id), "kind": str(kind),
           "status": str(status), "t0_s": t0,
           "dur_s": t1 - t0, "wall_time": time.time(), "spans": spans}
    if attrs:
        out["attrs"] = {k: v for k, v in attrs.items() if v is not None}
    return out


class TraceRecorder:
    """Thread-safe bounded ring of completed traces."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._traces: List[Dict] = []
        self._recorded = 0

    def record(self, tr: Dict) -> None:
        with self._lock:
            self._traces.append(tr)
            self._recorded += 1
            if len(self._traces) > self.capacity:
                del self._traces[:len(self._traces) - self.capacity]

    def record_lazy(self, builder, raw) -> None:
        """Hot-path variant: store ``(builder, raw)`` and materialize
        ``builder(raw)`` only when the ring is READ.  The serving
        batcher's per-request trace assembly (span/trace dict builds)
        thereby costs the request one tuple append instead of ~10 dict
        allocations — the bench `obs` row's 3% budget is why."""
        self.record((builder, raw))

    @staticmethod
    def _materialize(entry) -> Dict:
        if isinstance(entry, tuple):
            builder, raw = entry
            return builder(raw)
        return entry

    @property
    def recorded(self) -> int:
        """Lifetime count (the ring holds at most ``capacity``)."""
        with self._lock:
            return self._recorded

    def recent(self, n: Optional[int] = None,
               request_id: Optional[str] = None) -> List[Dict]:
        """Newest-last; optionally filtered by request id."""
        with self._lock:
            out = list(self._traces)
        out = [self._materialize(t) for t in out]
        if request_id is not None:
            out = [t for t in out if t.get("request_id") == request_id]
        if n is not None:
            out = out[-int(n):]
        return out

    def find(self, request_id: str) -> List[Dict]:
        return self.recent(request_id=request_id)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


def _tid(request_id: str) -> int:
    return zlib.crc32(request_id.encode()) & 0x7FFFFFFF


def chrome_trace(traces: List[Dict]) -> List[Dict]:
    """Chrome trace-event array: one "X" (complete) event per span, all
    requests on pid 1 with one thread per request id.  Timestamps are
    perf_counter microseconds — relative ordering within a process is
    exact, which is what the span taxonomy needs."""
    events: List[Dict] = []
    for tr in traces:
        tid = _tid(tr.get("request_id", ""))
        meta = {"request_id": tr.get("request_id"),
                "status": tr.get("status")}
        meta.update(tr.get("attrs") or {})
        events.append({
            "name": f"{tr.get('kind', 'request')}",
            "cat": tr.get("kind", "request"), "ph": "X",
            "ts": tr["t0_s"] * 1e6, "dur": tr["dur_s"] * 1e6,
            "pid": 1, "tid": tid, "args": meta})
        for s in tr.get("spans", ()):
            events.append({
                "name": s["name"], "cat": tr.get("kind", "request"),
                "ph": "X", "ts": s["t0_s"] * 1e6, "dur": s["dur_s"] * 1e6,
                "pid": 1, "tid": tid, "args": s.get("attrs", {})})
    return events
