"""Standalone metrics endpoint for the training plane.

The serving fronts (`ui/server.py`, `serving/fleet.py`) mount
``/metrics`` and ``/trace/recent`` on their existing HTTP surface; a
training run has no server, so ``dl4j train -metrics-port N`` starts
this one: a tiny stdlib HTTP server exposing

- ``GET /metrics``  — Prometheus text exposition of the run's registry
- ``GET /healthz``  — liveness
- ``GET /trace/recent`` — recent traces (when a recorder is attached)

Deliberately dependency-free (no serving imports): the training plane
must be scrapeable even in an environment where the serving stack never
loads.  ``port=0`` picks a free port (tests).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deeplearning4j_tpu.obs.registry import (
    EXPOSITION_CONTENT_TYPE,
    MetricsRegistry,
)
from deeplearning4j_tpu.obs.trace import TraceRecorder, chrome_trace


class _MetricsHTTPServer(ThreadingHTTPServer):
    allow_reuse_address = True
    daemon_threads = True


class _MetricsHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # silence stderr
        pass

    def _send(self, code: int, ctype: str, data: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802
        registry = self.server.obs_registry  # type: ignore[attr-defined]
        tracer = self.server.obs_tracer      # type: ignore[attr-defined]
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            self._send(200, EXPOSITION_CONTENT_TYPE,
                       registry.exposition().encode())
        elif path == "/healthz":
            self._send(200, "application/json", b'{"ok": true}')
        elif path == "/trace/recent" and tracer is not None:
            traces = tracer.recent()
            if "format=chrome" in query:
                body = json.dumps(chrome_trace(traces)).encode()
            else:
                body = json.dumps({"traces": traces}).encode()
            self._send(200, "application/json", body)
        else:
            self._send(404, "application/json",
                       json.dumps({"error": f"unknown path {path}"})
                       .encode())


class MetricsServer:
    """``MetricsServer(registry, port=0).start()``; ``.url``; ``.stop()``."""

    def __init__(self, registry: MetricsRegistry,
                 host: str = "127.0.0.1", port: int = 0,
                 tracer: Optional[TraceRecorder] = None):
        self._server = _MetricsHTTPServer((host, port), _MetricsHandler)
        self._server.obs_registry = registry  # type: ignore[attr-defined]
        self._server.obs_tracer = tracer      # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="obs-metrics")

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
