"""Unified observability plane (ISSUE-8).

One measurement substrate for the whole system — "what is this system
doing right now, and where did this one slow request spend its time":

- `registry` — thread-safe Counter/Gauge/Histogram + `MetricsRegistry`
  with Prometheus text exposition (served at ``GET /metrics`` by the
  UI server, the fleet front, and `MetricsServer`);
- `trace` — request ids, the bounded `TraceRecorder` ring behind
  ``GET /trace/recent``, and Chrome trace-event (Perfetto-loadable)
  export; request ids propagate across the fleet via ``X-Request-Id``;
- `compilewatch` — first-class ``compiles_total{program_key=...}``
  fed by ``jax.monitoring`` compile events, plus the recent-event ring
  the tracer uses to attach ``xla_compile`` spans to the request that
  paid for an off-ladder recompile;
- `telemetry` — `TrainingTelemetry`, the listener-slot feed for step
  time, examples/sec, grad norm, loss-scale grow/backoff events and
  supervisor interventions (``dl4j train -metrics-port``);
- `http` — `MetricsServer`, the standalone training-plane endpoint.

See docs/observability.md for the metric catalog, the trace span
taxonomy and a scrape quickstart.
"""

from deeplearning4j_tpu.obs.compilewatch import (
    COMPILE_EVENT,
    CompileWatcher,
    compile_scope,
    compile_watcher,
)
from deeplearning4j_tpu.obs.http import MetricsServer
from deeplearning4j_tpu.obs.registry import (
    EXPOSITION_CONTENT_TYPE,
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS,
    MetricsRegistry,
    STEP_TIME_BUCKETS,
)
from deeplearning4j_tpu.obs.telemetry import TrainingTelemetry
from deeplearning4j_tpu.obs.trace import (
    TraceRecorder,
    chrome_trace,
    new_request_id,
    span,
    trace,
)

__all__ = [
    "COMPILE_EVENT",
    "CompileWatcher",
    "Counter",
    "EXPOSITION_CONTENT_TYPE",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS",
    "MetricsRegistry",
    "MetricsServer",
    "STEP_TIME_BUCKETS",
    "TraceRecorder",
    "TrainingTelemetry",
    "chrome_trace",
    "compile_scope",
    "compile_watcher",
    "new_request_id",
    "span",
    "trace",
]
