"""First-class XLA compile accounting (ISSUE-8 satellite).

The zero-recompile storm tests always pinned compile counts via
hand-rolled ``jax.monitoring`` listeners; production had no equivalent.
`CompileWatcher` makes the counter first-class: one process-wide
listener on ``/jax/core/compile/backend_compile_duration`` feeding

- ``compiles_total{program_key=...}`` — a per-program-key counter.  The
  key is whatever `compile_scope(key)` is active on the COMPILING thread
  (the serving engine scopes each dispatch/warmup with its ladder shape,
  the LM pool with its step width), so an off-ladder recompile shows up
  under the key of the exact program that paid for it; unscoped
  compiles land under ``""``.
- a bounded ring of recent compile events ``(t_end, duration, key)`` so
  the request tracer can attach an ``xla_compile`` span to the request
  whose dispatch window the compile landed in.

The watcher survives ``jax.monitoring.clear_event_listeners()`` (tests
use it liberally): `ensure_installed()` re-registers when the listener
list no longer contains us, and every read path calls it.

jax is imported lazily — importing this module costs nothing.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_scope: contextvars.ContextVar = contextvars.ContextVar(
    "dl4j_compile_scope", default="")


@contextlib.contextmanager
def compile_scope(key: str):
    """Attribute any XLA compile triggered by this thread inside the
    block to ``program_key=key`` (contextvars: thread/task local)."""
    token = _scope.set(str(key))
    try:
        yield
    finally:
        _scope.reset(token)


class CompileWatcher:
    """Process-wide compile-event counter + recent-event ring."""

    def __init__(self, recent: int = 512):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._total_duration = 0.0
        self._events = collections.deque(maxlen=recent)  # (t_end, dur, key)
        self._installed = False   # fallback guard when jax's listener
        #                           list cannot be introspected

    # ---- listener ---------------------------------------------------------

    def _listener(self, event: str, duration: float, **kw) -> None:
        if event != COMPILE_EVENT:
            return
        key = _scope.get()
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._total_duration += float(duration)
            self._events.append((time.perf_counter(), float(duration), key))

    def ensure_installed(self) -> None:
        """Register the jax.monitoring listener; safe to call anywhere
        (idempotent, and re-installs after clear_event_listeners).

        The membership check MUST consult the listener list: the public
        ``jax.monitoring`` module does not re-export
        ``get_event_duration_listeners`` (only ``jax._src.monitoring``
        has it), and a getattr miss that silently skips the check would
        register a duplicate listener on EVERY call — each compile then
        counts once per listener and every /metrics scrape leaks one
        more.  When no introspection exists at all, fall back to a
        register-once flag (loses clear_event_listeners survival, never
        double-counts)."""
        import jax.monitoring as monitoring

        get = getattr(monitoring, "get_event_duration_listeners", None)
        if get is None:
            try:
                from jax._src import monitoring as src_monitoring

                get = getattr(src_monitoring,
                              "get_event_duration_listeners", None)
            except ImportError:
                get = None
        if get is not None:
            if self._listener in get():
                return
        elif self._installed:
            return
        monitoring.register_event_duration_secs_listener(self._listener)
        self._installed = True

    # ---- reading ----------------------------------------------------------

    def total(self, prefix: Optional[str] = None) -> int:
        """Compiles observed, optionally only for keys with `prefix`."""
        with self._lock:
            if prefix is None:
                return sum(self._counts.values())
            return sum(c for k, c in self._counts.items()
                       if k.startswith(prefix))

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def any_since(self, t: float) -> bool:
        """O(1) hot-path guard: did ANY compile end at/after `t`?  The
        tracer checks this before paying for `events_between` — on a
        warmed serving path it is False for every request."""
        # deliberately lock-free (this runs per REQUEST on the trace
        # path): deque ops are GIL-atomic, and the one observable race
        # — reading [-1] while a bounded rotation empties it — is
        # caught below and answered conservatively
        events = self._events  # noqa: LCK101 — lock-free hot-path guard, race handled
        if not events:
            return False
        try:
            return events[-1][0] >= t
        except IndexError:   # raced a rotation of the bounded deque
            return True

    def events_between(self, t0: float, t1: float
                       ) -> List[Tuple[float, float, str]]:
        """Compile events whose [start, end] overlaps [t0, t1] (perf
        seconds) — the tracer's 'which request paid for this compile'."""
        with self._lock:
            events = list(self._events)
        out = []
        for t_end, dur, key in events:
            if t_end - dur <= t1 and t_end >= t0:
                out.append((t_end, dur, key))
        return out

    def collector_samples(self) -> Iterable[Tuple]:
        """`MetricsRegistry.register_collector` source: one
        ``compiles_total`` sample per program key plus the cumulative
        compile seconds."""
        self.ensure_installed()
        with self._lock:
            counts = dict(self._counts)
            dur = self._total_duration
        for key, c in sorted(counts.items()):
            yield ("compiles_total", "counter",
                   "XLA backend compiles observed via jax.monitoring",
                   {"program_key": key}, float(c))
        yield ("compile_seconds_total", "counter",
               "cumulative XLA backend compile time", {}, dur)


_watcher: Optional[CompileWatcher] = None
_watcher_lock = threading.Lock()


def compile_watcher() -> CompileWatcher:
    """The process-wide watcher, installed on first use."""
    global _watcher
    with _watcher_lock:
        if _watcher is None:
            _watcher = CompileWatcher()
    _watcher.ensure_installed()
    return _watcher
