"""Metrics registry: one process-wide vocabulary of counters, gauges and
histograms, rendered as Prometheus text exposition at ``GET /metrics``.

The reference DL4J's only observation hook was the ``IterationListener``
SPI (SURVEY §5 — "no profiling subsystem"); our reproduction then grew
ad-hoc counters per subsystem: ``ServingMetrics`` dicts behind
``/serving/stats``, a hand-rolled ``fleet_stats()`` aggregator,
``StepTimer``/``LatencyRecorder`` in ``runtime/profiler.py``, and
compile counts that lived only inside tests.  This module is the one
measurement substrate they all re-register into (ISSUE-8):

- `Counter` / `Gauge` / `Histogram` — thread-safe metric primitives.
  Each instance stands alone (a ``ServingMetrics`` owns its own set and
  reads them for ``/serving/stats``); *registering* one into a
  `MetricsRegistry` additionally publishes it on ``/metrics`` under a
  label set (``plane="classifier"``, ``plane="lm"``, ``plane="fleet"``),
  so the stats endpoints and the scrape endpoint render the SAME
  underlying cells — no parallel snapshot dicts.
- `MetricsRegistry` — the per-server collection: ``register``/
  ``counter``/``gauge``/``histogram`` plus ``register_collector`` for
  sources whose sample set is dynamic (per-replica fleet gauges, the
  per-program-key compile counter).  ``exposition()`` renders the
  Prometheus text format (# HELP / # TYPE / samples, histogram
  ``_bucket``/``_sum``/``_count`` with cumulative ``le`` labels).

Stays stdlib-only so the HTTP layers can import it without pulling in
numpy/jax.  docs/observability.md has the metric catalog.
"""

from __future__ import annotations

import math
import re
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Default buckets for request-latency histograms (seconds).  Chosen to
# straddle the serving plane's observed range: sub-ms dispatch overhead
# up through multi-second overload tails.
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# Wider buckets for training-step times (seconds): steps span ~ms (tiny
# CPU nets) to minutes (flagship chunks).
STEP_TIME_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                     10.0, 30.0, 60.0, 300.0)


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


class Counter:
    """Monotonic counter.  ``inc()`` from any thread; ``value`` to read."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Set-to-current-value gauge.  ``fn`` makes it a callback gauge:
    the value is computed at read/scrape time (e.g. uptime)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None):
        self.name = _check_name(name)
        self.help = help
        self._fn = fn
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram (Prometheus semantics: cumulative ``le``
    buckets plus ``_sum``/``_count``).  ``summary()`` additionally
    estimates percentiles by linear interpolation inside the bucket —
    coarse next to an exact reservoir, but free at any volume, which is
    what a scraped histogram is for."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = LATENCY_BUCKETS):
        self.name = _check_name(name)
        self.help = help
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs or any(b <= 0 for b in bs if math.isfinite(b)):
            raise ValueError(f"histogram {name}: buckets must be positive")
        self.buckets = bs                      # finite upper bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bs) + 1)     # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        i = 0
        while i < len(self.buckets) and v > self.buckets[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le, cumulative_count)] including the +Inf bucket."""
        with self._lock:
            counts = list(self._counts)
        out, running = [], 0
        for le, c in zip(self.buckets + (math.inf,), counts):
            running += c
            out.append((le, running))
        return out

    def _quantile_locked(self, counts: List[int], q: float) -> float:
        total = sum(counts)
        if total == 0:
            return 0.0
        rank = q * total
        running = 0.0
        lo = 0.0
        for le, c in zip(self.buckets + (math.inf,), counts):
            if running + c >= rank:
                if not math.isfinite(le):
                    return lo                    # best lower bound
                frac = (rank - running) / c if c else 0.0
                return lo + (le - lo) * frac
            running += c
            lo = le
        return lo

    def summary(self) -> Dict[str, float]:
        """{count, mean, p50, p95, p99} in the observed unit (estimates
        interpolated from the bucket boundaries; empty -> {count: 0})."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        if total == 0:
            return {"count": 0}
        return {"count": total,
                "mean": s / total,
                "p50": self._quantile_locked(counts, 0.50),
                "p95": self._quantile_locked(counts, 0.95),
                "p99": self._quantile_locked(counts, 0.99)}


# One collector sample: (name, kind, help, labels, value).  Histograms
# from collectors are not supported — register the Histogram object.
Sample = Tuple[str, str, str, Dict[str, str], float]


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 2 ** 53 else repr(f)


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace(
            "\n", r"\n").replace('"', r'\"')
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


class MetricsRegistry:
    """A server's published metric set.

    ``register(metric, **labels)`` publishes a metric instance under a
    label set; re-registering the same (name, labels) REPLACES the old
    instance — a rolling weight swap's fresh engine takes over its
    predecessor's series instead of double-reporting.  Metrics with the
    same name but different labels render as one family (kind/help must
    agree).  ``register_collector(fn)`` adds a callable returning
    `Sample` tuples evaluated at scrape time.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # (name, sorted-label-items) -> (metric, labels)
        self._metrics: Dict[Tuple, Tuple[object, Dict[str, str]]] = {}
        self._collectors: List[Callable[[], Iterable[Sample]]] = []
        self._created = time.monotonic()

    # ---- registration -----------------------------------------------------

    def register(self, metric, **labels):
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name {k!r}")
        labels = {k: str(v) for k, v in labels.items()}
        key = (metric.name, tuple(sorted(labels.items())))
        with self._lock:
            for (name, _), (m, _l) in self._metrics.items():
                if name == metric.name and m.kind != metric.kind:
                    raise ValueError(
                        f"metric {name} already registered as {m.kind}, "
                        f"cannot re-register as {metric.kind}")
            self._metrics[key] = (metric, labels)
        return metric

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self.register(Counter(name, help), **labels)

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None, **labels) -> Gauge:
        return self.register(Gauge(name, help, fn=fn), **labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = LATENCY_BUCKETS,
                  **labels) -> Histogram:
        return self.register(Histogram(name, help, buckets=buckets),
                             **labels)

    def register_collector(self,
                           fn: Callable[[], Iterable[Sample]]) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    @property
    def uptime_s(self) -> float:
        return time.monotonic() - self._created

    # ---- rendering --------------------------------------------------------

    def _families(self):
        """name -> {kind, help, entries: [(labels, metric_or_value)]},
        static registrations first, then collector samples."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        fams: Dict[str, Dict] = {}
        for metric, labels in metrics:
            fam = fams.setdefault(metric.name, {
                "kind": metric.kind, "help": metric.help, "entries": []})
            fam["entries"].append((labels, metric))
        for fn in collectors:
            for name, kind, help, labels, value in fn():
                fam = fams.setdefault(name, {
                    "kind": kind, "help": help, "entries": []})
                fam["entries"].append((dict(labels), float(value)))
        return fams

    def collect(self) -> Dict[str, Dict]:
        """Snapshot view for tests/JSON: name -> {kind, help, samples:
        [(labels, value)]} (histograms sample their count)."""
        out = {}
        for name, fam in self._families().items():
            samples = []
            for labels, entry in fam["entries"]:
                v = entry if isinstance(entry, float) else (
                    entry.count if isinstance(entry, Histogram)
                    else entry.value)
                samples.append((labels, v))
            out[name] = {"kind": fam["kind"], "help": fam["help"],
                         "samples": samples}
        return out

    def exposition(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        fams = self._families()
        for name in sorted(fams):
            fam = fams[name]
            if fam["help"]:
                esc = fam["help"].replace("\\", r"\\").replace("\n", r"\n")
                lines.append(f"# HELP {name} {esc}")
            lines.append(f"# TYPE {name} {fam['kind']}")
            for labels, entry in fam["entries"]:
                if isinstance(entry, Histogram):
                    for le, c in entry.cumulative():
                        ll = dict(labels)
                        ll["le"] = _fmt_value(le)
                        lines.append(
                            f"{name}_bucket{_fmt_labels(ll)} {c}")
                    lines.append(f"{name}_sum{_fmt_labels(labels)} "
                                 f"{_fmt_value(entry.sum)}")
                    lines.append(f"{name}_count{_fmt_labels(labels)} "
                                 f"{entry.count}")
                else:
                    v = entry if isinstance(entry, float) else entry.value
                    lines.append(
                        f"{name}{_fmt_labels(labels)} {_fmt_value(v)}")
        return "\n".join(lines) + "\n"


EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
