"""Training telemetry: the training plane's feed into the registry.

`TrainingTelemetry` sits in the same listener slot as
`ScoreIterationListener` (``net.add_listener(...)``) and is chunk-aware
by construction: it declares a ``sync_interval`` so off-interval steps
never force the loss to the host, and it is a model-reading listener
(``score_only = False``) so under the fused chunk driver it fires only
at chunk boundaries — where the live model state matches the iteration
label (see ``MultiLayerNetwork._fire_chunk_listeners``).

What it feeds (all `obs.registry` metrics, readable standalone or
published on ``/metrics`` via ``register_into``):

- ``train_steps_total`` / ``train_loss`` / ``train_step_seconds``
  (histogram) / ``train_examples_per_sec`` — step accounting;
- ``train_grad_norm`` — the runner's listener-synced gradient norm;
- ``train_loss_scale`` + ``train_loss_scale_grow_total`` /
  ``train_loss_scale_backoff_total`` — the precision plane's dynamic
  loss-scale automaton transitions (grow = scale increased, backoff =
  overflow steps skipped), read from ``model.scaler_stats()``;
- ``train_rollbacks_total`` / ``train_poison_skips_total`` /
  ``train_preemptions_total`` / ``train_checkpoints_total`` — supervisor
  interventions (`TrainingSupervisor(..., telemetry=...)` calls
  `record_intervention`).

`snapshot()` returns the whole set as a plain dict — the supervisor
embeds it in every checkpoint manifest (``meta.json`` ``extra``), so a
resumed run can see what its predecessor's training plane looked like.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    STEP_TIME_BUCKETS,
)

# The intervention vocabulary (supervisor -> counter):
INTERVENTIONS = ("rollback", "poison_skip", "preemption", "checkpoint")


class TrainingTelemetry:
    """Iteration listener feeding training metrics into the registry.

    ``sync_interval`` gates host syncs exactly like
    `ScoreIterationListener`; ``batch_size`` (when known) turns step
    times into examples/sec.  Thread-safe: the listener fires on the
    training thread, `record_intervention` on whatever thread the
    supervisor runs on, and ``/metrics`` scrapes concurrently.
    """

    score_only = False      # chunk-aware: fire at chunk boundaries only

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 sync_interval: int = 10,
                 batch_size: Optional[int] = None, job: str = "train"):
        self.sync_interval = max(1, int(sync_interval))
        self.batch_size = batch_size
        self.job = str(job)
        self._lock = threading.Lock()
        self._last_t: Optional[float] = None
        # iteration baseline 0: attach the listener BEFORE training so
        # the first firing (iteration k under chunking) counts its
        # whole k-step delta
        self._last_it = 0
        self._last_scale: Optional[float] = None
        self._last_overflows = 0
        self.steps_total = Counter(
            "train_steps_total", "optimizer steps observed")
        self.loss = Gauge("train_loss", "last listener-synced loss")
        self.step_time = Histogram(
            "train_step_seconds", "wall-clock per optimizer step",
            buckets=STEP_TIME_BUCKETS)
        self.examples_per_sec = Gauge(
            "train_examples_per_sec", "examples/sec over the last "
            "listener window")
        self.grad_norm = Gauge(
            "train_grad_norm", "last listener-synced gradient norm")
        self.loss_scale = Gauge(
            "train_loss_scale", "dynamic loss scale (precision plane)")
        self.loss_scale_grow = Counter(
            "train_loss_scale_grow_total", "loss-scale grow transitions")
        self.loss_scale_backoff = Counter(
            "train_loss_scale_backoff_total",
            "loss-scale backoff transitions (overflow steps skipped)")
        self.interventions = {
            kind: Counter(f"train_{kind}s_total",
                          f"supervisor {kind} interventions")
            for kind in INTERVENTIONS}
        if registry is not None:
            self.register_into(registry)

    def register_into(self, registry: MetricsRegistry,
                      **labels) -> "TrainingTelemetry":
        labels.setdefault("job", self.job)
        for m in (self.steps_total, self.loss, self.step_time,
                  self.examples_per_sec, self.grad_norm, self.loss_scale,
                  self.loss_scale_grow, self.loss_scale_backoff,
                  *self.interventions.values()):
            registry.register(m, **labels)
        return self

    # ---- the listener slot ------------------------------------------------

    def iteration_done(self, model, iteration: int, score: float) -> None:
        now = time.perf_counter()
        with self._lock:
            last_t, last_it = self._last_t, self._last_it
            self._last_t, self._last_it = now, int(iteration)
        steps = (int(iteration) - last_it if iteration > last_it
                 else self.sync_interval)   # rollback replay: count anew
        self.steps_total.inc(steps)
        self.loss.set(float(score))
        if last_t is not None and steps > 0:
            per_step = max(1e-9, (now - last_t) / steps)
            self.step_time.observe(per_step)
            if self.batch_size:
                self.examples_per_sec.set(self.batch_size / per_step)
        gn = getattr(model, "last_grad_norm", None)
        if gn is not None:
            # already host-synced by the listener machinery's due gate
            self.grad_norm.set(float(gn))
        stats = None
        get_stats = getattr(model, "scaler_stats", None)
        if callable(get_stats):
            stats = get_stats()
        if stats:
            self.observe_scaler(stats)

    def observe_scaler(self, stats: Dict) -> None:
        """Fold one ``scaler_stats()`` reading into the grow/backoff
        event counters (a scale increase is a grow; each new overflow
        step is a backoff)."""
        scale = float(stats.get("scale", 0.0))
        overflows = int(stats.get("overflow_count", 0))
        with self._lock:
            last_scale = self._last_scale
            last_overflows = self._last_overflows
            self._last_scale = scale
            self._last_overflows = max(overflows, last_overflows)
        self.loss_scale.set(scale)
        if last_scale is not None and scale > last_scale:
            self.loss_scale_grow.inc()
        if overflows > last_overflows:
            self.loss_scale_backoff.inc(overflows - last_overflows)

    # ---- supervisor hook --------------------------------------------------

    def record_intervention(self, kind: str) -> None:
        if kind not in self.interventions:
            raise ValueError(f"unknown intervention {kind!r} "
                             f"(one of {INTERVENTIONS})")
        self.interventions[kind].inc()

    # ---- snapshot (checkpoint manifests, tests) ---------------------------

    def snapshot(self) -> Dict:
        st = self.step_time.summary()
        out = {
            "steps": int(self.steps_total.value),
            "loss": self.loss.value,
            "examples_per_sec": round(self.examples_per_sec.value, 1),
            "grad_norm": self.grad_norm.value,
            "step_time_mean_s": round(st.get("mean", 0.0), 6),
            "interventions": {k: int(c.value)
                              for k, c in self.interventions.items()
                              if c.value},
        }
        if self.loss_scale.value:
            out["loss_scale"] = self.loss_scale.value
            out["loss_scale_grows"] = int(self.loss_scale_grow.value)
            out["loss_scale_backoffs"] = int(self.loss_scale_backoff.value)
        return out
