"""ParagraphVectors (doc2vec), DBOW flavour on top of Word2Vec.

Parity: reference `models/paragraphvectors/ParagraphVectors.java:61` —
document labels live in the same vocab/lookup table as words (:64), and
`dbow():295` trains the label's vector to predict each word of the
document through the same HS/NEG objective as skip-gram. Inference for an
unseen document gradient-descends a fresh vector against frozen output
weights (a capability the reference lacked but doc2vec users expect).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.word2vec import Word2Vec, _log_sigmoid


class ParagraphVectors(Word2Vec):
    """DBOW paragraph vectors: labels as pseudo-words."""

    LABEL_PREFIX = "LABEL_"  # keeps labels distinct from corpus words

    def __init__(self, train_words: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.train_words = train_words
        self.labels: List[str] = []

    def fit_labelled(self, sentences: Sequence[str],
                     labels: Sequence[str]) -> "ParagraphVectors":
        if len(sentences) != len(labels):
            raise ValueError("sentences and labels must align")
        token_lists = self._sentences_to_tokens(sentences)
        self.labels = sorted(set(labels))
        # Labels enter the vocab as pseudo-words (reference: labels are
        # VocabWords :64). Their count is the number of DBOW training pairs
        # they appear in (= doc length), floored at min_word_frequency so the
        # vocab filter can never silently drop a label.
        with_labels = list(token_lists)
        floor = max(self.vocab.min_word_frequency, 1)
        for toks, lab in zip(token_lists, labels):
            with_labels.append(
                [self.LABEL_PREFIX + lab] * max(len(toks), floor))
        self.build_vocab(with_labels)
        self.reset_weights()

        rng = np.random.default_rng(self.seed)
        key = jax.random.PRNGKey(self.seed)
        use_hs = self.negative == 0
        syn0 = jnp.asarray(self.syn0)
        out = jnp.asarray(self.syn1 if use_hs else self.syn1neg)
        step = self._step

        encoded = [self.vocab.encode(t) for t in token_lists]
        label_idx = np.asarray(
            [self.vocab.index_of(self.LABEL_PREFIX + l) for l in labels],
            np.int32)
        if (label_idx < 0).any():
            raise AssertionError("label missing from vocab after build")

        # DBOW pairs: (input=label, target=word) for every word of the doc;
        # optionally also plain skip-gram pairs to train word vectors.
        lens = [len(s) for s in encoded]
        if sum(lens):
            arr = np.stack([np.repeat(label_idx, lens),
                            np.concatenate(encoded)], axis=1).astype(np.int32)
        else:
            arr = np.zeros((0, 2), np.int32)
        if self.train_words:
            arr = np.concatenate([arr, self._make_pairs(encoded, rng)])

        B = self.batch_size
        total = max(len(arr) * self.epochs, 1)
        seen = 0
        for epoch in range(self.epochs):
            arr = arr[rng.permutation(len(arr))]  # see _make_pairs: 2-D
            # rng.shuffle is per-row swaps, ~40x slower
            for s in range(0, len(arr), B):
                chunk = arr[s:s + B]
                n_real = len(chunk)
                valid = np.ones(B, np.int32)
                if n_real < B:
                    valid[n_real:] = 0
                    chunk = np.concatenate(
                        [chunk, np.zeros((B - n_real, 2), np.int32)])
                frac = min(seen / total, 1.0)
                lr = max(self.learning_rate * (1 - frac),
                         self.min_learning_rate)
                key, sub = jax.random.split(key)
                syn0, out, _ = step(syn0, out, jnp.asarray(chunk[:, 0]),
                                    jnp.asarray(chunk[:, 1]),
                                    jnp.float32(lr), sub,
                                    jnp.asarray(valid))
                seen += n_real
        self.syn0 = np.asarray(syn0)
        if use_hs:
            self.syn1 = np.asarray(out)
        else:
            self.syn1neg = np.asarray(out)
        self._norms = None
        return self

    # -- queries -----------------------------------------------------------
    def get_label_vector(self, label: str) -> Optional[np.ndarray]:
        return self.get_word_vector(self.LABEL_PREFIX + label)

    def similarity_to_label(self, words: Sequence[str], label: str) -> float:
        """Cosine between the mean word vector and a label vector."""
        vecs = [v for w in words if (v := self.get_word_vector(w)) is not None]
        lv = self.get_label_vector(label)
        if not vecs or lv is None:
            return float("nan")
        mean = np.mean(vecs, axis=0)
        denom = np.linalg.norm(mean) * np.linalg.norm(lv)
        return float(np.dot(mean, lv) / max(denom, 1e-12))

    def predict(self, words: Sequence[str]) -> Optional[str]:
        """Nearest label for a tokenized document (reference
        ParagraphVectors usage in sentiment examples)."""
        scored = [(self.similarity_to_label(words, l), l)
                  for l in self.labels]
        scored = [(s, l) for s, l in scored if np.isfinite(s)]
        return max(scored)[1] if scored else None

    def infer_vector(self, words: Sequence[str], steps: int = 50,
                     lr: float = 0.05) -> np.ndarray:
        """Gradient-descend a fresh doc vector against frozen output
        weights (DBOW objective)."""
        idx = self.vocab.encode(list(words))
        if len(idx) == 0:
            return np.zeros(self.vector_length, np.float32)
        use_hs = self.negative == 0
        rng = np.random.default_rng(self.seed)
        v = ((rng.random(self.vector_length) - 0.5)
             / self.vector_length).astype(np.float32)
        targets = jnp.asarray(idx)
        if use_hs:
            points, codes, lengths = self._hs
            syn1 = jnp.asarray(self.syn1)

            def loss_fn(vec):
                p = points[targets]
                c = codes[targets]
                L = p.shape[1]
                mask = (jnp.arange(L)[None, :]
                        < lengths[targets][:, None]).astype(vec.dtype)
                dots = jnp.einsum("d,nld->nl", vec, syn1[p])
                sign = 1.0 - 2.0 * c.astype(vec.dtype)
                return -jnp.sum(_log_sigmoid(sign * dots) * mask)
        else:
            syn1neg = jnp.asarray(self.syn1neg)
            table = self._neg_table
            K = self.negative
            key = jax.random.PRNGKey(self.seed + 1)
            negs = table[jax.random.randint(key, (len(idx), K), 0,
                                            table.shape[0])]

            def loss_fn(vec):
                pos = syn1neg[targets]           # [N, D]
                neg = syn1neg[negs]              # [N, K, D]
                pos_ll = _log_sigmoid(pos @ vec)
                neg_dot = jnp.einsum("nkd,d->nk", neg, vec)
                collide = (negs == targets[:, None])
                neg_ll = jnp.where(collide, 0.0, _log_sigmoid(-neg_dot))
                # Full contrastive NEG objective — without the negative
                # term the optimum is an unbounded-norm vector.
                return -(jnp.sum(pos_ll) + jnp.sum(neg_ll))

        grad = jax.jit(jax.grad(loss_fn))
        vec = jnp.asarray(v)
        for _ in range(steps):
            vec = vec - lr * grad(vec)
        return np.asarray(vec)
