"""Porter stemmer.

Parity: reference `text/annotator/StemmerAnnotator.java` (UIMA wrapper
around a Snowball stemmer). Self-contained Porter (1980) implementation —
no UIMA, usable as a token pre-processor in any tokenizer factory.
"""

from __future__ import annotations

_VOWELS = set("aeiou")


def _is_consonant(word: str, i: int) -> bool:
    c = word[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_consonant(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """Number of VC sequences."""
    m = 0
    prev_vowel = False
    for i in range(len(stem)):
        cons = _is_consonant(stem, i)
        if cons and prev_vowel:
            m += 1
        prev_vowel = not cons
    return m


def _contains_vowel(stem: str) -> bool:
    return any(not _is_consonant(stem, i) for i in range(len(stem)))


def _ends_double_consonant(word: str) -> bool:
    return (len(word) >= 2 and word[-1] == word[-2]
            and _is_consonant(word, len(word) - 1))


def _cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    if (_is_consonant(word, len(word) - 3)
            and not _is_consonant(word, len(word) - 2)
            and _is_consonant(word, len(word) - 1)):
        return word[-1] not in "wxy"
    return False


class PorterStemmer:
    def stem(self, word: str) -> str:
        w = word.lower()
        if len(w) <= 2:
            return w
        w = self._step1a(w)
        w = self._step1b(w)
        w = self._step1c(w)
        w = self._step2(w)
        w = self._step3(w)
        w = self._step4(w)
        w = self._step5(w)
        return w

    __call__ = stem

    # -- steps (Porter 1980) ------------------------------------------------
    def _step1a(self, w):
        if w.endswith("sses"):
            return w[:-2]
        if w.endswith("ies"):
            return w[:-2]
        if w.endswith("ss"):
            return w
        if w.endswith("s"):
            return w[:-1]
        return w

    def _step1b(self, w):
        if w.endswith("eed"):
            return w[:-1] if _measure(w[:-3]) > 0 else w
        flag = False
        if w.endswith("ed") and _contains_vowel(w[:-2]):
            w, flag = w[:-2], True
        elif w.endswith("ing") and _contains_vowel(w[:-3]):
            w, flag = w[:-3], True
        if flag:
            if w.endswith(("at", "bl", "iz")):
                return w + "e"
            if _ends_double_consonant(w) and not w.endswith(("l", "s", "z")):
                return w[:-1]
            if _measure(w) == 1 and _cvc(w):
                return w + "e"
        return w

    def _step1c(self, w):
        if w.endswith("y") and _contains_vowel(w[:-1]):
            return w[:-1] + "i"
        return w

    _STEP2 = [("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
              ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
              ("alli", "al"), ("entli", "ent"), ("eli", "e"),
              ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
              ("ator", "ate"), ("alism", "al"), ("iveness", "ive"),
              ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"),
              ("iviti", "ive"), ("biliti", "ble")]

    def _step2(self, w):
        for suf, rep in self._STEP2:
            if w.endswith(suf):
                stem = w[:-len(suf)]
                return stem + rep if _measure(stem) > 0 else w
        return w

    _STEP3 = [("icate", "ic"), ("ative", ""), ("alize", "al"),
              ("iciti", "ic"), ("ical", "ic"), ("ful", ""), ("ness", "")]

    def _step3(self, w):
        for suf, rep in self._STEP3:
            if w.endswith(suf):
                stem = w[:-len(suf)]
                return stem + rep if _measure(stem) > 0 else w
        return w

    _STEP4 = ["al", "ance", "ence", "er", "ic", "able", "ible", "ant",
              "ement", "ment", "ent", "ion", "ou", "ism", "ate", "iti",
              "ous", "ive", "ize"]

    def _step4(self, w):
        for suf in self._STEP4:
            if w.endswith(suf):
                stem = w[:-len(suf)]
                if _measure(stem) > 1:
                    if suf == "ion" and not stem.endswith(("s", "t")):
                        continue
                    return stem
                return w
        return w

    def _step5(self, w):
        if w.endswith("e"):
            stem = w[:-1]
            m = _measure(stem)
            if m > 1 or (m == 1 and not _cvc(stem)):
                w = stem
        if w.endswith("ll") and _measure(w) > 1:
            w = w[:-1]
        return w


class StemmingPreProcessor:
    """Token pre-processor slotting into the tokenizer factories (the role
    StemmerAnnotator played in the reference's UIMA pipeline)."""

    def __init__(self):
        self._stemmer = PorterStemmer()

    def pre_process(self, token: str) -> str:
        return self._stemmer.stem(token)

    __call__ = pre_process
