"""Word2Vec: skip-gram with hierarchical softmax and/or negative sampling,
dense-batched for TPU.

Parity: reference `models/word2vec/Word2Vec.java:59` (fit():103 — vocab
build → Huffman → training loop; `skipGram():319`; `iterate():342`) and the
HS/NEG inner loop `InMemoryLookupTable.iterateSample:192` with its expTable
sigmoid LUT, unigram^0.75 negative table, and linear learning-rate decay
floored at minLearningRate.

TPU-first re-design (SURVEY §7 hard part #1): the reference trains via
sparse per-pair saxpy updates, racy across a thread pool (Hogwild). Here:

- the host encodes sentences to int32 arrays once, then per epoch emits
  skip-gram (input, target) pairs with the word2vec dynamic-window trick,
  packed into fixed-size batches (static shapes → one XLA program);
- ONE jitted step evaluates the whole batch: embedding gathers, a [B,L]
  batched dot against the Huffman path rows (HS) and/or [B,K] negatives
  gathered from the unigram table, exact `log_sigmoid` instead of the
  1000-entry LUT, masked sum;
- gradients for syn0/syn1 are hand-derived for the TOUCHED rows only
  (the reference's per-pair saxpy math, batched) and applied as
  scatter-adds: O(B·D) work per step, never a dense O(V·D) gradient
  table, so vocabulary size costs memory, not step time;
- Hogwild's lock-free parallelism (`Word2Vec.java:145-258` thread pool
  over shared syn0, `InMemoryLookupTable.java:192`) maps to data-parallel
  batch sharding: pass ``mesh=`` and each step shard_maps the pair batch
  over the mesh's data axis, all_gathers the sparse (row, delta) pairs
  over ICI (O(B·D) comms, not a dense psum), and applies one identical
  scatter per replica — *more* synchronous than the reference's racy
  updates, not less, and bit-stable across device counts up to float
  reduction order.  ``mesh=None`` is the single-device case with
  identical numerics.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.mesh import (
    round_batch_to_mesh,
    sparse_allgather_step,
)

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import (
    Huffman,
    VocabCache,
    build_negative_table,
)
from deeplearning4j_tpu.nlp.word_vectors import WordVectors


def _log_sigmoid(x):
    # Stable log sigmoid; replaces the reference's clipped expTable LUT
    # (InMemoryLookupTable.java:173-177, MAX_EXP=6).
    return -jax.nn.softplus(-x)


# Reference MAX_EXP (InMemoryLookupTable.java): in the HIERARCHICAL
# SOFTMAX loop, pairs whose dot saturates (|dot| >= 6) contribute NO
# update — `iterateSample:214` skips them ("continue").  Besides parity,
# this is load-bearing for stability: a batched step accumulates
# hundreds of same-row contributions (e.g. doc labels in
# ParagraphVectors), and without the skip a badly-placed high-norm row
# feeds back |g|~1 updates and diverges geometrically; the skip freezes
# saturated pairs exactly as the reference does.  (The NEG loop is
# different — see _build_neg_step.)
MAX_EXP = 6.0

# Pairs staged on device per chunk during fit() (see the fit loop): the
# bound keeps device memory O(chunk) on huge corpora while still moving
# data to the device outside the hot loop.
STAGE_PAIRS = 1_048_576


class Word2Vec(WordVectors):
    """Skip-gram word embeddings (reference Word2Vec.java defaults:
    layerSize 100, window 5, alpha .025, minLearningRate 1e-2*alpha,
    negative sampling off → hierarchical softmax on)."""

    def __init__(self,
                 vector_length: int = 100,
                 window: int = 5,
                 min_word_frequency: int = 1,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 negative: int = 0,
                 subsample: float = 0.0,
                 batch_size: int = 2048,
                 epochs: int = 1,
                 seed: int = 42,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 mesh=None):
        self.vector_length = vector_length
        self.window = window
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.subsample = subsample
        self.mesh = mesh  # jax.sharding.Mesh: shard pairs over its 1st axis
        if mesh is not None:
            batch_size = round_batch_to_mesh(batch_size, mesh)
        self.batch_size = batch_size
        self.epochs = epochs
        self.seed = seed
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        vocab = VocabCache(min_word_frequency=min_word_frequency)
        super().__init__(vocab, np.zeros((0, vector_length), np.float32))
        self.syn1: Optional[np.ndarray] = None      # HS inner nodes
        self.syn1neg: Optional[np.ndarray] = None   # NEG output vectors
        self._hs = None  # (points, codes, lengths) device arrays
        self._neg_table = None
        self._step = None  # jitted train step, built in reset_weights

    # ------------------------------------------------------------------
    # vocab + weights

    def _sentences_to_tokens(self, sentences) -> List[List[str]]:
        out = []
        for s in sentences:
            out.append(self.tokenizer.tokenize(s) if isinstance(s, str)
                       else list(s))
        return out

    def build_vocab(self, token_lists: Sequence[Sequence[str]]) -> None:
        self.vocab.fit(token_lists)
        if len(self.vocab) == 0:
            raise ValueError("empty vocabulary — corpus too small or "
                             "min_word_frequency too high")
        Huffman(self.vocab).build()

    def reset_weights(self) -> None:
        """syn0 uniform in [-.5,.5]/D, syn1 zeros — reference
        `InMemoryLookupTable.resetWeights():94-100`."""
        rng = np.random.default_rng(self.seed)
        V, D = len(self.vocab), self.vector_length
        self.syn0 = ((rng.random((V, D)) - 0.5) / D).astype(np.float32)
        self.syn1 = np.zeros((max(V - 1, 1), D), np.float32)
        if self.negative > 0:
            self.syn1neg = np.zeros((V, D), np.float32)
            self._neg_table = jnp.asarray(build_negative_table(self.vocab))
        points, codes, lengths = self.vocab.hs_arrays()
        self._hs = (jnp.asarray(points), jnp.asarray(codes),
                    jnp.asarray(lengths))
        self._norms = None
        self._step = (self._build_neg_step() if self.negative > 0
                      else self._build_hs_step())

    # ------------------------------------------------------------------
    # pair generation (host side; reference skipGram():319)

    def _make_pairs(self, encoded: List[np.ndarray], rng: np.random.Generator
                    ) -> np.ndarray:
        """All (input=context, target=center) pairs for one epoch with the
        word2vec reduced-window trick; subsampling of frequent words if
        configured. Returns int32 [N, 2]."""
        total = self.vocab.total_word_count()
        keep_prob = None
        if self.subsample > 0:
            freq = np.array([self.vocab.word_frequency(self.vocab.word_at(i))
                             for i in range(len(self.vocab))], np.float64)
            ratio = freq / (self.subsample * total)
            keep_prob = np.minimum((np.sqrt(ratio) + 1) / ratio, 1.0)
        # Vectorized windowing: flatten the corpus with sentence ids, then
        # one numpy pass per offset d in [1, window] instead of a Python
        # loop per (token, offset) — ~20x faster host prep, same pair set
        # (context j for center i iff |j-i| <= window - b[i] within the
        # sentence, the word2vec reduced-window trick).
        sents = [s for s in encoded if len(s)]
        if not sents:
            return np.zeros((0, 2), np.int32)
        flat = np.concatenate(sents).astype(np.int32)
        sid = np.repeat(np.arange(len(sents)), [len(s) for s in sents])
        if keep_prob is not None and len(flat):
            keep = rng.random(len(flat)) < keep_prob[flat]
            flat, sid = flat[keep], sid[keep]
        n = len(flat)
        if n < 2:
            return np.zeros((0, 2), np.int32)
        win = self.window - rng.integers(0, self.window, n)  # in [1, window]
        chunks = []
        for d in range(1, self.window + 1):
            left = np.arange(n - d)
            same = sid[left] == sid[left + d]
            # center=left, context=left+d — gated by LEFT's reduced window
            c = left[same & (d <= win[left])]
            chunks.append(np.stack([flat[c + d], flat[c]], axis=1))
            # center=left+d, context=left — gated by RIGHT's reduced window
            c = left[same & (d <= win[left + d])]
            chunks.append(np.stack([flat[c], flat[c + d]], axis=1))
        arr = np.concatenate(chunks, axis=0).astype(np.int32)
        if not len(arr):
            return np.zeros((0, 2), np.int32)
        # permutation-gather, NOT rng.shuffle: numpy shuffles 2-D arrays
        # with per-row swaps (~40x slower; it dominated pair-gen time,
        # which is the host-side floor on TPU words/sec).
        return arr[rng.permutation(len(arr))]

    # ------------------------------------------------------------------
    # jitted training steps

    def _build_hs_step(self):
        """Sparse-update HS step: gradients are hand-derived for the
        TOUCHED rows only (the reference's `iterateSample:192` math,
        batched), applied as `.at[].add` scatters — O(B·L·D) work and
        memory instead of autodiff's dense O(V·D) gradient tables, which
        is the difference between toy and real vocabularies on TPU."""
        points, codes, lengths = self._hs
        L = points.shape[1]

        def deltas(syn0, syn1, inputs, targets, valid):
            """-> loss, (syn0 rows, syn0 deltas), (syn1 rows, syn1 deltas);
            deltas are DESCENT directions already scaled by -1 (add
            lr * delta to apply)."""
            h = syn0[inputs]                     # [B, D] input vectors
            p = points[targets]                  # [B, L] inner-node path
            c = codes[targets]                   # [B, L] branch bits
            mask = (jnp.arange(L)[None, :]
                    < lengths[targets][:, None]).astype(h.dtype)
            mask = mask * valid[:, None].astype(h.dtype)      # pad rows off
            w = syn1[p]                          # [B, L, D]
            dots = jnp.einsum("bd,bld->bl", h, w)
            # label 1 for code 0 (sign trick: s = 1 - 2*code)
            sign = 1.0 - 2.0 * c.astype(h.dtype)
            loss = -jnp.sum(_log_sigmoid(sign * dots) * mask)
            # d(-loss)/d(dots) = sign * sigmoid(-sign*dots), masked; the
            # reference's MAX_EXP skip zeroes saturated pairs.
            g = sign * jax.nn.sigmoid(-sign * dots) * mask    # [B, L]
            g = jnp.where(jnp.abs(dots) < MAX_EXP, g, 0.0)
            dh = jnp.einsum("bl,bld->bd", g, w)               # [B, D]
            dw = jnp.einsum("bl,bd->bld", g, h)               # [B, L, D]
            return loss, (inputs, dh), (p.reshape(-1),
                                        dw.reshape(-1, h.shape[-1]))

        step_core = self._sparse_step(deltas, with_key=False)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def hs_step(syn0, syn1, inputs, targets, lr, key, valid):
            return step_core(syn0, syn1, lr, inputs, targets, valid)

        return hs_step

    def _build_neg_step(self):
        """Sparse-update negative-sampling step; see _build_hs_step."""
        K = self.negative
        table = self._neg_table
        T = table.shape[0]

        def deltas(syn0, syn1neg, inputs, targets, valid, key):
            idx = jax.random.randint(key, (inputs.shape[0], K), 0, T)
            negs = table[idx]                    # [B, K]
            h = syn0[inputs]                     # [B, D]
            pos = syn1neg[targets]               # [B, D]
            neg = syn1neg[negs]                  # [B, K, D]
            pos_dot = jnp.sum(h * pos, axis=1)
            neg_dot = jnp.einsum("bd,bkd->bk", h, neg)
            # Collisions with the true target get masked out.
            collide = negs == targets[:, None]
            v = valid.astype(h.dtype)            # pad rows contribute zero
            neg_mask = jnp.where(collide, 0.0, v[:, None])
            loss = -(jnp.sum(_log_sigmoid(pos_dot) * v)
                     + jnp.sum(_log_sigmoid(-neg_dot) * neg_mask))
            # descent deltas (add lr * delta).  NOTE the asymmetry with
            # the HS step: the reference's negative-sampling loop does
            # NOT skip saturated pairs — it clamps the sigmoid to {0,1}
            # (InMemoryLookupTable.java:271-276), which the exact sigmoid
            # matches asymptotically, so no clip belongs here.
            g_pos = jax.nn.sigmoid(-pos_dot) * v              # [B]
            g_neg = -jax.nn.sigmoid(neg_dot) * neg_mask       # [B, K]
            dh = (g_pos[:, None] * pos
                  + jnp.einsum("bk,bkd->bd", g_neg, neg))     # [B, D]
            dpos = g_pos[:, None] * h                         # [B, D]
            dneg = jnp.einsum("bk,bd->bkd", g_neg, h)         # [B, K, D]
            out_rows = jnp.concatenate([targets, negs.reshape(-1)])
            out_deltas = jnp.concatenate(
                [dpos, dneg.reshape(-1, h.shape[-1])])
            return loss, (inputs, dh), (out_rows, out_deltas)

        step_core = self._sparse_step(deltas, with_key=True)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def neg_step(syn0, syn1neg, inputs, targets, lr, key, valid):
            return step_core(syn0, syn1neg, lr, inputs, targets, valid,
                             key)

        return neg_step

    def _sparse_step(self, deltas_fn, with_key: bool):
        """Turn a sparse-delta fn into the full table-update step via the
        shared `sparse_allgather_step` harness: single device scatter-adds
        `lr * delta` into the touched rows; with a mesh, the pair batch
        shards over the first axis (the documented TPU-native Hogwild,
        `Word2Vec.java:145-258`), the (rows, deltas) pairs are
        all_gathered — O(B·D) over ICI instead of a dense O(V·D) psum —
        and every replica applies the identical scatter."""

        def deltas(syn0, syn1, lr, inputs, targets, valid, *key):
            loss, p0, p1 = deltas_fn(syn0, syn1, inputs, targets, valid,
                                     *key)
            return loss, (p0, p1)

        def apply(syn0, syn1, lr, aux):
            (r0, d0), (r1, d1) = aux
            return (syn0.at[r0].add(lr * d0), syn1.at[r1].add(lr * d1))

        return sparse_allgather_step(self.mesh, deltas, apply, n_state=2,
                                     n_scalar=1, n_sharded=3,
                                     with_key=with_key)

    # ------------------------------------------------------------------
    # fit (reference Word2Vec.fit():103)

    def _pair_producer(self, encoded, out_q) -> None:
        """Background pair-chunk producer (reference parity: the
        Word2Vec.java:145-258 thread pool existed to overlap exactly this
        host work with training).  Epoch pair arrays are generated on a
        worker thread — numpy releases the GIL for the heavy ops — while
        the main thread keeps the device busy dispatching steps; the
        1-deep queue bounds host memory to one epoch ahead."""
        rng = np.random.default_rng(self.seed)
        try:
            for _ in range(self.epochs):
                out_q.put(("pairs", self._make_pairs(encoded, rng)))
            out_q.put(("done", None))
        except BaseException as e:  # noqa: BLE001 - re-raised by consumer
            out_q.put(("error", e))

    def fit(self, sentences) -> "Word2Vec":
        import os
        import queue
        import threading

        token_lists = self._sentences_to_tokens(sentences)
        if len(self.vocab) == 0:
            self.build_vocab(token_lists)
        if self.syn0.shape[0] != len(self.vocab):
            self.reset_weights()
        encoded = [self.vocab.encode(t) for t in token_lists]
        key = jax.random.PRNGKey(self.seed)

        use_hs = self.negative == 0
        syn0 = jnp.asarray(self.syn0)
        out = jnp.asarray(self.syn1 if use_hs else self.syn1neg)
        step = self._step

        # Pair-gen/device-step overlap needs a second core to be a win;
        # on a single-core host a quiet A/B measures the two paths equal
        # (threaded 0.99x inline — the GIL interleaves tolerably), so
        # prefer the simpler inline loop there and skip the thread
        # machinery that cannot help.  Either way the SAME rng object
        # generates epochs in order -> bit-identical pairs and results.
        producer = None
        if (os.cpu_count() or 1) > 1:
            pair_q: "queue.Queue" = queue.Queue(maxsize=1)
            producer = threading.Thread(
                target=self._pair_producer, args=(encoded, pair_q),
                daemon=True)
            producer.start()

            def epoch_chunks():
                while True:
                    kind, payload = pair_q.get()
                    if kind == "error":
                        raise payload
                    if kind == "done":
                        return
                    yield payload
        else:
            def epoch_chunks():
                rng = np.random.default_rng(self.seed)
                for _ in range(self.epochs):
                    yield self._make_pairs(encoded, rng)

        total_pairs = None
        seen = 0
        for pairs in epoch_chunks():
            if total_pairs is None:
                total_pairs = max(len(pairs) * self.epochs, 1)
            B = self.batch_size
            # Stage the pair stream on device in BOUNDED chunks (~1M
            # pairs each): per-batch slicing inside a chunk is
            # device-side — no host->device transfer in the hot loop
            # (HBM/tunnel hygiene) — while memory stays O(chunk), not
            # O(corpus).  The valid mask is all-ones except the final
            # tail batch, so only two [B] masks ever exist.
            n_batches = (len(pairs) + B - 1) // B  # 0 -> epoch skipped
            chunk_batches = max(1, STAGE_PAIRS // B)
            full_valid = jnp.ones((B,), jnp.int32)
            for c0 in range(0, n_batches, chunk_batches):
                c1 = min(c0 + chunk_batches, n_batches)
                part = pairs[c0 * B:c1 * B]
                padded = np.zeros(((c1 - c0) * B, 2), np.int32)
                padded[:len(part)] = part
                chunk_dev = jnp.asarray(padded.reshape(c1 - c0, B, 2))
                for bi in range(c1 - c0):
                    n_real = min(B, len(pairs) - (c0 + bi) * B)
                    if n_real < B:
                        tail = np.zeros((B,), np.int32)
                        tail[:n_real] = 1
                        valid = jnp.asarray(tail)
                    else:
                        valid = full_valid
                    # Linear LR decay by pairs seen (reference `alpha`
                    # decay, Word2Vec.java:231-238), floored at
                    # min_learning_rate.
                    frac = min(seen / total_pairs, 1.0)
                    lr = max(self.learning_rate * (1 - frac),
                             self.min_learning_rate)
                    key, sub = jax.random.split(key)
                    syn0, out, _ = step(
                        syn0, out, chunk_dev[bi, :, 0], chunk_dev[bi, :, 1],
                        jnp.float32(lr), sub, valid)
                    seen += n_real
        if producer is not None:
            producer.join()
        self.syn0 = np.asarray(syn0)
        if use_hs:
            self.syn1 = np.asarray(out)
        else:
            self.syn1neg = np.asarray(out)
        self._norms = None
        return self

    # reference naming
    train = fit
