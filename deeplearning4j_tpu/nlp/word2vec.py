"""Word2Vec: skip-gram with hierarchical softmax and/or negative sampling,
dense-batched for TPU.

Parity: reference `models/word2vec/Word2Vec.java:59` (fit():103 — vocab
build → Huffman → training loop; `skipGram():319`; `iterate():342`) and the
HS/NEG inner loop `InMemoryLookupTable.iterateSample:192` with its expTable
sigmoid LUT, unigram^0.75 negative table, and linear learning-rate decay
floored at minLearningRate.

TPU-first re-design (SURVEY §7 hard part #1): the reference trains via
sparse per-pair saxpy updates, racy across a thread pool (Hogwild). Here:

- the host encodes sentences to int32 arrays once, then per epoch emits
  skip-gram (input, target) pairs with the word2vec dynamic-window trick,
  packed into fixed-size batches (static shapes → one XLA program);
- ONE jitted step evaluates the whole batch: embedding gathers, a [B,L]
  batched dot against the Huffman path rows (HS) and/or [B,K] negatives
  gathered from the unigram table, exact `log_sigmoid` instead of the
  1000-entry LUT, masked sum;
- gradients reach syn0/syn1 through XLA's gather→scatter-add autodiff:
  the update is mathematically the reference's sparse saxpy, but batched,
  deterministic, and fused by the compiler;
- Hogwild's lock-free parallelism (`Word2Vec.java:145-258` thread pool
  over shared syn0, `InMemoryLookupTable.java:192`) maps to data-parallel
  batch sharding: pass ``mesh=`` and each step shard_maps the pair batch
  over the mesh's data axis, psums the syn0/syn1 gradients over ICI, and
  applies one identical update per replica — *more* synchronous than the
  reference's racy updates, not less, and bit-stable across device counts
  up to float reduction order.  ``mesh=None`` is the single-device case
  with identical numerics (the psum of one shard).
"""

from __future__ import annotations

import functools
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.mesh import (
    data_parallel_grads,
    round_batch_to_mesh,
)

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import (
    Huffman,
    VocabCache,
    build_negative_table,
)
from deeplearning4j_tpu.nlp.word_vectors import WordVectors


def _log_sigmoid(x):
    # Stable log sigmoid; replaces the reference's clipped expTable LUT
    # (InMemoryLookupTable.java:173-177, MAX_EXP=6).
    return -jax.nn.softplus(-x)


class Word2Vec(WordVectors):
    """Skip-gram word embeddings (reference Word2Vec.java defaults:
    layerSize 100, window 5, alpha .025, minLearningRate 1e-2*alpha,
    negative sampling off → hierarchical softmax on)."""

    def __init__(self,
                 vector_length: int = 100,
                 window: int = 5,
                 min_word_frequency: int = 1,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 negative: int = 0,
                 subsample: float = 0.0,
                 batch_size: int = 2048,
                 epochs: int = 1,
                 seed: int = 42,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 mesh=None):
        self.vector_length = vector_length
        self.window = window
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.subsample = subsample
        self.mesh = mesh  # jax.sharding.Mesh: shard pairs over its 1st axis
        if mesh is not None:
            batch_size = round_batch_to_mesh(batch_size, mesh)
        self.batch_size = batch_size
        self.epochs = epochs
        self.seed = seed
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        vocab = VocabCache(min_word_frequency=min_word_frequency)
        super().__init__(vocab, np.zeros((0, vector_length), np.float32))
        self.syn1: Optional[np.ndarray] = None      # HS inner nodes
        self.syn1neg: Optional[np.ndarray] = None   # NEG output vectors
        self._hs = None  # (points, codes, lengths) device arrays
        self._neg_table = None
        self._step = None  # jitted train step, built in reset_weights

    # ------------------------------------------------------------------
    # vocab + weights

    def _sentences_to_tokens(self, sentences) -> List[List[str]]:
        out = []
        for s in sentences:
            out.append(self.tokenizer.tokenize(s) if isinstance(s, str)
                       else list(s))
        return out

    def build_vocab(self, token_lists: Sequence[Sequence[str]]) -> None:
        self.vocab.fit(token_lists)
        if len(self.vocab) == 0:
            raise ValueError("empty vocabulary — corpus too small or "
                             "min_word_frequency too high")
        Huffman(self.vocab).build()

    def reset_weights(self) -> None:
        """syn0 uniform in [-.5,.5]/D, syn1 zeros — reference
        `InMemoryLookupTable.resetWeights():94-100`."""
        rng = np.random.default_rng(self.seed)
        V, D = len(self.vocab), self.vector_length
        self.syn0 = ((rng.random((V, D)) - 0.5) / D).astype(np.float32)
        self.syn1 = np.zeros((max(V - 1, 1), D), np.float32)
        if self.negative > 0:
            self.syn1neg = np.zeros((V, D), np.float32)
            self._neg_table = jnp.asarray(build_negative_table(self.vocab))
        points, codes, lengths = self.vocab.hs_arrays()
        self._hs = (jnp.asarray(points), jnp.asarray(codes),
                    jnp.asarray(lengths))
        self._norms = None
        self._step = (self._build_neg_step() if self.negative > 0
                      else self._build_hs_step())

    # ------------------------------------------------------------------
    # pair generation (host side; reference skipGram():319)

    def _make_pairs(self, encoded: List[np.ndarray], rng: np.random.Generator
                    ) -> np.ndarray:
        """All (input=context, target=center) pairs for one epoch with the
        word2vec reduced-window trick; subsampling of frequent words if
        configured. Returns int32 [N, 2]."""
        total = self.vocab.total_word_count()
        keep_prob = None
        if self.subsample > 0:
            freq = np.array([self.vocab.word_frequency(self.vocab.word_at(i))
                             for i in range(len(self.vocab))], np.float64)
            ratio = freq / (self.subsample * total)
            keep_prob = np.minimum((np.sqrt(ratio) + 1) / ratio, 1.0)
        # Vectorized windowing: flatten the corpus with sentence ids, then
        # one numpy pass per offset d in [1, window] instead of a Python
        # loop per (token, offset) — ~20x faster host prep, same pair set
        # (context j for center i iff |j-i| <= window - b[i] within the
        # sentence, the word2vec reduced-window trick).
        sents = [s for s in encoded if len(s)]
        if not sents:
            return np.zeros((0, 2), np.int32)
        flat = np.concatenate(sents).astype(np.int32)
        sid = np.repeat(np.arange(len(sents)), [len(s) for s in sents])
        if keep_prob is not None and len(flat):
            keep = rng.random(len(flat)) < keep_prob[flat]
            flat, sid = flat[keep], sid[keep]
        n = len(flat)
        if n < 2:
            return np.zeros((0, 2), np.int32)
        win = self.window - rng.integers(0, self.window, n)  # in [1, window]
        chunks = []
        for d in range(1, self.window + 1):
            left = np.arange(n - d)
            same = sid[left] == sid[left + d]
            # center=left, context=left+d — gated by LEFT's reduced window
            c = left[same & (d <= win[left])]
            chunks.append(np.stack([flat[c + d], flat[c]], axis=1))
            # center=left+d, context=left — gated by RIGHT's reduced window
            c = left[same & (d <= win[left + d])]
            chunks.append(np.stack([flat[c], flat[c + d]], axis=1))
        arr = np.concatenate(chunks, axis=0).astype(np.int32)
        if not len(arr):
            return np.zeros((0, 2), np.int32)
        rng.shuffle(arr)
        return arr

    # ------------------------------------------------------------------
    # jitted training steps

    def _build_hs_step(self):
        points, codes, lengths = self._hs
        L = points.shape[1]

        def grads(syn0, syn1, inputs, targets, valid):
            def loss_fn(s0, s1):
                h = s0[inputs]                   # [B, D] input vectors
                p = points[targets]              # [B, L] inner-node path
                c = codes[targets]               # [B, L] branch bits
                mask = (jnp.arange(L)[None, :]
                        < lengths[targets][:, None]).astype(h.dtype)
                mask = mask * valid[:, None].astype(h.dtype)  # pad rows off
                w = s1[p]                        # [B, L, D]
                dots = jnp.einsum("bd,bld->bl", h, w)
                # label 1 for code 0 (sign trick: s = 1 - 2*code)
                sign = 1.0 - 2.0 * c.astype(h.dtype)
                return -jnp.sum(_log_sigmoid(sign * dots) * mask)

            loss, (g0, g1) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                syn0, syn1)
            return loss, g0, g1

        grads = self._maybe_shard(grads, with_key=False)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def hs_step(syn0, syn1, inputs, targets, lr, key, valid):
            loss, g0, g1 = grads(syn0, syn1, inputs, targets, valid)
            return syn0 - lr * g0, syn1 - lr * g1, loss

        return hs_step

    def _build_neg_step(self):
        K = self.negative
        table = self._neg_table
        T = table.shape[0]

        def grads(syn0, syn1neg, inputs, targets, valid, key):
            idx = jax.random.randint(key, (inputs.shape[0], K), 0, T)
            negs = table[idx]                    # [B, K]

            def loss_fn(s0, s1n):
                h = s0[inputs]                   # [B, D]
                pos = s1n[targets]               # [B, D]
                neg = s1n[negs]                  # [B, K, D]
                pos_dot = jnp.sum(h * pos, axis=1)
                neg_dot = jnp.einsum("bd,bkd->bk", h, neg)
                # Collisions with the true target get masked out.
                collide = (negs == targets[:, None])
                neg_ll = jnp.where(collide, 0.0, _log_sigmoid(-neg_dot))
                v = valid.astype(h.dtype)        # pad rows contribute zero
                return -(jnp.sum(_log_sigmoid(pos_dot) * v)
                         + jnp.sum(neg_ll * v[:, None]))

            loss, (g0, g1) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                syn0, syn1neg)
            return loss, g0, g1

        grads = self._maybe_shard(grads, with_key=True)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def neg_step(syn0, syn1neg, inputs, targets, lr, key, valid):
            loss, g0, g1 = grads(syn0, syn1neg, inputs, targets, valid, key)
            return syn0 - lr * g0, syn1neg - lr * g1, loss

        return neg_step

    def _maybe_shard(self, grads_fn, with_key: bool):
        """Mesh-parallel training step core (the documented TPU-native
        Hogwild, `Word2Vec.java:145-258`): shard the pair batch over the
        mesh's first axis, keep syn0/syn1 replicated, psum gradients and
        loss over ICI so every replica applies one identical update.
        mesh=None returns the fn unwrapped — the exact single-device
        numerics (a one-shard psum)."""
        if self.mesh is None:
            return grads_fn
        return data_parallel_grads(grads_fn, self.mesh, n_replicated=2,
                                   n_sharded=3, with_key=with_key)

    # ------------------------------------------------------------------
    # fit (reference Word2Vec.fit():103)

    def fit(self, sentences) -> "Word2Vec":
        token_lists = self._sentences_to_tokens(sentences)
        if len(self.vocab) == 0:
            self.build_vocab(token_lists)
        if self.syn0.shape[0] != len(self.vocab):
            self.reset_weights()
        encoded = [self.vocab.encode(t) for t in token_lists]
        rng = np.random.default_rng(self.seed)
        key = jax.random.PRNGKey(self.seed)

        use_hs = self.negative == 0
        syn0 = jnp.asarray(self.syn0)
        out = jnp.asarray(self.syn1 if use_hs else self.syn1neg)
        step = self._step

        total_pairs = None
        seen = 0
        for epoch in range(self.epochs):
            pairs = self._make_pairs(encoded, rng)
            if total_pairs is None:
                total_pairs = max(len(pairs) * self.epochs, 1)
            B = self.batch_size
            # Stage the pair stream on device in BOUNDED chunks (~1M
            # pairs each): per-batch slicing inside a chunk is
            # device-side — no host->device transfer in the hot loop
            # (HBM/tunnel hygiene) — while memory stays O(chunk), not
            # O(corpus).  The valid mask is all-ones except the final
            # tail batch, so only two [B] masks ever exist.
            n_batches = (len(pairs) + B - 1) // B  # 0 -> epoch skipped
            chunk_batches = max(1, 1_048_576 // B)
            full_valid = jnp.ones((B,), jnp.int32)
            for c0 in range(0, n_batches, chunk_batches):
                c1 = min(c0 + chunk_batches, n_batches)
                part = pairs[c0 * B:c1 * B]
                padded = np.zeros(((c1 - c0) * B, 2), np.int32)
                padded[:len(part)] = part
                chunk_dev = jnp.asarray(padded.reshape(c1 - c0, B, 2))
                for bi in range(c1 - c0):
                    n_real = min(B, len(pairs) - (c0 + bi) * B)
                    if n_real < B:
                        tail = np.zeros((B,), np.int32)
                        tail[:n_real] = 1
                        valid = jnp.asarray(tail)
                    else:
                        valid = full_valid
                    # Linear LR decay by pairs seen (reference `alpha`
                    # decay, Word2Vec.java:231-238), floored at
                    # min_learning_rate.
                    frac = min(seen / total_pairs, 1.0)
                    lr = max(self.learning_rate * (1 - frac),
                             self.min_learning_rate)
                    key, sub = jax.random.split(key)
                    syn0, out, _ = step(
                        syn0, out, chunk_dev[bi, :, 0], chunk_dev[bi, :, 1],
                        jnp.float32(lr), sub, valid)
                    seen += n_real
        self.syn0 = np.asarray(syn0)
        if use_hs:
            self.syn1 = np.asarray(out)
        else:
            self.syn1neg = np.asarray(out)
        self._norms = None
        return self

    # reference naming
    train = fit
