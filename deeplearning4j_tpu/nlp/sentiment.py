"""Bundled mini sentiment corpus + labeled-tree builder for RNTN gates.

Parity role: the reference's RNTN pipeline trains on the labeled Stanford
Sentiment Treebank fed through its tree parser
(`models/rntn/RNTN.java:82`, `text/corpora/treeparser/TreeParser.java:427`,
exercised by `BasicRNTNTest`).  Offline, no treebank download exists, so
the framework ships this hand-written movie/product-review corpus: real
English sentences with genuine binary sentiment, parsed by the in-package
`TreeParser` (PoStagger -> chunker, the reference call stack) into
labeled `Tree`s that `models.rntn.RNTN` consumes directly.

Labels: 0 = negative, 1 = positive, applied to every node of a
sentence's tree (weak labeling: the per-node supervision of the real
SST is unavailable for hand-authored data; the root is what the gate
scores, matching `RNTNEval` root accuracy).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from deeplearning4j_tpu.nlp.tree import Tree

# (label, sentence): 1 positive / 0 negative.  Authored so that (a) each
# sentiment cue word RECURS in several different sentences — a held-out
# sentence's cues are in-vocabulary, as in real review corpora — and
# (b) templates are shared across both classes with different cues, so
# the sentence frame carries no class signal; the cue words do.
MINI_REVIEWS: List[Tuple[int, str]] = [
    (1, "this movie is wonderful from start to finish"),
    (1, "a brilliant performance anchors this wonderful film"),
    (1, "the plot is gripping and the pacing is superb"),
    (1, "i found the whole show delightful and moving"),
    (1, "a moving story told with brilliant craft"),
    (1, "the acting is superb and the script is sharp"),
    (1, "an excellent adventure with a gripping finale"),
    (1, "the cast delivers an excellent and heartfelt show"),
    (1, "a delightful comedy built on sharp writing"),
    (1, "the visuals are stunning and the music is gorgeous"),
    (1, "a funny and deeply satisfying movie"),
    (1, "the director delivers a stunning piece of work"),
    (1, "every scene feels fresh and inspired"),
    (1, "a charming film with a moving message"),
    (1, "the characters are charming and wonderfully drawn"),
    (1, "this album sounds fresh and completely inspired"),
    (1, "a gripping thriller with a satisfying ending"),
    (1, "the book is brilliant and rewarding"),
    (1, "an inspiring tale with a gorgeous setting"),
    (1, "the food was excellent and the service was charming"),
    (1, "a superb blend of humor and heart"),
    (1, "the performances are heartfelt and honest"),
    (1, "this game is polished and great fun"),
    (1, "a wonderful surprise with a satisfying payoff"),
    (1, "the writing is sharp and genuinely funny"),
    (1, "a moving and rewarding experience"),
    (1, "the new season is fresh and frequently brilliant"),
    (1, "the hotel was lovely and the staff were delightful"),
    (1, "the ending is honest and deeply satisfying"),
    (1, "a fascinating documentary with stunning photography"),
    (1, "the leads share a charming and funny chemistry"),
    (1, "a bold and rewarding piece of work"),
    (1, "this restaurant serves excellent pasta with lovely service"),
    (1, "a tender love story with gorgeous photography"),
    (1, "the soundtrack is inspired and elevates the film"),
    (1, "a heartfelt comedy that is funny and honest"),
    (1, "the craftsmanship here is polished and superb"),
    (1, "a lovely gem with a heartfelt core"),
    (1, "the lecture was inspiring and wonderfully clear"),
    (1, "a thrilling ride with an inspired payoff"),
    (1, "this phone is fast polished and a pleasure"),
    (1, "the garden looked lovely and fresh this morning"),
    (1, "an honest film made with brilliant care"),
    (1, "the team gave a superb and inspired effort"),
    (1, "a glorious and satisfying return for the studio"),
    (1, "the novel builds to a rewarding and honest finale"),
    (1, "a stunning and tender film about hope"),
    (1, "the show stays funny and charming all season"),
    (0, "this movie is terrible from start to finish"),
    (0, "a dull performance sinks this boring film"),
    (0, "the plot is tedious and the pacing is sloppy"),
    (0, "i found the whole show dull and lifeless"),
    (0, "a clumsy story told with lazy craft"),
    (0, "the acting is wooden and the script is weak"),
    (0, "a tedious adventure with a predictable finale"),
    (0, "the cast delivers an awful and lifeless show"),
    (0, "a painful comedy built on stale writing"),
    (0, "the visuals are cheap and the music is grating"),
    (0, "a hollow and deeply boring movie"),
    (0, "the director delivers a sloppy piece of work"),
    (0, "every scene feels stale and lazy"),
    (0, "a dreary film with a hollow message"),
    (0, "the characters are dull and poorly drawn"),
    (0, "this album sounds stale and completely derivative"),
    (0, "a dreary thriller with a predictable ending"),
    (0, "the book is clumsy and forgettable"),
    (0, "a depressing tale with a grating tone"),
    (0, "the food was bland and the service was rude"),
    (0, "an awful mix of noise and boredom"),
    (0, "the performances are wooden and fake"),
    (0, "this game is buggy and no fun"),
    (0, "a nasty surprise with a cheap payoff"),
    (0, "the writing is weak and painfully unfunny"),
    (0, "a tedious and forgettable experience"),
    (0, "the new season is stale and frequently awful"),
    (0, "the hotel was dirty and the staff were rude"),
    (0, "the ending is abrupt and deeply unsatisfying"),
    (0, "a shallow documentary with cheap photography"),
    (0, "the leads share a painful and wooden chemistry"),
    (0, "a timid and tiresome piece of work"),
    (0, "this restaurant serves bland pasta with rude service"),
    (0, "a cold love story with dreary photography"),
    (0, "the soundtrack is grating and ruins the film"),
    (0, "a heartless comedy that is unfunny and fake"),
    (0, "the craftsmanship here is sloppy and shoddy"),
    (0, "a dismal dud with a hollow core"),
    (0, "the lecture was boring and painfully vague"),
    (0, "a sluggish ride with a predictable payoff"),
    (0, "this phone is slow buggy and a pain"),
    (0, "the garden looked neglected and dreary this morning"),
    (0, "a dishonest film made with lazy care"),
    (0, "the team gave a sloppy and timid effort"),
    (0, "a dismal and unsatisfying low point for the studio"),
    (0, "the novel collapses into a botched and clumsy finale"),
    (0, "a grating and cold film about nothing"),
    (0, "the show stays dull and lifeless all season"),
]


def mini_reviews() -> List[Tuple[int, str]]:
    """The bundled (label, sentence) sentiment corpus."""
    return list(MINI_REVIEWS)


def sentiment_trees(parser=None, reviews=None,
                    node_labels: str = "all") -> List[Tree]:
    """Parse the review corpus with the in-package TreeParser (PoStagger
    -> chunker — the reference's TreeParser.java role) into RNTN-ready
    labeled trees.

    node_labels: "all" weak-labels every node with the sentence class
    (the shape of fully-labeled SST training); "root" labels only the
    root — interior nodes stay unsupervised via TreeProgram.labeled."""
    from deeplearning4j_tpu.nlp.annotators import TreeParser

    parser = parser or TreeParser()
    out = []
    for label, text in (reviews if reviews is not None else MINI_REVIEWS):
        trees = parser.parse_text(text)
        if not trees:
            continue
        tree = trees[0]
        for node in tree.nodes():
            node.label = label if node_labels == "all" else None
        tree.label = label
        out.append(tree)
    return out
