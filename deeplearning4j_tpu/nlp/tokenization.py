"""Tokenizer SPI + preprocessors.

Parity: reference `text/tokenization/` — `DefaultTokenizer` (Java
StringTokenizer semantics), `NGramTokenizer`, `TokenizerFactory` SPI,
`EndingPreProcessor` (crude suffix stemmer), `InputHomogenization`
(lowercase + punctuation strip). UIMA/PosUima tokenizers are represented by
the same SPI — plug any callable in via `TokenizerFactory(custom_fn)`.
"""

from __future__ import annotations

import re
import unicodedata
from typing import Callable, List, Optional


class TokenPreProcess:
    """SPI: per-token preprocessing (reference TokenPreProcess)."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError

    def __call__(self, token: str) -> str:
        return self.pre_process(token)


class EndingPreProcessor(TokenPreProcess):
    """Crude suffix stripper (reference `EndingPreProcessor.java`): drops
    plural/verb endings so 'apples'→'apple', 'running'→'runn'."""

    def pre_process(self, token: str) -> str:
        if token.endswith("s") and not token.endswith("ss"):
            token = token[:-1]
        if token.endswith("."):
            token = token[:-1]
        if token.endswith("ly"):
            token = token[:-2]
        if token.endswith("ing"):
            token = token[:-3]
        return token


class InputHomogenization:
    """Sentence-level normalisation (reference `InputHomogenization.java`):
    lowercase, strip punctuation/accents."""

    def __init__(self, preserve_case: bool = False):
        self.preserve_case = preserve_case

    def transform(self, text: str) -> str:
        text = unicodedata.normalize("NFD", text)
        text = "".join(c for c in text if unicodedata.category(c) != "Mn")
        text = re.sub(r"[^\w\s]", "", text)
        return text if self.preserve_case else text.lower()


class Tokenizer:
    """SPI matching the reference `Tokenizer` interface: hasMoreTokens /
    nextToken / getTokens, plus Python iteration."""

    def __init__(self, tokens: List[str],
                 pre_processor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._i = 0
        self.pre_processor = pre_processor

    def has_more_tokens(self) -> bool:
        return self._i < len(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)

    def next_token(self) -> str:
        tok = self._tokens[self._i]
        self._i += 1
        return self.pre_processor(tok) if self.pre_processor else tok

    def get_tokens(self) -> List[str]:
        return [self.pre_processor(t) if self.pre_processor else t
                for t in self._tokens]

    def __iter__(self):
        return iter(self.get_tokens())


class DefaultTokenizer(Tokenizer):
    """Whitespace tokenization (reference `DefaultTokenizer` wraps Java
    StringTokenizer)."""

    def __init__(self, text: str,
                 pre_processor: Optional[TokenPreProcess] = None):
        super().__init__(text.split(), pre_processor)


class NGramTokenizer(Tokenizer):
    """Word n-grams from the base tokens (reference `NGramTokenizer`):
    emits every n-gram for n in [min_n, max_n] joined by spaces."""

    def __init__(self, text: str, min_n: int = 1, max_n: int = 2,
                 pre_processor: Optional[TokenPreProcess] = None):
        base = text.split()
        if pre_processor:
            base = [pre_processor(t) for t in base]
        grams: List[str] = []
        for n in range(min_n, max_n + 1):
            for i in range(len(base) - n + 1):
                grams.append(" ".join(base[i:i + n]))
        super().__init__(grams, None)


class TokenizerFactory:
    """SPI: creates Tokenizers (reference `TokenizerFactory`)."""

    def __init__(self, fn: Callable[..., Tokenizer] = DefaultTokenizer,
                 pre_processor: Optional[TokenPreProcess] = None, **kwargs):
        self._fn = fn
        self._kwargs = kwargs
        self.pre_processor = pre_processor

    def create(self, text: str) -> Tokenizer:
        return self._fn(text, pre_processor=self.pre_processor,
                        **self._kwargs)

    def tokenize(self, text: str) -> List[str]:
        return self.create(text).get_tokens()


class DefaultTokenizerFactory(TokenizerFactory):
    def __init__(self, pre_processor: Optional[TokenPreProcess] = None):
        super().__init__(DefaultTokenizer, pre_processor)


class NGramTokenizerFactory(TokenizerFactory):
    def __init__(self, min_n: int = 1, max_n: int = 2,
                 pre_processor: Optional[TokenPreProcess] = None):
        super().__init__(NGramTokenizer, pre_processor, min_n=min_n,
                         max_n=max_n)
