"""Text vectorizers: bag-of-words counts and TF-IDF → DataSet.

Parity: reference `bagofwords/vectorizer/` — `BaseTextVectorizer.java`,
`CountVectorizer`, `TfidfVectorizer` (vectorize(text, label) → DataSet).
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.fetchers import one_hot
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache


class BaseTextVectorizer:
    def __init__(self, min_word_frequency: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 max_features: Optional[int] = None):
        self.vocab = VocabCache(min_word_frequency=min_word_frequency,
                                max_words=max_features)
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        self._doc_freq: Dict[str, int] = {}
        self._idf = np.zeros(0, np.float32)
        self.num_docs = 0

    def fit(self, documents: Sequence[str]) -> "BaseTextVectorizer":
        self._fit_tokens([self.tokenizer.tokenize(d) for d in documents])
        return self

    def _fit_tokens(self, token_lists: Sequence[Sequence[str]]) -> None:
        self.vocab.fit(token_lists)
        self.num_docs = len(token_lists)
        for toks in token_lists:
            for w in set(toks):
                if self.vocab.contains(w):
                    self._doc_freq[w] = self._doc_freq.get(w, 0) + 1
        self._idf = np.zeros(len(self.vocab), np.float32)
        for w, df in self._doc_freq.items():
            self._idf[self.vocab.index_of(w)] = math.log(
                max(self.num_docs, 1) / df)

    def fit_transform(self, documents: Sequence[str]) -> np.ndarray:
        """fit + transform tokenizing each document once (a large corpus is
        tokenized twice by fit(docs) followed by transform(docs))."""
        token_lists = [self.tokenizer.tokenize(d) for d in documents]
        self._fit_tokens(token_lists)
        return np.stack([self._row(toks) for toks in token_lists])

    def _row(self, tokens: Sequence[str]) -> np.ndarray:
        raise NotImplementedError

    def transform(self, documents: Sequence[str]) -> np.ndarray:
        return np.stack([self._row(self.tokenizer.tokenize(d))
                         for d in documents])

    def vectorize(self, documents: Sequence[str],
                  labels: Sequence[int],
                  num_classes: Optional[int] = None) -> DataSet:
        """text+label → DataSet (reference TextVectorizer.vectorize)."""
        x = self.transform(documents)
        y = np.asarray(labels, int)
        k = num_classes or int(y.max()) + 1
        return DataSet(x.astype(np.float32), one_hot(y, k))


class CountVectorizer(BaseTextVectorizer):
    """Raw term counts (reference CountVectorizer)."""

    def _row(self, tokens):
        row = np.zeros(len(self.vocab), np.float32)
        for t in tokens:
            i = self.vocab.index_of(t)
            if i >= 0:
                row[i] += 1.0
        return row


class TfidfVectorizer(BaseTextVectorizer):
    """TF-IDF weights (reference TfidfVectorizer: tf * log(N/df))."""

    def _row(self, tokens):
        row = np.zeros(len(self.vocab), np.float32)
        if not tokens:
            return row
        for t in tokens:
            i = self.vocab.index_of(t)
            if i >= 0:
                row[i] += 1.0
        row /= max(len(tokens), 1)
        return row * self._idf
