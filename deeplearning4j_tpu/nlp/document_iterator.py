"""Document iterators: whole-document streams for vectorizers and doc2vec.

Parity: reference `text/documentiterator/*` — `DocumentIterator` (InputStream
per document), `FileDocumentIterator` (one file = one document),
`LabelAwareDocumentIterator` variants.  Documents here are strings (the
tokenizer SPI consumes text, not streams).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Iterator, List, Optional, Sequence, Tuple


class DocumentIterator:
    """SPI: iterate whole documents (reference DocumentIterator)."""

    def __iter__(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CollectionDocumentIterator(DocumentIterator):
    def __init__(self, documents: Sequence[str]):
        self.documents = list(documents)

    def __iter__(self) -> Iterator[str]:
        return iter(self.documents)


class FileDocumentIterator(DocumentIterator):
    """One file under `root` = one document (reference
    FileDocumentIterator.java)."""

    def __init__(self, root: os.PathLike, suffix: str = ""):
        self.root = Path(root)
        self.suffix = suffix

    def _files(self) -> List[Path]:
        return sorted(p for p in self.root.rglob(f"*{self.suffix}")
                      if p.is_file())

    def __iter__(self) -> Iterator[str]:
        for p in self._files():
            yield p.read_text(errors="replace")


class LabelAwareDocumentIterator(DocumentIterator):
    """Documents + labels; directory mode labels each document with its
    parent directory name (the standard corpus-on-disk layout)."""

    def __init__(self, documents: Optional[Sequence[str]] = None,
                 labels: Optional[Sequence[str]] = None,
                 root: Optional[os.PathLike] = None, suffix: str = ""):
        if root is not None:
            paths = sorted(p for p in Path(root).rglob(f"*{suffix}")
                           if p.is_file())
            self._docs = [p.read_text(errors="replace") for p in paths]
            self._labels = [p.parent.name for p in paths]
        else:
            if documents is None or labels is None:
                raise ValueError("need documents+labels or root")
            if len(documents) != len(labels):
                raise ValueError("documents/labels length mismatch")
            self._docs = list(documents)
            self._labels = list(labels)
        self._pos = 0

    def __iter__(self) -> Iterator[str]:
        for d, _ in self.pairs():
            yield d

    def pairs(self) -> Iterator[Tuple[str, str]]:
        return iter(zip(self._docs, self._labels))

    def label_set(self) -> List[str]:
        return sorted(set(self._labels))
