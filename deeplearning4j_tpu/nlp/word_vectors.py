"""WordVectors query API: similarity / nearest neighbours over an
embedding matrix.

Parity: reference `models/embeddings/wordvectors/WordVectorsImpl.java`
(540 LoC — cosine `similarity()`, `wordsNearest()`) and the lookup-table
accessors. Cosine top-k runs as one jitted matmul over the normalised
matrix — the MXU does the scan the reference did row by row.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache


class WordVectors:
    """Embedding matrix + vocab with the reference's query surface."""

    def __init__(self, vocab: VocabCache, vectors: np.ndarray):
        self.vocab = vocab
        self.syn0 = np.asarray(vectors, np.float32)
        self.vector_length = int(self.syn0.shape[1])
        self._norms: Optional[np.ndarray] = None

    # -- accessors ---------------------------------------------------------
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return self.syn0[i] if i >= 0 else None

    def __contains__(self, word: str) -> bool:
        return word in self.vocab

    def _normed(self) -> np.ndarray:
        if self._norms is None or self._norms.shape != self.syn0.shape:
            n = np.linalg.norm(self.syn0, axis=1, keepdims=True)
            self._norms = self.syn0 / np.maximum(n, 1e-12)
        return self._norms

    # -- queries (reference WordVectorsImpl) -------------------------------
    def similarity(self, w1: str, w2: str) -> float:
        v1, v2 = self.get_word_vector(w1), self.get_word_vector(w2)
        if v1 is None or v2 is None:
            return float("nan")
        denom = np.linalg.norm(v1) * np.linalg.norm(v2)
        return float(np.dot(v1, v2) / max(denom, 1e-12))

    def words_nearest(self, word_or_vec, top_n: int = 10,
                      exclude: Sequence[str] = ()) -> List[str]:
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            if vec is None:
                return []
            exclude = tuple(exclude) + (word_or_vec,)
        else:
            vec = np.asarray(word_or_vec, np.float32)
        normed = self._normed()
        q = vec / max(np.linalg.norm(vec), 1e-12)
        sims = np.array(jnp.dot(jnp.asarray(normed), jnp.asarray(q)))
        for w in exclude:
            i = self.vocab.index_of(w)
            if i >= 0:
                sims[i] = -np.inf
        top = np.argsort(-sims)[:top_n]
        return [self.vocab.word_at(int(i)) for i in top if np.isfinite(sims[i])]

    def analogy(self, a: str, b: str, c: str, top_n: int = 5) -> List[str]:
        """a:b :: c:? — the classic king-queen probe."""
        va, vb, vc = (self.get_word_vector(w) for w in (a, b, c))
        if va is None or vb is None or vc is None:
            return []
        return self.words_nearest(vb - va + vc, top_n=top_n,
                                  exclude=(a, b, c))
