"""Constituency trees: structure, PTB-bracket parsing, binarization, and
compilation to padded device programs.

Parity: reference `nn/layers/feedforward/autoencoder/recursive/Tree.java`
(485 LoC) and `text/corpora/treeparser/TreeParser.java` (UIMA/OpenNLP
constituency parsing → Tree). Here trees parse from Penn-Treebank bracket
strings (the format the reference's sentiment fixtures use) or build as
right-branching binarizations of plain token lists.

The TPU-critical piece is `compile_trees`: a static-shape compiler cannot
recurse over Python tree objects, so each tree becomes a POST-ORDER program
over a node buffer — arrays (is_leaf, word, left, right, label, mask)
padded to a common length — which `lax.scan` executes on device
(models/rntn.py). This replaces the reference's per-node Java recursion
(`RNTN.forwardPropagateTree:426`) with one batched scan.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np


@dataclass
class Tree:
    label: Optional[int] = None          # class label (e.g. sentiment 0-4)
    word: Optional[str] = None           # set on leaves
    children: List["Tree"] = field(default_factory=list)

    def is_leaf(self) -> bool:
        return not self.children

    def leaves(self) -> List["Tree"]:
        if self.is_leaf():
            return [self]
        out = []
        for c in self.children:
            out.extend(c.leaves())
        return out

    def tokens(self) -> List[str]:
        return [l.word for l in self.leaves()]

    def nodes(self) -> List["Tree"]:
        """Post-order traversal (children before parents)."""
        out = []
        for c in self.children:
            out.extend(c.nodes())
        out.append(self)
        return out

    def depth(self) -> int:
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children)

    def binarize(self) -> "Tree":
        """Left-factor n-ary nodes into binary ones (the RNTN combine is
        strictly binary, RNTN.java:344)."""
        if self.is_leaf():
            return Tree(label=self.label, word=self.word)
        kids = [c.binarize() for c in self.children]
        if len(kids) == 1:
            only = kids[0]
            # collapse unary chains, keep the outermost label
            return Tree(label=self.label if self.label is not None
                        else only.label, word=only.word,
                        children=only.children)
        node = kids[0]
        for right in kids[1:-1]:
            node = Tree(label=self.label, children=[node, right])
        return Tree(label=self.label, children=[node, kids[-1]])


def parse_ptb(s: str) -> Tree:
    """Parse one Penn-Treebank-style bracketed tree, e.g.
    ``(3 (2 good) (3 (2 not) (1 bad)))`` — numeric labels, words at
    leaves (the SST format the reference's sentiment corpus uses)."""
    tokens = s.replace("(", " ( ").replace(")", " ) ").split()
    pos = 0

    def rec() -> Tree:
        nonlocal pos
        assert tokens[pos] == "(", f"expected ( at {pos}"
        pos += 1
        label: Optional[int] = None
        if tokens[pos] not in "()":
            try:
                label = int(tokens[pos])
            except ValueError:
                label = None  # syntactic category labels are dropped
            pos += 1
        node = Tree(label=label)
        while tokens[pos] != ")":
            if tokens[pos] == "(":
                node.children.append(rec())
            else:
                node.word = tokens[pos]
                pos += 1
        pos += 1
        return node

    tree = rec()
    if pos != len(tokens):
        raise ValueError(f"trailing tokens in tree string: {tokens[pos:]}")
    return tree


def right_branching(tokens: Sequence[str], label: int = 0) -> Tree:
    """Binary tree over a plain sentence when no parse exists (replaces the
    reference's dependency on an external constituency parser)."""
    if not tokens:
        raise ValueError("empty sentence")
    node = Tree(label=label, word=tokens[-1])
    for w in reversed(tokens[:-1]):
        node = Tree(label=label, children=[Tree(label=label, word=w), node])
    return node


@dataclass
class TreeProgram:
    """Padded post-order programs for a batch of trees (device arrays)."""

    is_leaf: np.ndarray   # [B, N] int32 1/0
    word: np.ndarray      # [B, N] int32 vocab index (0 where internal/pad)
    left: np.ndarray      # [B, N] int32 buffer index of left child
    right: np.ndarray     # [B, N] int32 buffer index of right child
    label: np.ndarray     # [B, N] int32 class label (0 where absent)
    mask: np.ndarray      # [B, N] float32 1 for real nodes
    labeled: np.ndarray   # [B, N] float32 1 where the node CARRIES a label
    root: np.ndarray      # [B] int32 buffer index of the root
    n_nodes: int

    def __len__(self) -> int:
        return self.is_leaf.shape[0]


def compile_trees(trees: Sequence[Tree], word_index,
                  max_nodes: Optional[int] = None,
                  unk_index: int = 0) -> TreeProgram:
    """Binarized trees → post-order programs, padded to a common length.

    word_index: dict word→int or callable. Labels default to 0 when a node
    carries none; the `labeled` array records which nodes actually carry
    one (label=None ⇒ labeled=0), so losses can supervise only labeled
    nodes — e.g. root-only sentence classification.
    """
    lookup = (word_index if callable(word_index)
              else lambda w: word_index.get(w, unk_index))
    progs = []
    for t in trees:
        t = t.binarize()
        nodes = t.nodes()
        if any(len(n.children) not in (0, 2) for n in nodes):
            raise ValueError("binarize() must yield strictly binary trees")
        index = {id(n): i for i, n in enumerate(nodes)}
        rows = []
        for n in nodes:
            has = int(n.label is not None)
            if n.is_leaf():
                rows.append((1, lookup(n.word), 0, 0, n.label or 0, has))
            else:
                l, r = (index[id(c)] for c in n.children)
                rows.append((0, 0, l, r, n.label or 0, has))
        progs.append(rows)

    n = max_nodes or max(len(p) for p in progs)
    if max(len(p) for p in progs) > n:
        raise ValueError(f"tree with {max(len(p) for p in progs)} nodes "
                         f"exceeds max_nodes={n}")
    b = len(progs)
    arrs = {k: np.zeros((b, n), np.int32)
            for k in ("is_leaf", "word", "left", "right", "label")}
    mask = np.zeros((b, n), np.float32)
    labeled = np.zeros((b, n), np.float32)
    root = np.zeros(b, np.int32)
    for i, rows in enumerate(progs):
        for j, (lf, w, l, r, lab, has) in enumerate(rows):
            arrs["is_leaf"][i, j] = lf
            arrs["word"][i, j] = w
            arrs["left"][i, j] = l
            arrs["right"][i, j] = r
            arrs["label"][i, j] = lab
            labeled[i, j] = has
        mask[i, :len(rows)] = 1.0
        root[i] = len(rows) - 1
    return TreeProgram(arrs["is_leaf"], arrs["word"], arrs["left"],
                       arrs["right"], arrs["label"], mask, labeled, root, n)
