"""NLP / embedding models.

Parity target: reference `deeplearning4j-scaleout/deeplearning4j-nlp`
(SURVEY §2.2, 18.8k LoC) — Word2Vec (skip-gram, hierarchical softmax +
negative sampling), GloVe, ParagraphVectors, tokenizer/sentence/document
iterator SPIs, vocab cache + Huffman coding, TF-IDF/BoW vectorizers, and
word2vec-C-compatible vector serialization.

TPU-first re-design (SURVEY §7 hard part #1): the reference trains
embeddings with sparse, racy, per-word-pair saxpy updates across a thread
pool (`InMemoryLookupTable.iterateSample:192`, Hogwild). Here training is
dense-batched and deterministic: the host streams integer-encoded skip-gram
pairs; ONE jitted step gathers embedding rows, evaluates the HS/NEG
objective for the whole batch, and applies the sparse update through XLA's
gather/scatter-add autodiff — the MXU sees big batched matmuls instead of
rank-1 updates.
"""

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizer,
    DefaultTokenizerFactory,
    EndingPreProcessor,
    InputHomogenization,
    NGramTokenizer,
    NGramTokenizerFactory,
)
from deeplearning4j_tpu.nlp.sentence_iterator import (
    CollectionSentenceIterator,
    FileSentenceIterator,
    LabelAwareSentenceIterator,
    LineSentenceIterator,
)
from deeplearning4j_tpu.nlp.document_iterator import (
    CollectionDocumentIterator,
    DocumentIterator,
    FileDocumentIterator,
    LabelAwareDocumentIterator,
)
from deeplearning4j_tpu.nlp.annotators import (
    SWN3,
    HmmPosTagger,
    TreeParser,
    TreeVectorizer,
)
from deeplearning4j_tpu.nlp.word2vec_iterator import Word2VecDataSetIterator
from deeplearning4j_tpu.nlp.vocab import Huffman, VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.vectorizers import CountVectorizer, TfidfVectorizer
from deeplearning4j_tpu.nlp.serde import (
    load_txt_vectors,
    read_binary_model,
    write_binary_model,
    write_word_vectors,
)
from deeplearning4j_tpu.nlp.tree import (
    Tree,
    compile_trees,
    parse_ptb,
    right_branching,
)
from deeplearning4j_tpu.nlp.news import (
    NewsGroupsDataSetIterator,
    news_corpus,
    news_dataset,
)

__all__ = [
    "DefaultTokenizer", "NGramTokenizer", "DefaultTokenizerFactory",
    "NGramTokenizerFactory", "EndingPreProcessor", "InputHomogenization",
    "CollectionSentenceIterator", "FileSentenceIterator",
    "LineSentenceIterator", "LabelAwareSentenceIterator",
    "VocabWord", "VocabCache", "Huffman",
    "Word2Vec", "Glove", "ParagraphVectors",
    "CountVectorizer", "TfidfVectorizer",
    "write_word_vectors", "load_txt_vectors", "write_binary_model",
    "read_binary_model",
    "Tree", "parse_ptb", "right_branching", "compile_trees",
    "DocumentIterator", "CollectionDocumentIterator",
    "FileDocumentIterator", "LabelAwareDocumentIterator",
    "HmmPosTagger", "SWN3", "TreeParser", "TreeVectorizer",
    "Word2VecDataSetIterator",
    "news_corpus", "news_dataset", "NewsGroupsDataSetIterator",
]
