"""Word-vector serialization: word2vec-C compatible text and binary formats.

Parity: reference `models/embeddings/loader/WordVectorSerializer.java:76` —
`writeWordVectors:335` (text: `word v1 v2 ...` per line),
`loadGoogleModel` (binary: header `V D\\n` then `word<space><D float32s>`),
`loadTxt:422`. Files written here load in gensim/word2vec-C and vice versa.
"""

from __future__ import annotations

import os

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word_vectors import WordVectors


def write_word_vectors(wv: WordVectors, path: os.PathLike) -> None:
    """Text format (reference writeWordVectors:335): one `word floats...`
    line per word, no header (reference writes no header either)."""
    with open(path, "w", encoding="utf-8") as f:
        for i in range(len(wv.vocab)):
            word = wv.vocab.word_at(i)
            vals = " ".join(f"{v:.6g}" for v in wv.syn0[i])
            f.write(f"{word} {vals}\n")


def load_txt_vectors(path: os.PathLike) -> WordVectors:
    """Load text vectors (reference loadTxt:422). Tolerates an optional
    gensim-style `V D` header line."""
    vocab = VocabCache()
    rows = []
    with open(path, encoding="utf-8") as f:
        first = f.readline().split()
        if len(first) == 2 and all(t.isdigit() for t in first):
            pass  # header line; skip
        elif first:
            vocab.add(first[0])
            rows.append([float(v) for v in first[1:]])
        for line in f:
            parts = line.split()  # robust to repeated/trailing whitespace
            if len(parts) < 2:
                continue
            vocab.add(parts[0])
            rows.append([float(v) for v in parts[1:]])
    return WordVectors(vocab, np.asarray(rows, np.float32))


def write_binary_model(wv: WordVectors, path: os.PathLike) -> None:
    """Google word2vec binary format (header `V D\\n`, then per word:
    `word ` + D little-endian float32s + `\\n`)."""
    with open(path, "wb") as f:
        V, D = wv.syn0.shape
        f.write(f"{V} {D}\n".encode())
        for i in range(V):
            f.write(wv.vocab.word_at(i).encode("utf-8") + b" ")
            f.write(wv.syn0[i].astype("<f4").tobytes())
            f.write(b"\n")


def read_binary_model(path: os.PathLike) -> WordVectors:
    """Reference `loadGoogleModel(binary=true)`."""
    vocab = VocabCache()
    with open(path, "rb") as f:
        header = f.readline().decode("utf-8").split()
        V, D = int(header[0]), int(header[1])
        vecs = np.empty((V, D), np.float32)
        for i in range(V):
            word = bytearray()
            while True:
                ch = f.read(1)
                if ch in (b" ", b""):
                    break
                if ch != b"\n":
                    word += ch
            vocab.add(word.decode("utf-8"))
            vecs[i] = np.frombuffer(f.read(4 * D), "<f4")
            nl = f.read(1)
            if nl not in (b"\n", b""):  # some writers omit the newline
                f.seek(-1, 1)
    return WordVectors(vocab, vecs)
