"""Labeled news-corpus loader: directory-of-label-dirs -> TF-IDF/BoW DataSet.

Parity: reference `datasets/loader/ReutersNewsGroupsLoader.java` (downloads
the 20-Newsgroups archive, walks one subdirectory per label, vectorizes
with TfidfVectorizer/BagOfWordsVectorizer) and its thin iterator wrapper
`datasets/iterator/ReutersNewsGroupsDataSetIterator.java`.

TPU-era differences: the corpus root is pluggable (any directory whose
immediate subdirectories are labels and whose files are documents), the
download is gated behind the shared dataset downloader (zero-egress hosts
fall back to a small bundled corpus with a loud warning), and the result is
a dense `DataSet` ready for `MultiLayerNetwork.fit` / the SPMD trainers.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.downloader import (
    cache_dir,
    download,
    downloads_allowed,
    warn_fallback,
)
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.nlp.vectorizers import CountVectorizer, TfidfVectorizer

NEWSGROUP_URL = "http://qwone.com/~jason/20Newsgroups/20news-18828.tar.gz"

# Tiny bundled fallback corpus (three topics, clearly separable vocabulary)
# used only when no corpus directory exists and downloads are unavailable.
_FALLBACK = {
    "sport": [
        "the team won the match with a late goal",
        "the coach praised the players after the game",
        "fans cheered as the striker scored twice",
        "the league title race goes to the final match",
    ],
    "tech": [
        "the new chip doubles memory bandwidth",
        "the compiler fuses kernels for faster inference",
        "engineers shipped a faster network driver",
        "the processor schedules threads across cores",
    ],
    "finance": [
        "the market rallied as rates fell",
        "investors bought bonds after the earnings report",
        "the bank raised its growth forecast",
        "shares climbed on strong quarterly profits",
    ],
}


def _round_robin(streams: dict, num_examples: Optional[int]
                 ) -> Tuple[List[str], List[str]]:
    """Interleave {label: iterator-of-documents} round-robin so a
    ``num_examples`` cap yields a class-balanced subset instead of
    exhausting the alphabetically-first label.  Iterators may yield None
    for unreadable items (skipped without consuming the cap)."""
    docs, doc_labels = [], []
    live = sorted(streams)
    while live and (num_examples is None or len(docs) < num_examples):
        for label in list(live):
            if num_examples is not None and len(docs) >= num_examples:
                break
            doc = next(streams[label], _round_robin)  # sentinel = exhausted
            if doc is _round_robin:
                live.remove(label)
                continue
            if doc is None:
                continue
            docs.append(doc)
            doc_labels.append(label)
    return docs, doc_labels


def _read_or_none(f: Path) -> Optional[str]:
    try:
        return f.read_text(errors="replace")
    except OSError:
        return None


def _walk_label_dirs(root: Path, num_examples: Optional[int]
                     ) -> Tuple[List[str], List[str], List[str]]:
    """(documents, doc_labels, label_names) from one-subdir-per-label."""
    labels = sorted(d.name for d in root.iterdir() if d.is_dir())
    streams = {
        label: (_read_or_none(f)
                for f in sorted((root / label).rglob("*")) if f.is_file())
        for label in labels
    }
    docs, doc_labels = _round_robin(streams, num_examples)
    return docs, doc_labels, labels


def _fetch_newsgroups() -> Optional[Path]:
    """Download + extract 20news into the dataset cache; None if offline."""
    root = cache_dir("newsgroups")
    extracted = root / "20news-18828"
    if extracted.is_dir():
        return extracted
    if not downloads_allowed():
        return None
    archive = root / "20news-18828.tar.gz"
    try:
        download(NEWSGROUP_URL, archive)
        import shutil
        import tarfile

        # Extract to a temp dir, then atomically rename — an interrupted
        # extractall must not leave a half-populated tree that later runs
        # would silently treat as the full corpus.
        tmp = root / ".extract.tmp"
        if tmp.exists():
            shutil.rmtree(tmp)
        with tarfile.open(archive) as tf:
            tf.extractall(tmp, filter="data")
        (tmp / "20news-18828").rename(extracted)
        shutil.rmtree(tmp, ignore_errors=True)
    except Exception as e:  # noqa: BLE001 - any failure -> offline fallback
        warn_fallback("newsgroups", f"download failed: {e}",
                      "bundled mini news corpus")
        return None
    return extracted if extracted.is_dir() else None


def news_corpus(root: Optional[os.PathLike] = None,
                num_examples: Optional[int] = None
                ) -> Tuple[List[str], List[str], List[str]]:
    """(documents, doc_labels, label_names) for a labeled news corpus.

    Resolution order: explicit ``root`` > $DL4J_NEWS_DIR > cached/downloaded
    20-Newsgroups > bundled mini corpus (loud warning).
    """
    if root is not None:
        if not Path(root).is_dir():
            raise FileNotFoundError(f"news corpus root not found: {root}")
        if not any(d.is_dir() for d in Path(root).iterdir()):
            raise ValueError(
                f"no label subdirectories under {root}: expected one "
                f"subdirectory per label containing document files")
        return _walk_label_dirs(Path(root), num_examples)
    env_root = os.environ.get("DL4J_NEWS_DIR")
    if env_root:
        if Path(env_root).is_dir() and any(
                d.is_dir() for d in Path(env_root).iterdir()):
            return _walk_label_dirs(Path(env_root), num_examples)
        warn_fallback(
            "newsgroups",
            f"$DL4J_NEWS_DIR={env_root} is not a directory with label "
            f"subdirectories", "downloaded/bundled corpus")
    fetched = _fetch_newsgroups()
    if fetched is not None:
        return _walk_label_dirs(fetched, num_examples)
    warn_fallback("newsgroups", "no corpus dir and downloads unavailable",
                  "bundled mini news corpus")
    docs, doc_labels = _round_robin(
        {label: iter(texts) for label, texts in _FALLBACK.items()},
        num_examples)
    return docs, doc_labels, sorted(_FALLBACK)


def news_dataset(root: Optional[os.PathLike] = None, tfidf: bool = True,
                 num_examples: Optional[int] = None,
                 min_word_frequency: int = 1,
                 max_features: Optional[int] = 10_000) -> DataSet:
    """Vectorized news corpus as a DataSet (ReutersNewsGroupsLoader parity:
    tfidf=True -> TfidfVectorizer, else BagOfWords/CountVectorizer).

    ``max_features`` caps the vocabulary at the top-N frequent terms so the
    dense feature matrix stays bounded (the full 20news vocabulary would be
    ~100k terms — ~7 GB dense); pass None for the uncapped reference
    behavior."""
    docs, doc_labels, labels = news_corpus(root, num_examples)
    vec_cls = TfidfVectorizer if tfidf else CountVectorizer
    vec = vec_cls(min_word_frequency=min_word_frequency,
                  max_features=max_features)
    features = np.asarray(vec.fit_transform(docs), dtype=np.float32)
    index = {l: i for i, l in enumerate(labels)}
    y = np.eye(len(labels), dtype=np.float32)[[index[l] for l in doc_labels]]
    return DataSet(features, y)


class NewsGroupsDataSetIterator(ArrayDataSetIterator):
    """Batched iterator over the vectorized news corpus (reference
    ReutersNewsGroupsDataSetIterator.java)."""

    def __init__(self, batch: int, num_examples: Optional[int] = None,
                 tfidf: bool = True, root: Optional[os.PathLike] = None):
        ds = news_dataset(root, tfidf=tfidf, num_examples=num_examples)
        super().__init__(ds.features, ds.labels, batch)
