"""GloVe: co-occurrence counting + weighted least squares embeddings.

Parity: reference `models/glove/Glove.java:60` (fit():109),
`CoOccurrences.java` (symmetric window counts weighted 1/distance) and
`GloveWeightLookupTable.java` (per-element AdaGrad on the weighted
least-squares objective, xMax=100, alpha=0.75).

TPU-first: co-occurrence counting stays on host (a dict pass over the
corpus — IO-bound); training runs on device as jitted batched AdaGrad steps
over shuffled COO triples (i, j, X_ij): gathers → fused elementwise →
scatter-add gradients. The reference updates one pair at a time; here every
step updates `batch_size` pairs dense-batched.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from deeplearning4j_tpu.parallel.mesh import (
    round_batch_to_mesh,
    sparse_allgather_step,
)

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word_vectors import WordVectors


class CoOccurrences:
    """Symmetric windowed co-occurrence counts, weight 1/distance
    (reference CoOccurrences.java:357)."""

    def __init__(self, window: int = 15):
        self.window = window
        self.counts: Dict[Tuple[int, int], float] = defaultdict(float)

    def fit(self, encoded: Sequence[np.ndarray]) -> "CoOccurrences":
        """Vectorized: one numpy pass per offset d (weight 1/d, both
        directions) instead of a Python loop per (token, offset); weighted
        counts aggregate via np.unique on packed (row, col) keys."""
        sents = [np.asarray(s, np.int64) for s in encoded if len(s)]
        if not sents:
            return self
        flat = np.concatenate(sents)
        sid = np.repeat(np.arange(len(sents)), [len(s) for s in sents])
        n = len(flat)
        vmax = int(flat.max()) + 1
        longest = max(len(s) for s in sents)
        # Aggregate per offset (peak memory O(n), not O(window*n)); cap d
        # at the longest sentence — larger offsets can never match.
        for d in range(1, min(self.window, longest - 1) + 1):
            left = np.arange(n - d)
            ok = sid[left] == sid[left + d]
            a, b = flat[left + d][ok], flat[left][ok]   # (later, earlier)
            if not len(a):
                continue
            packed = np.concatenate([a * vmax + b, b * vmax + a])
            uniq, inv = np.unique(packed, return_inverse=True)
            sums = np.bincount(inv, minlength=len(uniq)) / d
            for key, total in zip(uniq, sums):
                self.counts[(int(key // vmax),
                             int(key % vmax))] += float(total)
        return self

    def to_coo(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if not self.counts:
            return (np.zeros(0, np.int32),) * 2 + (np.zeros(0, np.float32),)
        items = list(self.counts.items())
        ij = np.asarray([k for k, _ in items], np.int32)
        x = np.asarray([v for _, v in items], np.float32)
        return ij[:, 0], ij[:, 1], x


class Glove(WordVectors):
    """GloVe embeddings (reference defaults: xMax=100, alpha=0.75,
    learning rate 0.05 AdaGrad)."""

    def __init__(self,
                 vector_length: int = 100,
                 window: int = 15,
                 min_word_frequency: int = 1,
                 learning_rate: float = 0.05,
                 x_max: float = 100.0,
                 alpha: float = 0.75,
                 batch_size: int = 4096,
                 epochs: int = 25,
                 seed: int = 42,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 mesh=None):
        self.window = window
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.mesh = mesh  # jax Mesh: shard COO batches over its 1st axis
        if mesh is not None:
            batch_size = round_batch_to_mesh(batch_size, mesh)
        self.batch_size = batch_size
        self.epochs = epochs
        self.seed = seed
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        super().__init__(VocabCache(min_word_frequency=min_word_frequency),
                         np.zeros((0, vector_length), np.float32))
        self.vector_length = vector_length

    def _build_step(self):
        """Sparse-update AdaGrad WLS step: per-entry gradients are
        closed-form for the TOUCHED rows and applied as scatter-adds —
        O(B·D) work per step instead of walking all V rows of four
        tables (the reference's per-pair sequential AdaGrad,
        `GloveWeightLookupTable.java`, batched).  Accumulators are
        scattered FIRST, so every entry divides by the denominator that
        includes the whole batch's mass for its row."""
        x_max, alpha = self.x_max, self.alpha
        lr = self.learning_rate
        eps = 1e-8

        def entry_grads(params, ii, jj, xx, valid):
            w, wc, b, bc = params
            diff = (jnp.sum(w[ii] * wc[jj], axis=1) + b[ii] + bc[jj]
                    - jnp.log(xx))
            fx = jnp.minimum((xx / x_max) ** alpha, 1.0)
            # `valid` zeroes rows padded in to keep one compiled shape.
            e = valid * fx * diff                          # [B]
            loss = 0.5 * jnp.sum(e * diff)                 # valid^2==valid
            return loss, (e[:, None] * wc[jj],             # d/dw[ii]
                          e[:, None] * w[ii],              # d/dwc[jj]
                          e, e)                            # d/db, d/dbc

        def deltas(params, adagrad, ii, jj, xx, valid):
            loss, grads = entry_grads(params, ii, jj, xx, valid)
            # rows ride along in aux so the mesh path gathers them with
            # their grads (the sharded ii/jj args are per-shard slices).
            return loss, (ii, jj, grads)

        def apply(params, adagrad, aux):
            ii, jj, grads = aux
            rows = (ii, jj, ii, jj)
            new_params, new_ada = [], []
            for p, h, r, g in zip(params, adagrad, rows, grads):
                h = h.at[r].add(g * g)
                new_params.append(
                    p.at[r].add(-lr * g / jnp.sqrt(h[r] + eps)))
                new_ada.append(h)
            return tuple(new_params), tuple(new_ada)

        # Mesh-parallel (same design as Word2Vec mesh=): COO batch
        # sharded over the data axis, params replicated, the sparse
        # (row, grad) entries all_gathered over ICI — O(B·D) comms, not
        # a dense psum — and every replica applies one identical scatter
        # (the TPU-native distributed GloVe, replacing the reference's
        # Spark driver-fold, spark Glove.java:241).
        step = sparse_allgather_step(self.mesh, deltas, apply, n_state=2,
                                     n_sharded=4)
        return jax.jit(step, donate_argnums=(0, 1))

    def _tokenize_all(self, sentences):
        return [self.tokenizer.tokenize(s) if isinstance(s, str)
                else list(s) for s in sentences]

    def _init_params(self) -> None:
        V, D = len(self.vocab), self.vector_length
        rng = np.random.default_rng(self.seed)
        self._params = tuple(jnp.asarray(a) for a in (
            (rng.random((V, D)) - 0.5).astype(np.float32) / D,   # w
            (rng.random((V, D)) - 0.5).astype(np.float32) / D,   # w-context
            np.zeros(V, np.float32),                             # b
            np.zeros(V, np.float32)))                            # b-context
        self._adagrad = tuple(jnp.zeros_like(p) for p in self._params)
        self._step = self._build_step()

    def _train(self, ii, jj, xx, epochs: int, rng) -> List[float]:
        B = self.batch_size
        order = np.arange(len(xx))
        losses = []
        params, adagrad = self._params, self._adagrad
        for _ in range(epochs):
            rng.shuffle(order)
            # keep every minibatch loss ON DEVICE (JIT107): a float()
            # per minibatch blocks the host every step, so back-to-back
            # batches could never pipeline; the syncs all land at the
            # epoch boundary, summed on host in float64 so the reported
            # curve matches the pre-pipelining numbers
            epoch_losses = []
            for s in range(0, len(order), B):
                sel = order[s:s + B]
                valid = np.ones(B, np.float32)
                if len(sel) < B:  # pad to keep one compiled shape
                    valid[len(sel):] = 0.0
                    pad = np.arange(B - len(sel)) % len(order)
                    sel = np.concatenate([sel, order[pad]])
                params, adagrad, loss = self._step(
                    params, adagrad, jnp.asarray(ii[sel]),
                    jnp.asarray(jj[sel]), jnp.asarray(xx[sel]),
                    jnp.asarray(valid))
                epoch_losses.append(loss)
            losses.append(sum(float(l) for l in epoch_losses))
        self._params, self._adagrad = params, adagrad
        self._refresh_syn0()
        return losses

    def _refresh_syn0(self) -> None:
        w, wc, _, _ = (np.asarray(p) for p in self._params)
        self.syn0 = (w + wc).astype(np.float32)  # GloVe paper: sum both sets
        self._norms = None

    def fit(self, sentences) -> "Glove":
        token_lists = self._tokenize_all(sentences)
        if len(self.vocab) == 0:
            self.vocab.fit(token_lists)
        if len(self.vocab) == 0:
            raise ValueError("empty vocabulary")
        encoded = [self.vocab.encode(t) for t in token_lists]
        ii, jj, xx = CoOccurrences(self.window).fit(encoded).to_coo()
        if len(xx) == 0:
            raise ValueError("no co-occurrences — corpus too small")
        self._init_params()
        self.losses = self._train(ii, jj, xx, self.epochs,
                                  np.random.default_rng(self.seed))
        return self

    def partial_fit(self, sentences, epochs: int = 1) -> "Glove":
        """Continue AdaGrad training on one sentence batch against the
        CURRENT weights (vocab must already be built) — the incremental
        unit a distributed GlovePerformer executes per job."""
        if len(self.vocab) == 0:
            raise ValueError("build vocab first (call fit once)")
        if getattr(self, "_params", None) is None:
            self._init_params()
        encoded = [self.vocab.encode(t)
                   for t in self._tokenize_all(sentences)]
        ii, jj, xx = CoOccurrences(self.window).fit(encoded).to_coo()
        if len(xx) == 0:
            return self
        self._train(ii, jj, xx, epochs, np.random.default_rng(self.seed))
        return self

    train = fit
