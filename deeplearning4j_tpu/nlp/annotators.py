"""Text annotators: HMM POS tagging, SentiWordNet sentiment scoring, and
raw-text constituency parsing.

Parity: the reference's UIMA annotator suite —
`text/annotator/PoStagger.java:248` (OpenNLP POS model behind a UIMA
AnalysisEngine), `text/corpora/sentiwordnet/SWN3.java:243` (SentiWordNet
3.0 lexicon scorer with rank-weighted sense averaging and threshold
classification), and `text/corpora/treeparser/TreeParser.java:427`
(OpenNLP chunker/parser → Tree).  The TPU redesign drops the UIMA/OpenNLP
machinery: tagging is an HMM decoded by the jitted Viterbi scan
(utils/viterbi.py) so the per-token argmax runs on device, the lexicon
scorer is pure table lookups, and parsing is a deterministic POS-driven
chunker producing the same `Tree` objects RNTN consumes.
"""

from __future__ import annotations

import re
from collections import Counter, defaultdict
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.tree import Tree
from deeplearning4j_tpu.utils.viterbi import Viterbi

# ---------------------------------------------------------------------------
# HMM POS tagger on the jitted Viterbi
# ---------------------------------------------------------------------------

TaggedSentence = Sequence[Tuple[str, str]]


class HmmPosTagger:
    """Bigram HMM part-of-speech tagger.

    Train with maximum-likelihood counts + add-k smoothing; decode with
    the device Viterbi (`utils/viterbi.py`, parity `util/Viterbi.java`).
    Unknown words fall back to a suffix-keyed emission table (the
    classic open-class guesser), so raw corpora tag without an OOV crash
    — the capability PoStagger.java got from its pretrained OpenNLP
    model.
    """

    def __init__(self, smoothing: float = 0.1, suffix_len: int = 3):
        self.smoothing = smoothing
        self.suffix_len = suffix_len
        self.tags: List[str] = []
        self._tag_idx: Dict[str, int] = {}
        self._emit: Dict[str, np.ndarray] = {}
        self._suffix: Dict[str, np.ndarray] = {}
        self._open_class: Optional[np.ndarray] = None
        self._viterbi: Optional[Viterbi] = None

    def fit(self, tagged_sentences: Sequence[TaggedSentence]
            ) -> "HmmPosTagger":
        trans = Counter()
        emit = defaultdict(Counter)
        suffix = defaultdict(Counter)
        initial = Counter()
        tag_counts = Counter()
        for sent in tagged_sentences:
            prev = None
            for word, tag in sent:
                w = word.lower()
                tag_counts[tag] += 1
                emit[w][tag] += 1
                suffix[w[-self.suffix_len:]][tag] += 1
                if prev is None:
                    initial[tag] += 1
                else:
                    trans[(prev, tag)] += 1
                prev = tag
        self.tags = sorted(tag_counts)
        self._tag_idx = {t: i for i, t in enumerate(self.tags)}
        n = len(self.tags)
        k = self.smoothing

        tmat = np.full((n, n), k)
        for (a, b), c in trans.items():
            tmat[self._tag_idx[a], self._tag_idx[b]] += c
        tmat /= tmat.sum(axis=1, keepdims=True)

        init = np.full(n, k)
        for t, c in initial.items():
            init[self._tag_idx[t]] += c
        init /= init.sum()

        def to_logvec(counter: Counter) -> np.ndarray:
            v = np.full(n, k)
            for t, c in counter.items():
                v[self._tag_idx[t]] += c
            # P(word|tag) ∝ count(word,tag)/count(tag); constant factors
            # drop out of the argmax
            v = v / np.array([tag_counts[t] + k * n for t in self.tags])
            return np.log(v)

        self._emit = {w: to_logvec(c) for w, c in emit.items()}
        self._suffix = {s: to_logvec(c) for s, c in suffix.items()}
        open_counts = Counter(
            {t: c for t, c in tag_counts.items() if t not in (".", "X")})
        self._open_class = to_logvec(open_counts)
        self._viterbi = Viterbi(np.log(tmat), np.log(init), log_space=True)
        return self

    def _emission(self, word: str) -> np.ndarray:
        w = word.lower()
        if w in self._emit:
            return self._emit[w]
        sfx = self._suffix.get(w[-self.suffix_len:])
        if sfx is not None:
            return sfx
        if re.fullmatch(r"[\d.,:%-]+", w):
            num = self._tag_idx.get("NUM")
            if num is not None:
                v = np.full(len(self.tags), -20.0)
                v[num] = 0.0
                return v
        return self._open_class

    def tag(self, tokens: Sequence[str]) -> List[Tuple[str, str]]:
        """Most likely tag sequence for a tokenized sentence."""
        if self._viterbi is None:
            raise RuntimeError("tagger not fitted")
        if not tokens:
            return []
        log_emit = np.stack([self._emission(t) for t in tokens])
        path, _ = self._viterbi.decode(log_emit, log_space=True)
        return [(tok, self.tags[int(i)]) for tok, i in zip(tokens, path)]

    def tag_text(self, text: str) -> List[Tuple[str, str]]:
        return self.tag(_tokenize(text))


def _tokenize(text: str) -> List[str]:
    return re.findall(r"[A-Za-z]+(?:'[A-Za-z]+)?|\d+(?:[.,]\d+)*|[^\sA-Za-z\d]",
                      text)


# A small embedded tagged corpus (hand-written, universal-ish tagset) so a
# default tagger exists without external downloads — the analog of the
# reference shipping a pretrained OpenNLP model on its classpath.
_SEED_CORPUS_TEXT = """
the/DET quick/ADJ brown/ADJ fox/NOUN jumps/VERB over/ADP the/DET lazy/ADJ dog/NOUN ./.
a/DET small/ADJ cat/NOUN sat/VERB on/ADP the/DET mat/NOUN ./.
she/PRON quickly/ADV reads/VERB a/DET long/ADJ book/NOUN ./.
he/PRON writes/VERB good/ADJ code/NOUN every/DET day/NOUN ./.
the/DET children/NOUN play/VERB in/ADP the/DET park/NOUN ./.
dogs/NOUN and/CONJ cats/NOUN are/VERB friendly/ADJ animals/NOUN ./.
i/PRON love/VERB this/DET great/ADJ movie/NOUN ./.
they/PRON walked/VERB slowly/ADV to/ADP the/DET old/ADJ house/NOUN ./.
we/PRON saw/VERB two/NUM birds/NOUN in/ADP a/DET tall/ADJ tree/NOUN ./.
the/DET weather/NOUN is/VERB very/ADV nice/ADJ today/NOUN ./.
john/NOUN gave/VERB mary/NOUN a/DET red/ADJ apple/NOUN ./.
my/PRON brother/NOUN runs/VERB fast/ADV ./.
the/DET big/ADJ storm/NOUN destroyed/VERB the/DET small/ADJ village/NOUN ./.
students/NOUN study/VERB hard/ADV for/ADP exams/NOUN ./.
she/PRON sings/VERB a/DET beautiful/ADJ song/NOUN ./.
the/DET sun/NOUN rises/VERB in/ADP the/DET east/NOUN ./.
birds/NOUN fly/VERB south/ADV in/ADP winter/NOUN ./.
he/PRON bought/VERB three/NUM new/ADJ books/NOUN yesterday/NOUN ./.
the/DET teacher/NOUN explains/VERB the/DET hard/ADJ lesson/NOUN ./.
a/DET good/ADJ friend/NOUN always/ADV helps/VERB ./.
this/DET terrible/ADJ film/NOUN wastes/VERB your/PRON time/NOUN ./.
the/DET happy/ADJ children/NOUN laughed/VERB loudly/ADV ./.
rain/NOUN falls/VERB softly/ADV on/ADP the/DET roof/NOUN ./.
we/PRON eat/VERB fresh/ADJ bread/NOUN and/CONJ cheese/NOUN ./.
the/DET old/ADJ man/NOUN walks/VERB with/ADP a/DET cane/NOUN ./.
my/PRON sister/NOUN paints/VERB bright/ADJ pictures/NOUN of/ADP flowers/NOUN ./.
the/DET tired/ADJ workers/NOUN finished/VERB the/DET long/ADJ project/NOUN ./.
four/NUM ships/NOUN sailed/VERB across/ADP the/DET calm/ADJ sea/NOUN ./.
she/PRON carefully/ADV opened/VERB the/DET heavy/ADJ wooden/ADJ door/NOUN ./.
the/DET doctor/NOUN and/CONJ the/DET nurse/NOUN help/VERB sick/ADJ patients/NOUN ./.
a/DET strong/ADJ wind/NOUN blew/VERB through/ADP the/DET quiet/ADJ valley/NOUN ./.
they/PRON often/ADV visit/VERB their/PRON grandmother/NOUN in/ADP spring/NOUN ./.
the/DET young/ADJ artist/NOUN draws/VERB beautiful/ADJ portraits/NOUN quickly/ADV ./.
five/NUM students/NOUN answered/VERB the/DET difficult/ADJ question/NOUN correctly/ADV ./.
the/DET river/NOUN flows/VERB slowly/ADV through/ADP the/DET green/ADJ fields/NOUN ./.
he/PRON never/ADV forgets/VERB an/DET important/ADJ meeting/NOUN ./.
the/DET hungry/ADJ wolves/NOUN hunted/VERB near/ADP the/DET dark/ADJ forest/NOUN ./.
our/PRON team/NOUN won/VERB the/DET final/ADJ match/NOUN easily/ADV ./.
a/DET clever/ADJ student/NOUN solves/VERB hard/ADJ problems/NOUN fast/ADV ./.
the/DET baker/NOUN sells/VERB warm/ADJ bread/NOUN every/DET morning/NOUN ./.
six/NUM horses/NOUN ran/VERB across/ADP the/DET open/ADJ plain/NOUN ./.
she/PRON wrote/VERB a/DET short/ADJ letter/NOUN to/ADP her/PRON mother/NOUN ./.
the/DET busy/ADJ market/NOUN opens/VERB early/ADV on/ADP saturday/NOUN ./.
i/PRON usually/ADV drink/VERB hot/ADJ coffee/NOUN with/ADP milk/NOUN ./.
the/DET brave/ADJ firefighter/NOUN saved/VERB the/DET frightened/ADJ child/NOUN ./.
small/ADJ boats/NOUN float/VERB on/ADP the/DET deep/ADJ lake/NOUN ./.
the/DET engineer/NOUN designs/VERB safe/ADJ bridges/NOUN and/CONJ roads/NOUN ./.
you/PRON should/VERB read/VERB this/DET interesting/ADJ article/NOUN ./.
the/DET gray/ADJ clouds/NOUN covered/VERB the/DET bright/ADJ sky/NOUN ./.
seven/NUM trees/NOUN grow/VERB behind/ADP the/DET white/ADJ fence/NOUN ./.
the/DET curious/ADJ tourists/NOUN photographed/VERB the/DET ancient/ADJ castle/NOUN ./.
my/PRON father/NOUN repairs/VERB broken/ADJ clocks/NOUN and/CONJ watches/NOUN ./.
the/DET singer/NOUN performed/VERB a/DET famous/ADJ song/NOUN tonight/NOUN ./.
wild/ADJ geese/NOUN fly/VERB north/ADV in/ADP early/ADJ summer/NOUN ./.
the/DET cook/NOUN prepares/VERB tasty/ADJ soup/NOUN with/ADP fresh/ADJ vegetables/NOUN ./.
eight/NUM players/NOUN practice/VERB on/ADP the/DET muddy/ADJ field/NOUN ./.
she/PRON always/ADV smiles/VERB at/ADP her/PRON little/ADJ brother/NOUN ./.
the/DET lazy/ADJ cat/NOUN sleeps/VERB under/ADP the/DET warm/ADJ blanket/NOUN ./.
a/DET sudden/ADJ noise/NOUN woke/VERB the/DET sleeping/ADJ baby/NOUN ./.
the/DET farmer/NOUN plants/VERB corn/NOUN and/CONJ beans/NOUN in/ADP april/NOUN ./.
we/PRON watched/VERB a/DET wonderful/ADJ film/NOUN last/ADJ night/NOUN ./.
the/DET mechanic/NOUN fixed/VERB the/DET old/ADJ engine/NOUN quickly/ADV ./.
two/NUM eagles/NOUN circled/VERB above/ADP the/DET rocky/ADJ mountain/NOUN ./.
the/DET polite/ADJ waiter/NOUN brought/VERB our/PRON delicious/ADJ dinner/NOUN ./.
heavy/ADJ rain/NOUN flooded/VERB the/DET narrow/ADJ streets/NOUN yesterday/NOUN ./.
the/DET librarian/NOUN quietly/ADV arranges/VERB the/DET new/ADJ books/NOUN ./.
he/PRON proudly/ADV showed/VERB us/PRON his/PRON first/ADJ medal/NOUN ./.
the/DET nervous/ADJ speaker/NOUN forgot/VERB his/PRON opening/ADJ line/NOUN ./.
nine/NUM candles/NOUN burned/VERB on/ADP the/DET birthday/NOUN cake/NOUN ./.
the/DET gardener/NOUN waters/VERB the/DET thirsty/ADJ plants/NOUN daily/ADV ./.
cold/ADJ winds/NOUN blow/VERB from/ADP the/DET northern/ADJ hills/NOUN ./.
the/DET pilot/NOUN lands/VERB the/DET huge/ADJ plane/NOUN smoothly/ADV ./.
she/PRON and/CONJ her/PRON friend/NOUN play/VERB chess/NOUN on/ADP sunday/NOUN ./.
the/DET angry/ADJ driver/NOUN honked/VERB at/ADP the/DET slow/ADJ truck/NOUN ./.
ten/NUM soldiers/NOUN guarded/VERB the/DET main/ADJ gate/NOUN carefully/ADV ./.
the/DET scientist/NOUN studies/VERB rare/ADJ butterflies/NOUN in/ADP the/DET jungle/NOUN ./.
a/DET gentle/ADJ breeze/NOUN moves/VERB the/DET yellow/ADJ leaves/NOUN ./.
the/DET judge/NOUN listened/VERB to/ADP the/DET long/ADJ argument/NOUN patiently/ADV ./.
my/PRON uncle/NOUN builds/VERB strong/ADJ wooden/ADJ tables/NOUN ./.
the/DET children/NOUN happily/ADV opened/VERB their/PRON colorful/ADJ presents/NOUN ./.
fresh/ADJ snow/NOUN covered/VERB the/DET silent/ADJ village/NOUN overnight/ADV ./.
the/DET manager/NOUN calmly/ADV explained/VERB the/DET new/ADJ rules/NOUN ./.
bright/ADJ stars/NOUN shine/VERB over/ADP the/DET peaceful/ADJ desert/NOUN ./.
"""


def seed_corpus() -> List[List[Tuple[str, str]]]:
    out = []
    for line in _SEED_CORPUS_TEXT.strip().splitlines():
        sent = []
        for pair in line.split():
            word, tag = pair.rsplit("/", 1)
            sent.append((word, tag))
        out.append(sent)
    return out


_default_tagger: Optional[HmmPosTagger] = None


def default_tagger() -> HmmPosTagger:
    global _default_tagger
    if _default_tagger is None:
        _default_tagger = HmmPosTagger().fit(seed_corpus())
    return _default_tagger


# ---------------------------------------------------------------------------
# SentiWordNet scorer (SWN3.java parity)
# ---------------------------------------------------------------------------

class SWN3:
    """SentiWordNet 3.0 scorer.

    Lexicon format (the official distribution, SWN3.java:70-105):
    ``POS \\t id \\t posScore \\t negScore \\t term#rank [term#rank ...]``.
    Each term's senses are combined rank-weighted (1/rank, normalized by
    the harmonic number) exactly like the reference; text scoring sums
    token scores with negation-window sign flipping; classification uses
    the same seven sentiment bands (classForScore, SWN3.java:152-167)."""

    NEGATION_WORDS = {
        "could", "would", "should", "not", "no", "never", "isn't",
        "aren't", "wasn't", "weren't", "haven't", "doesn't", "didn't",
        "don't", "cannot", "can't", "won't",
    }
    _POS_ORDER = ("a", "n", "v", "r")

    def __init__(self, lexicon_path: Optional[str] = None):
        self._dict: Dict[str, float] = {}
        if lexicon_path is not None:
            self._load(Path(lexicon_path).read_text())
        else:
            self._load(_MINI_SENTIWORDNET)

    def _load(self, text: str) -> None:
        temp: Dict[str, Dict[int, float]] = defaultdict(dict)
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            data = line.split("\t")
            if len(data) < 5 or not data[2] or not data[3]:
                continue
            score = float(data[2]) - float(data[3])
            for w in data[4].split(" "):
                if not w or "#" not in w:
                    continue
                term, rank = w.rsplit("#", 1)
                temp[f"{term}#{data[0]}"][int(rank) - 1] = score
        for word, senses in temp.items():
            num = sum(s / (i + 1) for i, s in senses.items())
            den = sum(1.0 / i for i in range(1, max(senses) + 2))
            self._dict[word] = num / den

    def word_score(self, word: str) -> float:
        """Rank-weighted score, first matching POS (a, n, v, r)."""
        w = word.lower()
        for pos in self._POS_ORDER:
            key = f"{w}#{pos}"
            if key in self._dict:
                return self._dict[key]
        return 0.0

    def score_tokens(self, tokens: Sequence[str]) -> float:
        """Sum of token scores; a negation word flips the sign of the
        following sentiment-bearing token (SWN3.scoreTokens)."""
        total = 0.0
        negate = False
        for tok in tokens:
            w = tok.lower()
            if w in self.NEGATION_WORDS:
                negate = True
                continue
            s = self.word_score(w)
            if s != 0.0:
                total += -s if negate else s
                negate = False
        return total

    def score(self, text: str) -> float:
        return self.score_tokens(_tokenize(text))

    @staticmethod
    def class_for_score(score: float) -> str:
        if score >= 0.75:
            return "strong_positive"
        if score > 0.5:
            return "positive"
        if score > 0.0:
            return "weak_positive"
        if score == 0.0:
            return "neutral"
        if score >= -0.5:
            return "weak_negative"
        if score > -0.75:
            return "negative"
        return "strong_negative"

    def classify(self, text: str) -> str:
        return self.class_for_score(self.score(text))

    def label(self, text: str, num_classes: int = 5) -> int:
        """Sentiment band -> integer class (SST-style 0..4 for 5-class)."""
        s = self.score(text)
        if num_classes == 2:
            return int(s > 0)
        edges = np.linspace(-0.75, 0.75, num_classes - 1)
        return int(np.searchsorted(edges, s, side="right"))


# Embedded starter lexicon in the official SentiWordNet format (a tiny
# hand-curated subset; pass lexicon_path for the real 117k-entry file).
_MINI_SENTIWORDNET = """
a\t1\t0.75\t0\tgood#1 great#2
a\t2\t0.875\t0\texcellent#1 wonderful#2 fantastic#3
a\t3\t0\t0.75\tbad#1 awful#2
a\t4\t0\t0.875\tterrible#1 horrible#2
a\t5\t0.625\t0\thappy#1 glad#2
a\t6\t0\t0.625\tsad#1 unhappy#2
a\t7\t0.5\t0\tnice#1 pleasant#2
a\t8\t0\t0.5\tugly#1 nasty#2
a\t9\t0.625\t0\tbeautiful#1 lovely#2
a\t10\t0\t0.625\tpoor#1 lousy#2
a\t11\t0.5\t0.125\tfriendly#1
a\t12\t0.375\t0\tfresh#1
a\t13\t0\t0.375\tboring#1 dull#2
a\t14\t0.25\t0\tbig#2 tall#3
a\t15\t0\t0.25\tlazy#1
v\t16\t0.5\t0\tlove#1 enjoy#2
v\t17\t0\t0.5\thate#1 dislike#2
v\t18\t0.375\t0\thelp#1
v\t19\t0\t0.5\tdestroy#1 waste#2
v\t20\t0.25\t0\tlaugh#1
n\t21\t0.375\t0\tfriend#1
n\t22\t0\t0.375\tstorm#2 problem#1
n\t23\t0.25\t0\tsun#2
r\t24\t0.25\t0\twell#1 nicely#2
r\t25\t0\t0.25\tbadly#1 poorly#2
"""


# ---------------------------------------------------------------------------
# Raw-text constituency parsing (TreeParser.java parity)
# ---------------------------------------------------------------------------

class TreeParser:
    """Deterministic POS-driven chunker producing `Tree` objects.

    The reference (TreeParser.java:427) runs text through an OpenNLP
    constituency parser; this redesign tags with the HMM tagger, groups
    tokens into NP/VP/PP chunks with standard patterns, and combines the
    chunks right-branching into a binarized S — enough structure for the
    RNTN's strictly binary combine (models/rntn.py) to train on raw
    sentences."""

    NP_TAGS = {"DET", "ADJ", "NOUN", "PRON", "NUM"}
    VP_TAGS = {"VERB", "ADV"}

    def __init__(self, tagger: Optional[HmmPosTagger] = None,
                 labeler=None):
        self.tagger = tagger or default_tagger()
        # labeler: tokens -> int label for the root/leaf nodes (e.g. an
        # SWN3-based sentiment labeler); None leaves labels at 0 so RNTN
        # consumers can relabel.
        self.labeler = labeler

    def sentences(self, text: str) -> List[str]:
        return [s.strip() for s in re.split(r"(?<=[.!?])\s+", text.strip())
                if s.strip()]

    def parse(self, sentence: str) -> Tree:
        tagged = self.tagger.tag_text(sentence)
        tagged = [(w, t) for w, t in tagged if t != "."]
        if not tagged:
            raise ValueError(f"no tokens in sentence {sentence!r}")
        label = (self.labeler([w for w, _ in tagged])
                 if self.labeler else 0)
        chunks: List[Tree] = []
        i = 0
        while i < len(tagged):
            word, tag = tagged[i]
            group = [Tree(label=label, word=word)]
            fam = (self.NP_TAGS if tag in self.NP_TAGS
                   else self.VP_TAGS if tag in self.VP_TAGS else None)
            j = i + 1
            while fam is not None and j < len(tagged) and tagged[j][1] in fam:
                group.append(Tree(label=label, word=tagged[j][0]))
                j += 1
            chunks.append(group[0] if len(group) == 1
                          else Tree(label=label, children=group))
            i = j
        root = chunks[-1]
        for left in reversed(chunks[:-1]):
            root = Tree(label=label, children=[left, root])
        return root.binarize()

    def parse_text(self, text: str) -> List[Tree]:
        return [self.parse(s) for s in self.sentences(text)]


class TreeVectorizer:
    """Raw corpus -> labeled trees for RNTN training (reference
    TreeVectorizer.java: parse + attach labels). The default labeler is
    the SWN3 sentiment band, matching the reference's sentiment
    pipeline."""

    def __init__(self, parser: Optional[TreeParser] = None,
                 swn: Optional[SWN3] = None, num_classes: int = 5):
        self.swn = swn or SWN3()
        self.num_classes = num_classes
        self.parser = parser or TreeParser(
            labeler=lambda toks: self.swn.label(" ".join(toks),
                                                self.num_classes))

    def vectorize(self, text: str) -> List[Tree]:
        return self.parser.parse_text(text)
