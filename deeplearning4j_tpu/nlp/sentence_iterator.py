"""Sentence / document iterator SPIs.

Parity: reference `text/sentenceiterator/` (Collection/Line/File/UIMA
iterators, label-aware variants, SentencePreProcessor) and
`text/documentiterator/`. All are thin, restartable streams over text
sources — the corpus side of the Word2Vec/GloVe pipelines.
"""

from __future__ import annotations

import os
import pathlib
from typing import Callable, Iterable, Iterator, Optional, Sequence


class SentenceIterator:
    """SPI: nextSentence/hasNext/reset (+ Python iteration), with an
    optional SentencePreProcessor applied to every sentence."""

    def __init__(self, pre_processor: Optional[Callable[[str], str]] = None):
        self.pre_processor = pre_processor

    def _raw(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def __iter__(self) -> Iterator[str]:
        self.reset()
        for sentence in self._raw():
            yield (self.pre_processor(sentence) if self.pre_processor
                   else sentence)


class CollectionSentenceIterator(SentenceIterator):
    """Over an in-memory collection (reference
    `CollectionSentenceIterator`)."""

    def __init__(self, sentences: Sequence[str], pre_processor=None):
        super().__init__(pre_processor)
        self.sentences = list(sentences)

    def _raw(self):
        return iter(self.sentences)


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file (reference `LineSentenceIterator`)."""

    def __init__(self, path: os.PathLike, pre_processor=None):
        super().__init__(pre_processor)
        self.path = pathlib.Path(path)

    def _raw(self):
        with open(self.path, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """Every file under a directory, one sentence per line (reference
    `FileSentenceIterator` walks a dir)."""

    def __init__(self, root: os.PathLike, pre_processor=None):
        super().__init__(pre_processor)
        self.root = pathlib.Path(root)

    def _raw(self):
        files = ([self.root] if self.root.is_file()
                 else sorted(p for p in self.root.rglob("*") if p.is_file()))
        for path in files:
            with open(path, encoding="utf-8", errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield line


class LabelAwareSentenceIterator(SentenceIterator):
    """(sentence, label) streams for ParagraphVectors (reference
    `LabelAwareSentenceIterator` / LabelAwareListSentenceIterator)."""

    def __init__(self, sentences: Sequence[str], labels: Sequence[str],
                 pre_processor=None):
        super().__init__(pre_processor)
        if len(sentences) != len(labels):
            raise ValueError("sentences and labels must align")
        self.sentences = list(sentences)
        self.labels = list(labels)
        self._pos = 0

    def _raw(self):
        for i, s in enumerate(self.sentences):
            self._pos = i
            yield s

    def current_label(self) -> str:
        return self.labels[self._pos]

    def pairs(self) -> Iterator[tuple]:
        for s, l in zip(self.sentences, self.labels):
            yield ((self.pre_processor(s) if self.pre_processor else s), l)


class DocumentIterator:
    """SPI over whole documents (reference `text/documentiterator/`)."""

    def __init__(self, docs: Iterable[str]):
        self.docs = list(docs)

    def __iter__(self) -> Iterator[str]:
        return iter(self.docs)
