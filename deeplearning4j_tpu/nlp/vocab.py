"""Vocabulary store + Huffman coding for hierarchical softmax.

Parity: reference `models/word2vec/wordstore/VocabCache` /
`InMemoryLookupCache.java` (word→index/frequency), `VocabWord.java`, and
`Huffman.java:29` (binary Huffman tree over word frequencies assigning each
word its code bits and inner-node "points" path, consumed by the HS
objective at `InMemoryLookupTable.iterateSample:192`).

The TPU twist: codes/points are padded into dense int arrays
(`VocabCache.hs_arrays()`) so the whole batch's Huffman paths are two
gathers inside the jitted step instead of per-word Java loops.
"""

from __future__ import annotations

import heapq
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class VocabWord:
    """Reference `VocabWord.java`: word + frequency + HS codes/points."""
    word: str
    count: int = 0
    index: int = -1
    codes: List[int] = field(default_factory=list)
    points: List[int] = field(default_factory=list)


class VocabCache:
    """Word→VocabWord store with frequency-ordered contiguous indices."""

    def __init__(self, min_word_frequency: int = 1,
                 max_words: Optional[int] = None):
        self.min_word_frequency = min_word_frequency
        self.max_words = max_words  # keep only the top-N frequent words
        self.words: Dict[str, VocabWord] = {}
        self._index: List[str] = []

    # -- building ----------------------------------------------------------
    def fit(self, sentences: Iterable[Sequence[str]]) -> "VocabCache":
        counts: Counter = Counter()
        for tokens in sentences:
            counts.update(tokens)
        for word, count in counts.most_common():
            if self.max_words is not None and len(self._index) >= self.max_words:
                break
            if count >= self.min_word_frequency:
                self.add(word, count)
        return self

    def add(self, word: str, count: int = 1) -> VocabWord:
        if word in self.words:
            vw = self.words[word]
            vw.count += count
            return vw
        vw = VocabWord(word=word, count=count, index=len(self._index))
        self.words[word] = vw
        self._index.append(word)
        return vw

    # -- lookups (reference VocabCache API) --------------------------------
    def index_of(self, word: str) -> int:
        vw = self.words.get(word)
        return vw.index if vw else -1

    def word_at(self, index: int) -> str:
        return self._index[index]

    def word_frequency(self, word: str) -> int:
        vw = self.words.get(word)
        return vw.count if vw else 0

    def contains(self, word: str) -> bool:
        return word in self.words

    def num_words(self) -> int:
        return len(self._index)

    def __len__(self) -> int:
        return len(self._index)

    def __contains__(self, word: str) -> bool:
        return word in self.words

    def total_word_count(self) -> int:
        return sum(vw.count for vw in self.words.values())

    def encode(self, tokens: Sequence[str]) -> np.ndarray:
        """Tokens → int32 indices, dropping OOV (reference trainSentence
        skips unknown words)."""
        idx = [self.index_of(t) for t in tokens]
        return np.asarray([i for i in idx if i >= 0], np.int32)

    # -- hierarchical softmax arrays --------------------------------------
    def hs_arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Dense (points, codes, lengths): [V, L] int32 paths through the
        Huffman tree per word, padded with 0; lengths [V]. Requires
        Huffman(...).build() first."""
        V = len(self._index)
        L = max((len(self.words[w].codes) for w in self._index), default=0)
        points = np.zeros((V, L), np.int32)
        codes = np.zeros((V, L), np.int32)
        lengths = np.zeros((V,), np.int32)
        for w in self._index:
            vw = self.words[w]
            n = len(vw.codes)
            lengths[vw.index] = n
            points[vw.index, :n] = vw.points
            codes[vw.index, :n] = vw.codes
        return points, codes, lengths


class Huffman:
    """Builds the Huffman tree over word frequencies and writes each word's
    `codes` (branch bits) and `points` (inner-node indices) — reference
    `Huffman.java:29` build()."""

    def __init__(self, vocab: VocabCache):
        self.vocab = vocab

    def build(self) -> VocabCache:
        vocab = self.vocab
        V = len(vocab)
        if V == 0:
            return vocab
        if V == 1:
            only = vocab.words[vocab.word_at(0)]
            only.codes, only.points = [0], [0]
            return vocab
        # Standard word2vec-style array Huffman: leaves 0..V-1, inner nodes
        # V..2V-2; inner node k is addressed as (k - V) in syn1.
        count = np.empty(2 * V - 1, np.int64)
        for w, vw in vocab.words.items():
            count[vw.index] = vw.count
        heap = [(int(count[i]), i) for i in range(V)]
        heapq.heapify(heap)
        parent = np.zeros(2 * V - 1, np.int32)
        binary = np.zeros(2 * V - 1, np.int8)
        for k in range(V, 2 * V - 1):
            c1, i1 = heapq.heappop(heap)
            c2, i2 = heapq.heappop(heap)
            count[k] = c1 + c2
            parent[i1] = k
            parent[i2] = k
            binary[i2] = 1
            heapq.heappush(heap, (int(count[k]), k))
        root = 2 * V - 2
        for w, vw in vocab.words.items():
            codes: List[int] = []
            points: List[int] = []
            node = vw.index
            while node != root:
                codes.append(int(binary[node]))
                node = int(parent[node])
                points.append(node - V)
            vw.codes = list(reversed(codes))
            vw.points = list(reversed(points))
        return vocab


def build_negative_table(vocab: VocabCache, table_size: int = 100_000,
                         power: float = 0.75) -> np.ndarray:
    """Unigram^0.75 sampling table (reference
    `InMemoryLookupTable.makeTable:165` / word2vec-C): int32 [table_size]
    where word i occupies a share proportional to count_i^power. Negative
    sampling is then a uniform gather into this table on device."""
    V = len(vocab)
    freqs = np.array([vocab.word_frequency(vocab.word_at(i))
                      for i in range(V)], np.float64) ** power
    cum = np.cumsum(freqs / freqs.sum())
    positions = (np.arange(table_size) + 0.5) / table_size
    return np.searchsorted(cum, positions).astype(np.int32).clip(0, V - 1)
