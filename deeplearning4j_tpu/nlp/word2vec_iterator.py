"""Word2Vec -> supervised DataSet bridge.

Parity: reference `models/word2vec/iterator/Word2VecDataSetIterator.java`
(286 LoC) + `WindowConverter.java`: slide a centered window over each
labeled sentence, featurize the window as the concatenation of its tokens'
word vectors, label it with the sentence's label — producing the DataSets
a windowed classifier (e.g. a tagger MLP on MultiLayerNetwork) trains on.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.fetchers import one_hot
from deeplearning4j_tpu.nlp.windows import Window, windows


def window_to_vector(w2v, window: Window) -> np.ndarray:
    """Concatenated word vectors of the window's tokens (WindowConverter.
    asExampleMatrix); unknown/pad tokens contribute zero vectors."""
    dim = w2v.syn0.shape[1]
    parts = []
    for tok in window.as_tokens():
        idx = w2v.vocab.index_of(tok) if hasattr(w2v.vocab, "index_of") \
            else w2v.vocab.get(tok, -1)
        parts.append(w2v.syn0[idx] if 0 <= idx < len(w2v.syn0)
                     else np.zeros(dim, np.float32))
    return np.concatenate(parts).astype(np.float32)


class Word2VecDataSetIterator:
    """Iterate (features, labels) DataSet batches from labeled sentences.

    `sentences_with_labels`: any iterable of (sentence, label) pairs — a
    LabelAwareSentenceIterator's `.pairs()` works directly.  Feature dim =
    window_size * vector_length; labels one-hot over `labels`."""

    def __init__(self, w2v, sentences_with_labels, labels: Sequence[str],
                 batch: int = 10, window_size: int = 5,
                 tokenizer=None):
        self.w2v = w2v
        self.source = sentences_with_labels
        self.labels = list(labels)
        self._label_idx = {l: i for i, l in enumerate(self.labels)}
        self.batch = batch
        self.window_size = window_size
        if tokenizer is None:
            from deeplearning4j_tpu.nlp.tokenization import (
                DefaultTokenizerFactory,
            )
            tokenizer = DefaultTokenizerFactory()
        self.tokenizer = tokenizer
        self._pairs: Optional[List] = None

    @property
    def input_columns(self) -> int:
        return self.window_size * self.w2v.syn0.shape[1]

    def _materialized(self) -> List:
        if self._pairs is None:
            pairs = (self.source.pairs()
                     if hasattr(self.source, "pairs") else self.source)
            self._pairs = [(s, l) for s, l in pairs]
        return self._pairs

    def _examples(self) -> Iterator[tuple]:
        for sentence, label in self._materialized():
            tokens = (self.tokenizer.tokenize(sentence)
                      if isinstance(sentence, str) else list(sentence))
            if not tokens:
                continue
            y = self._label_idx[label]
            for win in windows(tokens, self.window_size):
                yield window_to_vector(self.w2v, win), y

    def __iter__(self) -> Iterator[DataSet]:
        feats: List[np.ndarray] = []
        ys: List[int] = []
        for x, y in self._examples():
            feats.append(x)
            ys.append(y)
            if len(feats) == self.batch:
                yield DataSet(np.stack(feats),
                              one_hot(np.asarray(ys), len(self.labels)))
                feats, ys = [], []
        if feats:
            yield DataSet(np.stack(feats),
                          one_hot(np.asarray(ys), len(self.labels)))

    def reset(self) -> None:
        pass  # re-iteration re-reads the materialized pairs

    def all_data(self) -> DataSet:
        """Entire corpus as one DataSet (convenience for evaluation)."""
        xs, ys = [], []
        for x, y in self._examples():
            xs.append(x)
            ys.append(y)
        return DataSet(np.stack(xs), one_hot(np.asarray(ys),
                                             len(self.labels)))
