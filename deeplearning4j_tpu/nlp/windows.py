"""Moving-window views over token sequences.

Parity: reference `text/movingwindow/Windows.java:189` + `Window.java` —
fixed-size context windows (padded with <s>/</s>) used by the windowed
classifiers and Viterbi-style taggers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

BEGIN = "<s>"
END = "</s>"


@dataclass
class Window:
    """One centered window (reference Window.java)."""
    words: List[str]
    focus_index: int
    label: str = ""

    @property
    def focus(self) -> str:
        return self.words[self.focus_index]

    def as_tokens(self) -> List[str]:
        return list(self.words)


def windows(tokens: Sequence[str], window_size: int = 5) -> List[Window]:
    """All windows of `window_size` centered on each token, edge-padded
    with BEGIN/END markers (reference Windows.windows)."""
    if window_size % 2 == 0:
        raise ValueError("window_size must be odd")
    half = window_size // 2
    padded = [BEGIN] * half + list(tokens) + [END] * half
    out = []
    for i in range(len(tokens)):
        out.append(Window(words=padded[i:i + window_size],
                          focus_index=half))
    return out
