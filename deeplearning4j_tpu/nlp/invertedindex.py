"""In-memory inverted index with mini-batch document sampling.

Parity: reference `text/invertedindex/LuceneInvertedIndex.java` (929 LoC) —
the role it plays for Word2Vec batching: store docs as word lists, map
word→documents, and serve random mini-batches of documents for training.
Lucene (on-disk segments, analyzers) is infrastructure the TPU build does
not need; a dict-backed index covers the consumed API.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache


class InvertedIndex:
    def __init__(self, vocab: Optional[VocabCache] = None):
        self.vocab = vocab
        self._docs: List[List[str]] = []
        self._word_to_docs: Dict[str, set] = defaultdict(set)

    # -- reference InvertedIndex API ---------------------------------------
    def add_word_to_doc(self, doc: int, word: str) -> None:
        while doc >= len(self._docs):
            self._docs.append([])
        self._docs[doc].append(word)
        self._word_to_docs[word].add(doc)

    def add_doc(self, words: Sequence[str]) -> int:
        doc_id = len(self._docs)
        self._docs.append(list(words))
        for w in words:
            self._word_to_docs[w].add(doc_id)
        return doc_id

    def document(self, index: int) -> List[str]:
        return list(self._docs[index])

    def documents(self, word: str) -> List[int]:
        return sorted(self._word_to_docs.get(word, ()))

    def num_documents(self) -> int:
        return len(self._docs)

    def all_docs(self) -> List[List[str]]:
        return [list(d) for d in self._docs]

    # -- mini-batch sampling (the Word2Vec batching role) ------------------
    def sample_batches(self, batch_size: int, num_batches: int,
                       seed: int = 0) -> Iterator[List[List[str]]]:
        rng = np.random.default_rng(seed)
        n = len(self._docs)
        if n == 0:
            return
        for _ in range(num_batches):
            idx = rng.integers(0, n, batch_size)
            yield [list(self._docs[i]) for i in idx]

    def eachDocWithLabel(self):  # reference casing kept for familiarity
        for i, d in enumerate(self._docs):
            yield list(d), i
