"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A from-scratch JAX/XLA/Pallas re-design with capability parity to the 2015
Skymind Deeplearning4j stack (reference: huamichaelchen/deeplearning4j).
Where the reference delegated tensor math to ND4J (JBLAS/JCublas) and wrote
hand-coded backprop per layer, this framework is built TPU-first:

- ops/        named activation/loss/init/updater registries, jit-compiled
              (replaces the ND4J op surface, ref SURVEY §1 L0)
- nn/         typed configs with JSON/YAML round-trip + pure init/apply layers
              (replaces nn/conf + nn/layers, ref deeplearning4j-core)
- models/     MultiLayerNetwork and friends (ref nn/multilayer)
- optimize/   solvers (SGD/line-search/CG/LBFGS), listeners (ref optimize/)
- datasets/   DataSet + iterators/fetchers (ref datasets/ + Canova bridge)
- eval/       Evaluation + ConfusionMatrix (ref eval/)
- parallel/   SPMD data/model parallelism over jax.sharding.Mesh + psum
              (replaces Spark/Akka/YARN parameter averaging, ref scaleout)
- nlp/        Word2Vec/GloVe/ParagraphVectors, tokenizers (ref dl4j-nlp)
- clustering/ KMeans + spatial trees (ref clustering/)
- plot/       t-SNE (ref plot/)
- runtime/    control plane: job queue, heartbeats, checkpointing
"""

__version__ = "0.2.0"

from deeplearning4j_tpu.ops import activations, losses, initializers, updaters  # noqa: F401

# Lazy top-level conveniences (PEP 562): `from deeplearning4j_tpu import
# MultiLayerNetwork` without paying for every subpackage at import time.
_LAZY = {
    "MultiLayerNetwork": ("deeplearning4j_tpu.models", "MultiLayerNetwork"),
    "get_model": ("deeplearning4j_tpu.models", "get_model"),
    "DataParallelTrainer": ("deeplearning4j_tpu.parallel",
                            "DataParallelTrainer"),
    "make_mesh": ("deeplearning4j_tpu.parallel", "make_mesh"),
    "generate": ("deeplearning4j_tpu.parallel", "generate"),
    "beam_search": ("deeplearning4j_tpu.parallel", "beam_search"),
    "load_source": ("deeplearning4j_tpu.ml", "load_source"),
    "Evaluation": ("deeplearning4j_tpu.evaluation", "Evaluation"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'deeplearning4j_tpu' has no attribute "
                         f"{name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
