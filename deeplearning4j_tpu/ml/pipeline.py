"""Estimators, transformers, pipelines.

Parity: reference dl4j-spark-ml estimators (SURVEY §2.3 dl4j-spark-ml row).
Convention: fit(X[, y]) -> self, predict/transform on arrays, get_params/
set_params for config introspection — drop-in friendly next to sklearn
without importing it.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.fetchers import one_hot
from deeplearning4j_tpu.models import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
from deeplearning4j_tpu.precision import default_dtype


class _BaseEstimator:
    def get_params(self) -> dict:
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}

    def set_params(self, **kwargs) -> "_BaseEstimator":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown param {k!r}")
            setattr(self, k, v)
        return self


class StandardScaler(_BaseEstimator):
    """Zero-mean/unit-variance feature scaling (the preprocessing the
    reference bakes into DataSet.normalizeZeroMeanZeroUnitVariance)."""

    def __init__(self):
        self.mean_: Optional[np.ndarray] = None
        self.std_: Optional[np.ndarray] = None

    def fit(self, x, y=None) -> "StandardScaler":
        x = np.asarray(x, default_dtype())
        self.mean_ = x.mean(axis=0)
        self.std_ = x.std(axis=0)
        self.std_[self.std_ == 0] = 1.0
        return self

    def transform(self, x) -> np.ndarray:
        if self.mean_ is None:
            raise ValueError("fit() first")
        return (np.asarray(x, default_dtype()) - self.mean_) / self.std_

    def fit_transform(self, x, y=None) -> np.ndarray:
        return self.fit(x, y).transform(x)


class NetworkClassifier(_BaseEstimator):
    """MultiLayerNetwork as a classifier estimator.

    distributed=True trains through the SPMD DataParallelTrainer — the
    TPU-native replacement for the reference's
    ParameterAveragingTrainingStrategy (TrainingStrategy.scala:39-81).
    """

    def __init__(self, conf: MultiLayerConfiguration, epochs: int = 10,
                 batch_size: int = 32, distributed: bool = False):
        self.conf = conf
        self.epochs = epochs
        self.batch_size = batch_size
        self.distributed = distributed
        self._net: Optional[MultiLayerNetwork] = None

    @property
    def network(self) -> MultiLayerNetwork:
        if self._net is None:
            raise ValueError("fit() first")
        return self._net

    def fit(self, x, y) -> "NetworkClassifier":
        # precision plane: feed the net's DECLARED input dtype instead of
        # silently upcasting every batch to 4-byte floats
        x = np.asarray(x, default_dtype(self.conf))
        y = np.asarray(y)
        if y.ndim == 1:
            n_out = self.conf.layers[-1].n_out
            y = one_hot(y.astype(int), n_out)
        self._net = MultiLayerNetwork(self.conf).init()
        if self.distributed:
            from deeplearning4j_tpu.parallel import DataParallelTrainer

            trainer = DataParallelTrainer(self._net)
            n = trainer.n_devices
            batch = max(self.batch_size // n * n, n)
            for _ in range(self.epochs):
                for s in range(0, len(x) - batch + 1, batch):
                    trainer.fit_batch(x[s:s + batch], y[s:s + batch])
        else:
            from deeplearning4j_tpu.datasets import ArrayDataSetIterator

            it = ArrayDataSetIterator(x, y, batch=self.batch_size)
            self._net.fit(it, epochs=self.epochs)
        return self

    def predict_proba(self, x) -> np.ndarray:
        return np.asarray(self.network.label_probabilities(
            np.asarray(x, default_dtype(self.network))))

    def predict(self, x) -> np.ndarray:
        return self.predict_proba(x).argmax(axis=1)

    def score(self, x, y) -> float:
        y = np.asarray(y)
        if y.ndim == 2:
            y = y.argmax(axis=1)
        return float((self.predict(x) == y).mean())


class NetworkReconstruction(_BaseEstimator):
    """Unsupervised feature extraction: pretrain, then transform() emits a
    chosen layer's activations (MultiLayerNetworkReconstruction.scala —
    reconstruction via the pretrained hidden representation)."""

    def __init__(self, conf: MultiLayerConfiguration, epochs: int = 10,
                 batch_size: int = 32, layer: int = -1):
        self.conf = conf
        self.epochs = epochs
        self.batch_size = batch_size
        self.layer = layer
        self._net: Optional[MultiLayerNetwork] = None

    def fit(self, x, y=None) -> "NetworkReconstruction":
        from deeplearning4j_tpu.datasets import ArrayDataSetIterator

        x = np.asarray(x, default_dtype(self.conf))
        self._net = MultiLayerNetwork(self.conf).init()
        dummy = np.zeros((len(x), 1), default_dtype(self.conf))
        it = ArrayDataSetIterator(x, dummy, batch=self.batch_size)
        self._net.pretrain(it, epochs=self.epochs)
        return self

    def transform(self, x) -> np.ndarray:
        if self._net is None:
            raise ValueError("fit() first")
        acts = self._net.feed_forward(
            np.asarray(x, default_dtype(self._net)))
        return np.asarray(acts[self.layer])

    def fit_transform(self, x, y=None) -> np.ndarray:
        return self.fit(x).transform(x)


class Pipeline(_BaseEstimator):
    """Chain of (name, transformer/estimator) steps, sklearn-shaped:
    intermediate steps need fit/transform, the last needs fit and either
    predict or transform."""

    def __init__(self, steps: Sequence[Tuple[str, object]]):
        self.steps: List[Tuple[str, object]] = list(steps)

    def _validate(self):
        names = [n for n, _ in self.steps]
        if len(set(names)) != len(names):
            raise ValueError("duplicate step names")

    def fit(self, x, y=None) -> "Pipeline":
        self._validate()
        for name, step in self.steps[:-1]:
            x = step.fit_transform(x, y) if hasattr(step, "fit_transform") \
                else step.fit(x, y).transform(x)
        last = self.steps[-1][1]
        last.fit(x, y) if y is not None else last.fit(x)
        return self

    def _pre(self, x):
        for _, step in self.steps[:-1]:
            x = step.transform(x)
        return x

    def predict(self, x) -> np.ndarray:
        return self.steps[-1][1].predict(self._pre(x))

    def predict_proba(self, x) -> np.ndarray:
        return self.steps[-1][1].predict_proba(self._pre(x))

    def transform(self, x) -> np.ndarray:
        return self.steps[-1][1].transform(self._pre(x))

    def score(self, x, y) -> float:
        return self.steps[-1][1].score(self._pre(x), y)
