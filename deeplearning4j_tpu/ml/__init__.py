"""High-level estimator/pipeline API.

Parity: reference `dl4j-spark-ml` (Scala) — Spark ML pipeline integration:
`MultiLayerNetworkClassification.scala:47` (Estimator whose train() runs
ParameterAveragingTrainingStrategy, model predicts on the driver),
`MultiLayerNetworkReconstruction.scala` (unsupervised hidden-layer
transform), `ml/Unsupervised.scala`. The TPU-native equivalent drops Spark:
estimators wrap `MultiLayerNetwork` (optionally the SPMD
`DataParallelTrainer` — the psum analog of parameter averaging) behind the
fit/transform/predict convention Python ML code expects.
"""

from deeplearning4j_tpu.ml.pipeline import (
    NetworkClassifier,
    NetworkReconstruction,
    Pipeline,
    StandardScaler,
)
from deeplearning4j_tpu.ml.sources import (
    SOURCES,
    DataSource,
    load_source,
    source_schema,
)

__all__ = [
    "NetworkClassifier",
    "NetworkReconstruction",
    "Pipeline",
    "StandardScaler",
    "DataSource",
    "SOURCES",
    "load_source",
    "source_schema",
]
