"""High-level estimator/pipeline API.

Parity: reference `dl4j-spark-ml` (Scala) — Spark ML pipeline integration:
`MultiLayerNetworkClassification.scala:47` (Estimator whose train() runs
ParameterAveragingTrainingStrategy, model predicts on the driver),
`MultiLayerNetworkReconstruction.scala` (unsupervised hidden-layer
transform), `ml/Unsupervised.scala`. The TPU-native equivalent drops Spark:
estimators wrap `MultiLayerNetwork` (optionally the SPMD
`DataParallelTrainer` — the psum analog of parameter averaging) behind the
fit/transform/predict convention Python ML code expects.
"""

from deeplearning4j_tpu.ml.pipeline import (
    NetworkClassifier,
    NetworkReconstruction,
    Pipeline,
    StandardScaler,
)

__all__ = [
    "NetworkClassifier",
    "NetworkReconstruction",
    "Pipeline",
    "StandardScaler",
]
