"""Named data sources for the estimator API.

Parity: reference `dl4j-spark-ml` Spark SQL relations —
`sql/sources/mnist/MnistRelation.scala:90`, `iris/IrisRelation`,
`lfw/LfwRelation` — which expose the bundled datasets as schema-carrying
tables the pipeline API can load by name. Without Spark, the analog is a
small registry of sources that each yield a `DataSet` plus a column
schema, so `load_source("iris")` is the one-liner the Scala
`sqlContext.read.format("...iris").load()` was.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSource:
    """A named, schema-carrying dataset (reference BaseRelation role)."""

    def __init__(self, name: str, loader: Callable[..., DataSet],
                 feature_shape: tuple, num_classes: Optional[int],
                 description: str):
        self.name = name
        self._loader = loader
        self.feature_shape = feature_shape
        self.num_classes = num_classes
        self.description = description

    def load(self, **kw) -> DataSet:
        return self._loader(**kw)

    def schema(self) -> dict:
        return {"name": self.name,
                "features": list(self.feature_shape),
                "num_classes": self.num_classes,
                "description": self.description}


def _iris(**kw) -> DataSet:
    from deeplearning4j_tpu.datasets.fetchers import iris_dataset

    return iris_dataset(**kw)


def _mnist(**kw) -> DataSet:
    from deeplearning4j_tpu.datasets.fetchers import mnist_dataset

    return mnist_dataset(**kw)


def _lfw(**kw) -> DataSet:
    from deeplearning4j_tpu.datasets.fetchers import lfw_dataset

    return lfw_dataset(**kw)


def _cifar10(**kw) -> DataSet:
    from deeplearning4j_tpu.datasets.fetchers import cifar10_dataset

    return cifar10_dataset(**kw)


def _news(**kw) -> DataSet:
    from deeplearning4j_tpu.nlp.news import news_dataset

    return news_dataset(**kw)


SOURCES: Dict[str, DataSource] = {
    s.name: s for s in (
        DataSource("iris", _iris, (4,), 3,
                   "150-example Iris (IrisRelation parity)"),
        DataSource("mnist", _mnist, (28, 28, 1), 10,
                   "MNIST NHWC (MnistRelation parity)"),
        DataSource("lfw", _lfw, (50, 37, 1), None,
                   "Labeled Faces in the Wild (LfwRelation parity)"),
        DataSource("cifar10", _cifar10, (32, 32, 3), 10,
                   "CIFAR-10 NHWC (BASELINE #5 dataset)"),
        DataSource("newsgroups", _news, (None,), None,
                   "TF-IDF vectorized labeled news corpus"),
    )
}


def load_source(name: str, **kw) -> DataSet:
    """`load_source("iris")` — the `read.format(...).load()` one-liner."""
    if name not in SOURCES:
        raise KeyError(f"unknown data source '{name}'; known: "
                       f"{sorted(SOURCES)}")
    return SOURCES[name].load(**kw)


def source_schema(name: str) -> dict:
    if name not in SOURCES:
        raise KeyError(f"unknown data source '{name}'; known: "
                       f"{sorted(SOURCES)}")
    return SOURCES[name].schema()
