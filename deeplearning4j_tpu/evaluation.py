"""Classification evaluation: confusion matrix, precision/recall/F1/accuracy.

Parity: reference `eval/Evaluation.java:36` (eval(real,guess) :67 argmax +
confusion update; stats() :149; precision/recall/f1/accuracy :177-267) and
`eval/ConfusionMatrix.java` (generic counts). Host-side numpy — metrics are
bookkeeping, not MXU work.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Optional, Sequence

import numpy as np


class ConfusionMatrix:
    """Generic confusion counts keyed by (actual, predicted)."""

    def __init__(self, classes: Optional[Sequence[int]] = None):
        self.counts: Dict[int, Dict[int, int]] = defaultdict(lambda: defaultdict(int))
        self.classes: set = set(classes or [])

    def add(self, actual: int, predicted: int, count: int = 1) -> None:
        self.counts[actual][predicted] += count
        self.classes.add(actual)
        self.classes.add(predicted)

    def count(self, actual: int, predicted: int) -> int:
        return self.counts[actual][predicted]

    def actual_total(self, actual: int) -> int:
        return sum(self.counts[actual].values())

    def predicted_total(self, predicted: int) -> int:
        return sum(row[predicted] for row in self.counts.values())

    def to_array(self) -> np.ndarray:
        classes = sorted(self.classes)
        idx = {c: i for i, c in enumerate(classes)}
        arr = np.zeros((len(classes), len(classes)), dtype=np.int64)
        for a, row in self.counts.items():
            for p, n in row.items():
                arr[idx[a], idx[p]] = n
        return arr

    def __str__(self) -> str:
        classes = sorted(self.classes)
        arr = self.to_array()
        header = "      " + " ".join(f"{c:>6}" for c in classes)
        rows = [header] + [
            f"{c:>6}" + " ".join(f"{arr[i, j]:>6}" for j in range(len(classes)))
            for i, c in enumerate(classes)
        ]
        return "\n".join(rows)


class Evaluation:
    """Accumulating classifier evaluation over (one-hot or index) labels."""

    def __init__(self, num_classes: Optional[int] = None):
        self.confusion = ConfusionMatrix(range(num_classes) if num_classes else None)
        self.examples = 0

    def eval(self, labels: np.ndarray, predictions: np.ndarray) -> None:
        """labels/predictions: [batch, num_classes] scores or [batch] indices
        (reference eval(realOutcomes, guesses) :67)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        actual = labels.argmax(-1) if labels.ndim > 1 else labels.astype(int)
        guess = predictions.argmax(-1) if predictions.ndim > 1 else predictions.astype(int)
        for a, g in zip(actual.reshape(-1), guess.reshape(-1)):
            self.confusion.add(int(a), int(g))
        self.examples += actual.size

    # ---- metrics ----------------------------------------------------------

    def true_positives(self, cls: int) -> int:
        return self.confusion.count(cls, cls)

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is None:
            vals = [self.precision(c) for c in sorted(self.confusion.classes)]
            return float(np.mean(vals)) if vals else 0.0
        denom = self.confusion.predicted_total(cls)
        return self.true_positives(cls) / denom if denom else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is None:
            vals = [self.recall(c) for c in sorted(self.confusion.classes)]
            return float(np.mean(vals)) if vals else 0.0
        denom = self.confusion.actual_total(cls)
        return self.true_positives(cls) / denom if denom else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def accuracy(self) -> float:
        if not self.examples:
            return 0.0
        correct = sum(self.true_positives(c) for c in self.confusion.classes)
        return correct / self.examples

    def stats(self) -> str:
        """Printable report (reference stats() :149)."""
        lines = [
            "==================== Evaluation ====================",
            f"Examples:  {self.examples}",
            f"Accuracy:  {self.accuracy():.4f}",
            f"Precision: {self.precision():.4f}",
            f"Recall:    {self.recall():.4f}",
            f"F1 Score:  {self.f1():.4f}",
            "Confusion matrix (rows=actual, cols=predicted):",
            str(self.confusion),
        ]
        return "\n".join(lines)

    def merge(self, other: "Evaluation") -> None:
        """Combine evaluations from shards (for multi-host eval)."""
        for a, row in other.confusion.counts.items():
            for p, n in row.items():
                self.confusion.add(a, p, n)
        self.examples += other.examples
