"""Viterbi decoding as a jitted lax.scan.

Parity: reference `util/Viterbi.java` (194 LoC — most-likely label sequence
from per-step outcome probabilities with a Markov transition prior). The
reference loops in Java; here the forward pass is a `lax.scan` over time
and the backtrace a reverse scan — both on device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=())
def _decode(log_emit: jax.Array, log_trans: jax.Array,
            log_init: jax.Array):
    """log_emit [T, S], log_trans [S, S] (from->to), log_init [S] ->
    (path [T], best_logprob)."""

    def step(prev, emit_t):
        # prev: [S] best log-prob ending in each state
        scores = prev[:, None] + log_trans            # [S_from, S_to]
        best_prev = jnp.argmax(scores, axis=0)         # [S_to]
        cur = jnp.max(scores, axis=0) + emit_t
        return cur, best_prev

    first = log_init + log_emit[0]
    last, backptrs = jax.lax.scan(step, first, log_emit[1:])

    final_state = jnp.argmax(last)

    def back(state, ptr_t):
        prev = ptr_t[state]
        return prev, state

    # reverse scan emits the state for times 1..T-1 (final state included);
    # the last carry is the state at time 0.
    state0, path_tail = jax.lax.scan(back, final_state, backptrs,
                                     reverse=True)
    path = jnp.concatenate([state0[None], path_tail])
    return path, jnp.max(last)


class Viterbi:
    """decode(emission_probs) -> most likely state sequence."""

    def __init__(self, transition, initial=None, log_space: bool = False):
        trans = np.asarray(transition, np.float64)
        if not log_space:
            trans = np.log(np.maximum(trans, 1e-300))
        self.log_trans = jnp.asarray(trans, jnp.float32)
        n = trans.shape[0]
        if initial is None:
            init = np.full(n, -np.log(n))
        else:
            init = np.asarray(initial, np.float64)
            if not log_space:
                init = np.log(np.maximum(init, 1e-300))
        self.log_init = jnp.asarray(init, jnp.float32)

    def decode(self, emissions, log_space: bool = False):
        """emissions [T, S] (probabilities unless log_space). Returns
        (states [T] np.int32, best_logprob)."""
        e = np.asarray(emissions, np.float64)
        if not log_space:
            e = np.log(np.maximum(e, 1e-300))
        path, logp = _decode(jnp.asarray(e, jnp.float32), self.log_trans,
                             self.log_init)
        return np.asarray(path), float(logp)
