"""Disk-backed FIFO queue.

Parity: reference `util/DiskBasedQueue.java` (205 LoC — spills queued items
to disk so unbounded work queues don't exhaust heap; used by the scaleout
runtimes to buffer pending jobs).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import threading
import uuid
from collections import deque
from typing import Any, Optional


class DiskBasedQueue:
    def __init__(self, directory: Optional[str] = None):
        self._own_dir = directory is None
        self.dir = directory or tempfile.mkdtemp(prefix="dl4j-queue-")
        os.makedirs(self.dir, exist_ok=True)
        self._order: deque = deque()
        self._lock = threading.Lock()

    def add(self, item: Any) -> None:
        name = f"{len(self._order):012d}-{uuid.uuid4().hex}.pkl"
        path = os.path.join(self.dir, name)
        with open(path, "wb") as f:
            pickle.dump(item, f, protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            self._order.append(path)

    put = add

    def poll(self) -> Any:
        """Remove and return the head; raises IndexError when empty."""
        with self._lock:
            path = self._order.popleft()
        with open(path, "rb") as f:
            item = pickle.load(f)
        os.remove(path)
        return item

    def peek(self) -> Any:
        with self._lock:
            path = self._order[0]
        with open(path, "rb") as f:
            return pickle.load(f)

    def __len__(self) -> int:
        with self._lock:
            return len(self._order)

    def empty(self) -> bool:
        return len(self) == 0

    def close(self) -> None:
        if self._own_dir:
            shutil.rmtree(self.dir, ignore_errors=True)
        self._order.clear()

    def __enter__(self) -> "DiskBasedQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
