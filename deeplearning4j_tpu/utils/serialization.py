"""Object serialization helpers.

Parity: reference `util/SerializationUtils.java` (Java serialization to
file/stream). Model/parameter persistence has its own typed format in
runtime/checkpoint.py; this is the generic object spillway.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any


def save_object(obj: Any, path: os.PathLike) -> None:
    """Atomic pickle write (temp file + rename)."""
    path = os.fspath(path)
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    except BaseException:  # noqa: BLE001 — cleanup, re-raised
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_object(path: os.PathLike) -> Any:
    with open(os.fspath(path), "rb") as f:
        return pickle.load(f)
