"""Numeric helpers.

Parity: the used surface of reference `util/MathUtils.java` (1,293 LoC —
sigmoid, log2, entropy/information gain, normalization, correlation,
distances, ssq, uniform sampling, bernoulli likelihood). numpy-vectorized
instead of the reference's per-element Java loops.
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np

SMALL = 1e-6


def sigmoid(x):
    x = np.asarray(x, np.float64)
    return 1.0 / (1.0 + np.exp(-x))


def log2(x) -> np.ndarray:
    return np.log2(np.asarray(x, np.float64))


def entropy(probs: Sequence[float]) -> float:
    """Shannon entropy in bits; zeros contribute nothing."""
    p = np.asarray(probs, np.float64)
    p = p[p > 0]
    return float(-(p * np.log2(p)).sum())


def information_gain(parent: Sequence[float],
                     splits: Sequence[Sequence[float]],
                     weights: Sequence[float]) -> float:
    """entropy(parent) - sum_i w_i * entropy(split_i)."""
    gain = entropy(parent)
    for w, s in zip(weights, splits):
        gain -= w * entropy(s)
    return float(gain)


def normalize(values, new_min: float = 0.0, new_max: float = 1.0):
    v = np.asarray(values, np.float64)
    lo, hi = v.min(), v.max()
    if hi == lo:
        return np.full_like(v, (new_min + new_max) / 2.0)
    return (v - lo) / (hi - lo) * (new_max - new_min) + new_min


def correlation(a, b) -> float:
    """Pearson correlation coefficient."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    ac, bc = a - a.mean(), b - b.mean()
    denom = math.sqrt(float((ac * ac).sum() * (bc * bc).sum()))
    if denom == 0:
        return 0.0
    return float((ac * bc).sum() / denom)


def cosine_similarity(a, b) -> float:
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    na, nb = np.linalg.norm(a), np.linalg.norm(b)
    if na == 0 or nb == 0:
        return 0.0
    return float(a @ b / (na * nb))


def euclidean_distance(a, b) -> float:
    return float(np.linalg.norm(np.asarray(a, np.float64)
                                - np.asarray(b, np.float64)))


def manhattan_distance(a, b) -> float:
    return float(np.abs(np.asarray(a, np.float64)
                        - np.asarray(b, np.float64)).sum())


def ssq(values) -> float:
    v = np.asarray(values, np.float64)
    return float((v * v).sum())


def uniform(rng: np.random.Generator, lo: float, hi: float) -> float:
    return float(rng.random() * (hi - lo) + lo)


def bernoulli_log_likelihood(targets, probs) -> float:
    """sum t*log(p) + (1-t)*log(1-p), clipped away from 0/1."""
    t = np.asarray(targets, np.float64)
    p = np.clip(np.asarray(probs, np.float64), SMALL, 1.0 - SMALL)
    return float((t * np.log(p) + (1 - t) * np.log(1 - p)).sum())
