"""Utility tier.

Parity: reference `deeplearning4j-core/.../util/` (MathUtils.java 1,293 LoC,
Viterbi.java, MovingWindowMatrix, DiskBasedQueue, SerializationUtils,
ImageLoader) and the vendored Berkeley-NLP `berkeley/` package (Counter,
CounterMap, Pair). Host-side helpers; the Viterbi decoder is jittable
(lax.scan) since it is the one with real compute.
"""

from deeplearning4j_tpu.utils.counter import Counter, CounterMap
from deeplearning4j_tpu.utils.disk_queue import DiskBasedQueue
from deeplearning4j_tpu.utils.image_loader import ImageLoader
from deeplearning4j_tpu.utils.math_utils import (
    bernoulli_log_likelihood,
    correlation,
    cosine_similarity,
    entropy,
    euclidean_distance,
    information_gain,
    log2,
    manhattan_distance,
    normalize,
    sigmoid,
    ssq,
    uniform,
)
from deeplearning4j_tpu.utils.moving_window import MovingWindowMatrix
from deeplearning4j_tpu.utils.serialization import (
    load_object,
    save_object,
)
from deeplearning4j_tpu.utils.viterbi import Viterbi

__all__ = [
    "Counter", "CounterMap", "DiskBasedQueue", "ImageLoader",
    "MovingWindowMatrix", "Viterbi", "save_object", "load_object",
    "sigmoid", "log2", "entropy", "information_gain", "normalize",
    "correlation", "cosine_similarity", "euclidean_distance",
    "manhattan_distance", "ssq", "uniform", "bernoulli_log_likelihood",
]
