"""Sliding-window views over matrices.

Parity: reference `util/MovingWindowMatrix.java` — extract all (or strided)
rows x cols sub-windows of a 2-D array, optionally with rotations, used for
patch-based training. numpy stride tricks instead of copy loops.
"""

from __future__ import annotations

from typing import List

import numpy as np


class MovingWindowMatrix:
    def __init__(self, matrix, window_rows: int, window_cols: int,
                 add_rotate: bool = False):
        self.matrix = np.asarray(matrix)
        if self.matrix.ndim != 2:
            raise ValueError("expected a 2-D matrix")
        if (window_rows > self.matrix.shape[0]
                or window_cols > self.matrix.shape[1]):
            raise ValueError("window larger than matrix")
        self.rows = window_rows
        self.cols = window_cols
        self.add_rotate = add_rotate

    def windows(self, stride_rows: int = 1, stride_cols: int = 1
                ) -> List[np.ndarray]:
        view = np.lib.stride_tricks.sliding_window_view(
            self.matrix, (self.rows, self.cols))
        out = [view[i, j].copy()
               for i in range(0, view.shape[0], stride_rows)
               for j in range(0, view.shape[1], stride_cols)]
        if self.add_rotate:
            rotated = []
            for w in out:
                for k in (1, 2, 3):
                    rotated.append(np.rot90(w, k))
            out.extend(rotated)
        return out
