"""Counter / CounterMap.

Parity: reference vendored Berkeley-NLP `berkeley/Counter.java` (643 LoC)
and `CounterMap.java` — count/weight maps with argmax, normalization, and
pretty-printing, used across the NLP stack.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Generic, Hashable, Iterable, List, Optional, Tuple, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V", bound=Hashable)


class Counter(Generic[K]):
    def __init__(self, items: Optional[Iterable[K]] = None):
        self._counts: Dict[K, float] = defaultdict(float)
        if items:
            for it in items:
                self.increment(it)

    def increment(self, key: K, amount: float = 1.0) -> float:
        self._counts[key] += amount
        return self._counts[key]

    def set_count(self, key: K, count: float) -> None:
        self._counts[key] = count

    def get_count(self, key: K) -> float:
        return self._counts.get(key, 0.0)

    def remove(self, key: K) -> float:
        return self._counts.pop(key, 0.0)

    def total_count(self) -> float:
        return sum(self._counts.values())

    def arg_max(self) -> Optional[K]:
        if not self._counts:
            return None
        return max(self._counts, key=self._counts.get)

    def max_count(self) -> float:
        return self._counts[self.arg_max()] if self._counts else 0.0

    def normalize(self) -> "Counter[K]":
        total = self.total_count()
        if total:
            for k in self._counts:
                self._counts[k] /= total
        return self

    def sorted_keys(self, descending: bool = True) -> List[K]:
        return sorted(self._counts, key=self._counts.get,
                      reverse=descending)

    def keys(self):
        return self._counts.keys()

    def items(self):
        return self._counts.items()

    def __contains__(self, key: K) -> bool:
        return key in self._counts

    def __len__(self) -> int:
        return len(self._counts)

    def __str__(self) -> str:
        top = ", ".join(f"{k}: {self._counts[k]:g}"
                        for k in self.sorted_keys()[:10])
        return f"Counter[{top}]"


class CounterMap(Generic[K, V]):
    """key -> Counter of sub-keys (conditional counts)."""

    def __init__(self):
        self._maps: Dict[K, Counter[V]] = {}

    def increment(self, key: K, sub: V, amount: float = 1.0) -> float:
        return self.get_counter(key).increment(sub, amount)

    def set_count(self, key: K, sub: V, count: float) -> None:
        self.get_counter(key).set_count(sub, count)

    def get_count(self, key: K, sub: V) -> float:
        c = self._maps.get(key)
        return c.get_count(sub) if c else 0.0

    def get_counter(self, key: K) -> Counter[V]:
        if key not in self._maps:
            self._maps[key] = Counter()
        return self._maps[key]

    def total_count(self) -> float:
        return sum(c.total_count() for c in self._maps.values())

    def normalize(self) -> "CounterMap[K, V]":
        for c in self._maps.values():
            c.normalize()
        return self

    def keys(self):
        return self._maps.keys()

    def items(self) -> Iterable[Tuple[K, Counter[V]]]:
        return self._maps.items()

    def __contains__(self, key: K) -> bool:
        return key in self._maps

    def __len__(self) -> int:
        return len(self._maps)
