"""Image → array loading.

Parity: reference `util/ImageLoader.java` (load image files into row
vectors, optionally resized, for the LFW pipeline). PIL-backed and gated so
minimal installs raise a clear error instead of importing eagerly.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class ImageLoader:
    def __init__(self, height: Optional[int] = None,
                 width: Optional[int] = None, grayscale: bool = True):
        self.height = height
        self.width = width
        self.grayscale = grayscale

    def _pil(self):
        try:
            from PIL import Image
        except ImportError as e:  # pragma: no cover
            raise RuntimeError(
                "ImageLoader requires Pillow (PIL) to be installed") from e
        return Image

    def load(self, path: str) -> np.ndarray:
        """[H, W] (grayscale) or [H, W, C] float32 in [0, 1]."""
        Image = self._pil()
        img = Image.open(path)
        if self.grayscale:
            img = img.convert("L")
        else:
            img = img.convert("RGB")
        if self.height and self.width:
            img = img.resize((self.width, self.height))
        arr = np.asarray(img, np.float32) / 255.0
        return arr

    def as_row_vector(self, path: str) -> np.ndarray:
        return self.load(path).reshape(1, -1)

    def as_matrix(self, paths) -> np.ndarray:
        return np.concatenate([self.as_row_vector(p) for p in paths], axis=0)
