"""Serving fleet: replicated engines behind a failover router.

One `ServingEngine` process was both the scale ceiling and the only
copy — the single point of failure ROADMAP item 5 names.  This module is
the layer that removes it, modernizing what the 2015 reference's
`scaleout/` module (ZooKeeper registry + parameter-server workers) was
for: serving that survives any single worker dying.

- `Replica` — one engine endpoint in the fleet: a URL plus lifecycle
  hooks.  Thread-hosted replicas carry their in-process `UiServer`
  (`spawn_local_replica`, how tier-1 CPU tests and the `serve-fleet`
  CLI host them); process-per-replica deployments attach externally
  launched `dl4j serve` workers (`runtime.launcher.FleetProcessLauncher`
  generates/spawns the commands) by URL.
- `FleetRouter` — dispatch + health + lifecycle:

  * least-loaded dispatch (router-side in-flight per replica) with
    rendezvous prefix-affinity hashing for LM traffic, so one prompt
    prefix keeps landing on the same replica (feeds prefix/KV reuse,
    ROADMAP item 2) without a rebalance storm when membership changes;
  * health ejection: a background loop (or explicit `poll_health_once`)
    probes each replica's `/readyz`; failures feed that replica's own
    `CircuitBreaker` (`serving/resilience.py`) — threshold failures
    eject it from rotation, the cooldown's half-open window makes the
    next probe the re-admission test;
  * failover: predict is pure, so a failed dispatch is *resubmitted* on
    a different replica with an excluded-replica set — a replica dying
    mid-storm costs zero failed requests.  Replica 503/504 answers
    (overload, draining, deadline) fail over WITHOUT a breaker penalty:
    the replica is alive, just busy; connection-level failures and
    other 5xx count toward ejection.  4xx answers are the client's
    request and never retry anywhere;
  * rolling weight swaps: `rolling_swap()` spawns a standby with the
    new weights (the factory warms every bucket BEFORE it is attached),
    attaches it, takes one old replica out of rotation, drains its
    in-flight work, stops it — repeat per replica.  Zero 5xx under live
    traffic: the standby is warm before the flip, and a request that
    raced the flip into the draining replica fails over;
  * queue-depth-driven autoscale: mean router-side in-flight per active
    replica above `scale_up_depth` adds a replica, below
    `scale_down_depth` drains one out gracefully, bounded by
    `[min_replicas, max_replicas]`.

- **Disaggregated prefill/decode roles** (ISSUE-14): replicas carry a
  `role` — `prefill` workers chew long prompts chunk-by-chunk and ship
  the finished KV pages (`serving/transfer.py`) to the `decode` worker
  the router picked up front; `decode`/`both` workers run the token
  loop and take short-prompt traffic directly.  Sticky `session_id`
  rendezvous affinity keeps a multi-turn chat on the replica holding
  its pages; spill-over off an overloaded preferred replica is served
  by page shipping (prefill on the cache-hot replica, decode on the
  spill target) instead of a cold recompute.  The failure ladder never
  fails a request: dead prefill worker -> resubmit the prompt to a
  peer; rejected/corrupt shipment or no prefill capacity -> recompute
  on a decode worker.  `open_lm_stream` routes SSE token streams the
  same way.

- `FleetServer` — the fleet's own HTTP front (`/model/predict`,
  `/lm/generate`, `/fleet/stats`, `/serving/stats`, `/healthz`,
  `/readyz`) with the same typed-failure -> status mapping as
  `ui/server.py`, plus fleet-wide graceful drain (the `serve-fleet`
  SIGTERM path).
- `check_fleet_ledger` — the cross-layer accounting invariant: every
  request the fleet answered was answered by exactly one replica, so
  `sum(replica.requests) == fleet.requests` and client-side
  `submitted == fleet.requests + fleet.rejected`.

Deterministic fleet chaos (kill-replica, slow-replica, flapping-readyz)
lives in `resilience/chaos.py` (`FleetChaosConfig` / `chaos_fleet`);
docs/robustness.md has the eject -> probe -> re-admit lifecycle and the
rolling-swap timeline.
"""

from __future__ import annotations

import collections
import hashlib
import http.client
import inspect
import json
import math
import subprocess
import threading
import time
import urllib.error
import urllib.request
from concurrent import futures
from http.server import BaseHTTPRequestHandler
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.obs.compilewatch import compile_watcher
from deeplearning4j_tpu.obs.registry import (
    EXPOSITION_CONTENT_TYPE,
    MetricsRegistry,
)
from deeplearning4j_tpu.obs.trace import (
    TraceRecorder,
    chrome_trace,
    new_request_id,
    span,
    trace,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.resilience import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    DeadlineExceededError,
    ServingHTTPMixin,
    ServingHTTPServer,
    ServingUnavailableError,
)


class FleetClientError(ValueError):
    """A replica answered 4xx: the request payload itself is wrong, so
    retrying it on a different replica would just fail again — the
    router propagates it instead of failing over.  Maps back to the
    replica's status code at the fleet front.  A quota 429 (ISSUE-16)
    is exactly this shape — every replica sharing the tenant registry
    would refuse identically — and carries the replica's own
    ``retry_after_s`` so the front can relay the Retry-After header."""

    def __init__(self, msg: str, status: int = 400,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.status = int(status)
        self.retry_after_s = (None if retry_after_s is None
                              else float(retry_after_s))


class _ReplicaDispatchError(RuntimeError):
    """Internal: one dispatch attempt against one replica failed in a
    way that justifies failover.  `replica_fault` distinguishes a
    replica that is *broken* (connection refused/reset, 500 — counts
    toward breaker ejection) from one that is alive but unavailable
    (503 overload/draining, 504 deadline — fail over penalty-free)."""

    def __init__(self, msg: str, replica_fault: bool):
        super().__init__(msg)
        self.replica_fault = bool(replica_fault)


# Replica lifecycle states (the closed vocabulary /fleet/stats uses):
REPLICA_ACTIVE = "active"
REPLICA_DRAINING = "draining"
REPLICA_STOPPED = "stopped"

# Worker roles (ISSUE-14 disaggregated serving): prefill workers chew
# long prompts and ship finished KV pages; decode workers run the token
# loop (and take short-prompt traffic directly); "both" is the classic
# undifferentiated worker.  Role routing only constrains LM traffic —
# classifier dispatch stays role-agnostic.
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_BOTH = "both"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_BOTH)
# which roles may serve each side of the split
_PREFILL_ROLES = (ROLE_PREFILL,)
_DECODE_ROLES = (ROLE_DECODE, ROLE_BOTH)


class Replica:
    """One serving endpoint in the fleet.

    `server` is the in-process `UiServer` for thread-hosted replicas
    (tests, `serve-fleet` CLI); `process` a `subprocess.Popen` for
    process-per-replica deployments; both may be None for a purely
    attached URL (an externally managed worker).  The router assigns
    `breaker` at attach time when none is supplied, and owns the
    router-side counters (`in_flight`, `dispatches`, `failures`).
    """

    def __init__(self, name: str, url: str, server=None, process=None,
                 breaker: Optional[CircuitBreaker] = None, version: int = 0,
                 role: str = ROLE_BOTH):
        self.name = str(name)
        self.url = url.rstrip("/")
        self.server = server
        self.process = process
        self.breaker = breaker
        self.version = int(version)
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        self.role = role
        self.lock = threading.Lock()
        self.state = REPLICA_ACTIVE
        self.in_flight = 0      # router-side queue-depth proxy
        self.dispatches = 0     # successful dispatches via the router
        self.failures = 0       # replica-fault dispatch failures
        self.ejections = 0      # breaker closed/half-open -> open
        self.readmissions = 0   # open/half-open -> closed
        self._ejected = False

    def _on_breaker(self, state: str) -> None:
        # NOTE: fired while the breaker holds ITS lock; `self.lock` is
        # only ever taken after a breaker lock (never the reverse), so
        # the ordering is acyclic.
        with self.lock:
            if state == BREAKER_OPEN:
                self.ejections += 1
                self._ejected = True
            elif state == "closed" and self._ejected:
                self.readmissions += 1
                self._ejected = False

    def routable(self) -> bool:
        """Eligible for new traffic: in rotation and not breaker-open.
        `breaker.state` lazily commits open -> half_open once the
        cooldown elapses, so an ejected replica re-enters routing
        exactly when its re-admission probe window opens."""
        if self.state != REPLICA_ACTIVE:
            return False
        return self.breaker is None or self.breaker.state != BREAKER_OPEN

    # ---- lifecycle --------------------------------------------------------

    def begin_drain(self) -> None:
        if self.server is not None:
            self.server.begin_drain()

    def drain(self, grace_s: float = 5.0) -> bool:
        """Graceful: stop admission, let in-flight work finish.  For a
        process replica this is SIGTERM — `dl4j serve` installs the
        graceful-drain handler (cli.py)."""
        if self.server is not None:
            return self.server.drain(grace_s)
        if self.process is not None:
            self.process.terminate()
            try:
                self.process.wait(timeout=grace_s)
                return True
            except subprocess.TimeoutExpired:
                return False
        return True

    def stop(self) -> None:
        self.state = REPLICA_STOPPED
        if self.server is not None:
            self.server.stop()
        if self.process is not None:
            self.process.terminate()

    def kill(self) -> None:
        """Hard stop — the chaos 'replica process died' fault.  For a
        thread-hosted replica the HTTP socket closes and its engine
        fails queued work typed; in-flight router dispatches see a
        connection error or a 503 and fail over either way.
        Deliberately does NOT flip `state`: the control plane has not
        noticed the death yet — the router must discover it the honest
        way (dispatch failures and failed readyz probes feeding the
        breaker until ejection)."""
        if self.process is not None:
            self.process.kill()
        elif self.server is not None:
            self.server.stop()

    def summary(self) -> Dict:
        with self.lock:
            out = {"name": self.name, "url": self.url, "state": self.state,
                   "role": self.role,
                   "version": self.version, "in_flight": self.in_flight,
                   "dispatches": self.dispatches, "failures": self.failures,
                   "ejections": self.ejections,
                   "readmissions": self.readmissions}
        out["breaker"] = self.breaker.state if self.breaker else None
        return out


def spawn_local_replica(name: str, net=None, *, lm=None, lm_slots: int = 4,
                        host: str = "127.0.0.1", ladder=None,
                        max_batch: Optional[int] = None,
                        max_wait_ms: float = 2.0, warmup_example=None,
                        max_queue_depth: Optional[int] = None,
                        default_deadline_s: Optional[float] = None,
                        breaker_threshold: Optional[int] = 5,
                        breaker_cooldown_s: float = 1.0,
                        quantize: Optional[str] = None,
                        lm_kv: str = "paged", lm_page_size: int = 16,
                        lm_pages: Optional[int] = None,
                        lm_prefill_chunk: int = 8,
                        lm_speculate: str = "off",
                        lm_draft_len: int = 4,
                        lm_ship: bool = False,
                        lm_preempt: bool = False,
                        lm_swap_bytes: int = 64 << 20,
                        lm_brownout=None,
                        lm_tenants=None,
                        lm_hibernate_idle_s: Optional[float] = None,
                        lm_state_dir: Optional[str] = None,
                        lm_state_disk_bytes: int = 1 << 30,
                        lm_swap_quantize: bool = True,
                        role: str = ROLE_BOTH,
                        version: int = 0) -> Replica:
    """Thread-hosted replica: an in-process `UiServer` on a free port
    with its own engine surface (`/model/predict`, `/lm/generate`,
    `/serving/stats`, `/readyz`).  `warmup_example` pre-compiles every
    bucket shape BEFORE the replica is returned — a rolling swap attaches
    only warm standbys, which is what makes the flip zero-5xx.  `lm` is
    an optional `(cfg, params)` pair for the continuous LM pool."""
    from deeplearning4j_tpu.ui.server import UiServer

    srv = UiServer(host=host, port=0)
    if net is not None:
        from deeplearning4j_tpu.serving.bucketing import BucketLadder

        ladder = ladder if ladder is not None else BucketLadder()
        srv.serve_model(
            net, ladder=ladder,
            max_batch=(max_batch if max_batch is not None
                       else ladder.max_batch),
            max_wait_ms=max_wait_ms, warmup_example=warmup_example,
            max_queue_depth=max_queue_depth,
            default_deadline_s=default_deadline_s,
            breaker_threshold=breaker_threshold,
            breaker_cooldown_s=breaker_cooldown_s, quantize=quantize)
    if lm is not None:
        cfg, params = lm
        # a role-differentiated worker always speaks the page-shipping
        # wire plane — that is what its role MEANS; undifferentiated
        # workers opt in via lm_ship (sticky-session spill-over shipping)
        ship = bool(lm_ship) or role != ROLE_BOTH
        srv.serve_lm(cfg, params, slots=lm_slots,
                     max_queue_depth=max_queue_depth,
                     default_deadline_s=default_deadline_s,
                     breaker_threshold=breaker_threshold,
                     breaker_cooldown_s=breaker_cooldown_s,
                     kv=lm_kv, page_size=lm_page_size, pages=lm_pages,
                     prefill_chunk=lm_prefill_chunk,
                     speculate=lm_speculate, draft_len=lm_draft_len,
                     ship=ship, preempt=lm_preempt,
                     swap_bytes=lm_swap_bytes, brownout=lm_brownout,
                     tenants=lm_tenants,
                     hibernate_idle_s=lm_hibernate_idle_s,
                     state_dir=lm_state_dir,
                     state_disk_bytes=lm_state_disk_bytes,
                     swap_quantize=lm_swap_quantize)
        # warm the paged programs BEFORE the replica enters rotation —
        # same zero-compile-on-the-request-path rule as warmup_example
        if srv.state.lm_server is not None:
            srv.state.lm_server.warmup()
    srv.start()
    return Replica(name, srv.url, server=srv, version=version, role=role)


class FleetRouter:
    """Failover router over N replica endpoints.

    `factory(name) -> Replica` spawns a warm replica (see
    `spawn_local_replica`); `replicas` spawns that many up front.
    Externally launched workers attach by URL via `attach()`.  All
    dispatch is HTTP to the replica's endpoint surface, so thread-hosted
    and process-hosted replicas fail (and fail over) identically.
    """

    def __init__(self, factory: Optional[Callable[[str], Replica]] = None,
                 replicas: int = 0, *,
                 replica_breaker_threshold: int = 2,
                 replica_breaker_cooldown_s: float = 1.0,
                 health_interval_s: float = 1.0,
                 request_timeout_s: float = 60.0,
                 probe_timeout_s: float = 2.0,
                 affinity_prefix_tokens: int = 8,
                 affinity_spill_depth: int = 8,
                 disagg_min_prompt: int = 32,
                 min_replicas: int = 1, max_replicas: int = 8,
                 scale_up_depth: float = 4.0,
                 scale_down_depth: float = 0.5,
                 metrics: Optional[ServingMetrics] = None,
                 tracer: Optional[TraceRecorder] = None):
        self.factory = factory
        self.replica_breaker_threshold = int(replica_breaker_threshold)
        self.replica_breaker_cooldown_s = float(replica_breaker_cooldown_s)
        self.health_interval_s = float(health_interval_s)
        self.request_timeout_s = float(request_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.affinity_prefix_tokens = int(affinity_prefix_tokens)
        self.affinity_spill_depth = int(affinity_spill_depth)
        # disaggregation (ISSUE-14): prompts at least this long are
        # split prefill/decode when prefill-role workers exist; shorter
        # ones go straight to a decode worker (shipping a page of KV
        # costs more than prefilling a short prompt locally)
        self.disagg_min_prompt = int(disagg_min_prompt)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_depth = float(scale_up_depth)
        self.scale_down_depth = float(scale_down_depth)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        # fleet-level request tracing (ISSUE-8): every routed request
        # gets ONE trace whose spans name each dispatch attempt and
        # failover hop — a replica killed mid-storm shows up as a
        # failed span on the corpse and a successful span on the
        # replica that answered, under the same X-Request-Id the
        # replicas' own serving planes traced
        self.tracer = tracer if tracer is not None else TraceRecorder()
        self._lock = threading.Lock()
        self._replicas: List[Replica] = []
        self._seq = 0
        self._version = 0
        self.failovers = 0       # failed dispatch attempts that moved on
        self.swaps = 0           # completed rolling swaps
        self.scale_ups = 0
        self.scale_downs = 0
        self.health_polls = 0
        # disaggregation ledger (ISSUE-14): successful page shipments,
        # shipments that fell back to a local recompute (integrity /
        # dead worker / no prefill capacity), sticky-session routing
        # outcomes, and per-role successful-dispatch counts
        self.ships = 0
        self.ship_fallbacks = 0
        self.session_spill_ships = 0
        self.session_affinity_hits = 0
        self._role_requests: Dict[str, int] = {r: 0 for r in ROLES}
        self._session_route: "collections.OrderedDict[str, str]" = (
            collections.OrderedDict())
        self._session_capacity = 4096
        self.autoscale = False   # health loop calls autoscale_tick() too
        # process supervision (ISSUE-10): a FleetSupervisor installs
        # itself here so /fleet/stats carries the supervision section
        # (worker states, death classifications, quarantines)
        self.supervisor = None
        self._autoscale_busy = threading.Lock()
        # ledger counts of gracefully retired replicas (rolling swap /
        # scale-down) + how many retired without reporting (process
        # SIGTERM, corpse) — check_fleet_ledger folds these in
        self._retired_agg = {"requests": 0, "rejected": 0, "shed": 0,
                             "deadline_missed": 0, "poison_isolated": 0}
        self._retired_lost = 0
        self._stop_health = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        for _ in range(int(replicas)):
            self.add_replica()

    # ---- membership -------------------------------------------------------

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    def attach(self, replica: Replica) -> Replica:
        """Put a replica into rotation.  Assigns the router's breaker
        policy when the replica has none; every breaker transition feeds
        the replica's ejection/re-admission counters."""
        if replica.breaker is None:
            replica.breaker = CircuitBreaker(
                failure_threshold=self.replica_breaker_threshold,
                cooldown_s=self.replica_breaker_cooldown_s)
        replica.breaker.add_listener(replica._on_breaker)
        with self._lock:
            self._replicas.append(replica)
        return replica

    def add_replica(self, role: Optional[str] = None) -> Replica:
        """Spawn (via the factory) and attach one replica.  `role`
        (ISSUE-15 satellite) puts the new replica into a specific role
        group — how role-aware autoscaling grows the prefill and
        decode pools independently.  A factory that accepts a `role`
        keyword gets it (so it can build a ship-capable pool for a
        role-differentiated worker); otherwise the replica is
        re-stamped after the fact — role is ROUTER state (every worker
        serves the same surface), and a re-stamped worker whose pool
        happens not to ship only ever costs recompute fallbacks, never
        failed requests."""
        if self.factory is None:
            raise ValueError("no replica factory configured")
        if role is not None and role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        with self._lock:
            name = f"replica-{self._seq}"
            self._seq += 1
            version = self._version
        takes_role = False
        if role is not None:
            try:
                takes_role = "role" in inspect.signature(
                    self.factory).parameters
            except (TypeError, ValueError):
                takes_role = False
        replica = (self.factory(name, role=role) if takes_role
                   else self.factory(name))
        replica.version = version
        if role is not None:
            replica.role = role
        return self.attach(replica)

    def remove(self, replica: Replica, grace_s: float = 5.0) -> bool:
        """Take a replica out of rotation, drain it gracefully, stop
        it.  Returns True when its in-flight work finished in time.
        The replica's final serving counts are folded into the router's
        retired aggregate first, so the fleet ledger keeps balancing
        after rolling swaps and scale-downs instead of permanently
        reporting the retired replicas' requests as lost."""
        with self._lock:
            replica.state = REPLICA_DRAINING
        drained = replica.drain(grace_s)
        payload = self._replica_stats(replica)
        with self._lock:
            # fold ONLY when this call actually takes the replica out of
            # the list: concurrent remove()s of the same replica (e.g. a
            # rolling swap racing an async autoscale scale-down) must
            # count its requests exactly once
            removed = replica in self._replicas
            if removed:
                self._replicas.remove(replica)
                if payload is None:
                    # a process replica's SIGTERM drain already stopped
                    # its HTTP surface (and a corpse never answers): its
                    # counts are unrecoverable — the ledger reports that
                    # honestly
                    self._retired_lost += 1
                else:
                    _fold_plane_counts(self._retired_agg, payload)
        replica.stop()
        return drained

    def has_routable(self) -> bool:
        with self._lock:
            return any(r.routable() for r in self._replicas)

    # ---- picking ----------------------------------------------------------

    @staticmethod
    def _rendezvous_weight(key: str, name: str) -> bytes:
        return hashlib.blake2b(f"{key}|{name}".encode(),
                               digest_size=8).digest()

    def _pick(self, excluded: frozenset = frozenset(),
              key: Optional[str] = None,
              roles: Optional[Sequence[str]] = None) -> Optional[Replica]:
        """Choose a replica for one dispatch attempt.  Least-loaded by
        router-side in-flight (ties broken deterministically by name);
        with an affinity `key`, rendezvous hashing picks a preferred
        replica that stays stable under membership changes, spilling to
        least-loaded only when the preferred one is backed up by more
        than `affinity_spill_depth` requests over the least loaded.
        `roles` restricts candidacy (the disaggregated LM split);
        None = role-agnostic (classifier traffic)."""
        with self._lock:
            candidates = [r for r in self._replicas
                          if r.routable() and r.name not in excluded
                          and (roles is None or r.role in roles)]
        if not candidates:
            return None
        # a half-open replica is ejected-pending-probe, not healthy: its
        # in_flight is ~0 precisely BECAUSE it got no traffic, so plain
        # least-loaded would prefer the corpse for every new request.
        # Route to closed-breaker replicas whenever any exist; half-open
        # ones are the last resort (and `_dispatch`'s allow_dispatch
        # gate caps them to one probe at a time)
        healthy = [r for r in candidates
                   if r.breaker is None
                   or r.breaker.state == BREAKER_CLOSED]
        pool = healthy or candidates
        least = min(pool, key=lambda r: (r.in_flight, r.name))
        if key is None:
            return least
        preferred = max(pool,
                        key=lambda r: self._rendezvous_weight(key, r.name))
        if preferred.in_flight - least.in_flight > self.affinity_spill_depth:
            return least
        return preferred

    # ---- transport --------------------------------------------------------

    def _http(self, method: str, url: str, body=None,
              timeout: Optional[float] = None,
              headers: Optional[Dict[str, str]] = None,
              raw_body: Optional[bytes] = None,
              raw_response: bool = False):
        """One HTTP exchange.  JSON in/out by default; `raw_body` sends
        an octet-stream request (a KV page shipment), `raw_response`
        returns the body bytes unparsed (a shipment coming back)."""
        if raw_body is not None:
            data, ctype = raw_body, "application/octet-stream"
        else:
            data = None if body is None else json.dumps(body).encode()
            ctype = "application/json"
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": ctype, **(headers or {})})
        with urllib.request.urlopen(
                req, timeout=(timeout if timeout is not None
                              else self.request_timeout_s)) as resp:
            raw = resp.read()
            if raw_response:
                return resp.status, raw
            return resp.status, json.loads(raw or b"{}")

    def _dispatch(self, replica: Replica, path: str, body,
                  timeout: Optional[float] = None,
                  request_id: Optional[str] = None,
                  raw_body: Optional[bytes] = None,
                  raw_response: bool = False,
                  deadline_ms: Optional[float] = None):
        """One dispatch attempt against one replica.  Raises
        `FleetClientError` (4xx — never retried) or
        `_ReplicaDispatchError` (failover) on failure; feeds the
        replica's breaker and router-side counters.  `request_id` is
        forwarded as ``X-Request-Id`` so the replica's serving plane
        traces under the SAME id — including on failover resubmission.
        `raw_body`/`raw_response` carry the binary page-shipping legs
        through the same breaker/counter discipline."""
        if (replica.breaker is not None
                and not replica.breaker.allow_dispatch()):
            # half-open single-probe discipline (same as batcher/lm):
            # one request at a time rides the re-admission probe; the
            # rest fail over penalty-free instead of piling unbounded
            # traffic — each hanging up to request_timeout_s — onto a
            # replica the breaker has not re-admitted yet
            raise _ReplicaDispatchError(
                f"replica {replica.name} half-open: re-admission probe "
                f"already in flight", replica_fault=False)
        with replica.lock:
            replica.in_flight += 1
        try:
            headers = {}
            if request_id:
                headers["X-Request-Id"] = request_id
            if deadline_ms is not None:
                # binary legs cannot carry deadline_ms in a JSON body:
                # the remaining budget rides the header instead
                headers["X-Deadline-Ms"] = f"{deadline_ms:.0f}"
            try:
                _, payload = self._http(
                    "POST", replica.url + path, body, timeout,
                    headers=headers or None,
                    raw_body=raw_body, raw_response=raw_response)
            except urllib.error.HTTPError as e:
                status = e.code
                try:
                    err_payload = json.loads(e.read() or b"{}")
                except ValueError:
                    err_payload = {}
                detail = err_payload.get("error", "")
                if 400 <= status < 500:
                    raise FleetClientError(
                        detail or f"replica {replica.name} answered "
                                  f"{status}", status=status,
                        retry_after_s=err_payload.get(
                            "retry_after_s")) from e
                # 503/504: alive but unavailable (overload / draining /
                # deadline) — fail over penalty-free.  Any other 5xx is
                # a replica fault and counts toward ejection.
                raise _ReplicaDispatchError(
                    f"replica {replica.name} answered {status}: {detail}",
                    replica_fault=status not in (503, 504)) from e
            except (http.client.HTTPException, OSError, ValueError) as e:
                # connection refused/reset, short read, timeout, or a
                # 2xx answer whose body is not JSON (a misconfigured
                # attached endpoint): the replica is gone, wedged, or
                # answering garbage — a breaker-worthy fault either way
                raise _ReplicaDispatchError(
                    f"replica {replica.name} unusable: "
                    f"{type(e).__name__}: {e}", replica_fault=True) from e
        except FleetClientError:
            # the replica ANSWERED — the payload was the problem.  An
            # answer is liveness evidence: it re-admits a half-open
            # replica (releasing the probe claim) and resets the
            # failure streak, exactly like a 200 would
            if replica.breaker is not None:
                replica.breaker.record_success()
            raise
        except _ReplicaDispatchError as e:
            if replica.breaker is not None:
                if e.replica_fault:
                    replica.breaker.record_failure()
                else:
                    # 503/504: alive-but-unavailable is neither
                    # re-admission evidence nor a fault — just release
                    # any probe claim so the half-open window stays open
                    replica.breaker.abandon_probe()
            with replica.lock:
                if e.replica_fault:
                    replica.failures += 1
            raise
        finally:
            with replica.lock:
                replica.in_flight -= 1
        if replica.breaker is not None:
            replica.breaker.record_success()
        with replica.lock:
            replica.dispatches += 1
        with self._lock:
            self._role_requests[replica.role] = (
                self._role_requests.get(replica.role, 0) + 1)
        return payload

    def _submit(self, path: str, body, key: Optional[str] = None,
                timeout: Optional[float] = None,
                request_id: Optional[str] = None,
                roles: Optional[Sequence[str]] = None,
                session_id: Optional[str] = None):
        """Failover loop: try routable replicas (excluded set grows per
        failure) until one answers or none remain.  Predict is pure, so
        resubmitting a failed dispatch elsewhere is always safe.  The
        whole loop is ONE trace under `request_id` (minted here when the
        caller has none): one span per dispatch attempt plus a
        failover_hop span per resubmission.  `session_id` is noted
        against the replica that ACTUALLY answered — a failover must
        not leave the sticky-session map pointing at a corpse."""
        t0 = time.perf_counter()
        rid = request_id or new_request_id()
        spans: List[Dict] = []

        def finish(status: str, error: Optional[str] = None):
            self.tracer.record(trace(
                rid, "fleet", spans, status=status, path=path,
                failovers=sum(1 for s in spans
                              if s["name"] == "failover_hop") or None,
                error=error))

        # the client's deadline is a TOTAL budget across failovers: each
        # retry forwards only what remains of it, and an exhausted
        # budget is a typed 504 here — not a fresh full-deadline
        # dispatch per attempt
        deadline_ms = (body.get("deadline_ms")
                       if isinstance(body, dict) else None)
        excluded: set = set()
        last: Optional[BaseException] = None
        while True:
            if deadline_ms is not None:
                remaining = deadline_ms - (time.perf_counter() - t0) * 1e3
                if remaining <= 0:
                    self.metrics.record_deadline_missed()
                    self.metrics.record_rejected()
                    finish("timeout", error=str(last) if last else None)
                    raise DeadlineExceededError(
                        f"deadline of {deadline_ms:.0f}ms exhausted "
                        f"after {len(excluded)} failover(s)"
                        + (f" (last failure: {last})" if last else ""))
                body["deadline_ms"] = remaining
            replica = self._pick(frozenset(excluded), key, roles=roles)
            if replica is None:
                break
            ta = time.perf_counter()
            try:
                payload = self._dispatch(replica, path, body, timeout,
                                         request_id=rid)
            except FleetClientError as e:
                # the payload's fault everywhere — no failover, but it
                # is still a typed rejection in the router's ledger:
                # client_balanced (submitted == requests + rejected)
                # must keep holding when some submissions are 4xx
                spans.append(span("dispatch", ta, time.perf_counter(),
                                  replica=replica.name, outcome="4xx"))
                self.metrics.record_rejected()
                finish("client_error", error=str(e))
                raise
            except _ReplicaDispatchError as e:
                tb = time.perf_counter()
                spans.append(span(
                    "dispatch", ta, tb, replica=replica.name,
                    outcome=("fault" if e.replica_fault
                             else "unavailable"), error=str(e)[:200]))
                spans.append(span("failover_hop", tb, tb,
                                  excluded=replica.name))
                excluded.add(replica.name)
                with self._lock:
                    self.failovers += 1
                last = e
                continue
            spans.append(span("dispatch", ta, time.perf_counter(),
                              replica=replica.name, outcome="ok"))
            self.metrics.record_request(time.perf_counter() - t0)
            self._note_session_route(session_id, replica)
            finish("ok")
            return payload
        self.metrics.record_rejected()
        finish("unroutable", error=str(last) if last else None)
        raise ServingUnavailableError(
            "no routable replica" + (f" (last failure: {last})"
                                     if last else ""))

    # ---- client surface ---------------------------------------------------

    def predict_proba(self, x, deadline_s: Optional[float] = None,
                      timeout: Optional[float] = None,
                      request_id: Optional[str] = None,
                      tenant: Optional[str] = None) -> np.ndarray:
        """[n, ...] features -> [n, classes] activations, served by
        whichever healthy replica the router picks (float32 survives the
        JSON hop bit-exactly: float32 -> float64 -> shortest-repr
        round-trip -> float32 is the identity).  `tenant` forwards
        verbatim (ISSUE-16): the replica's registry owns the vocabulary
        — unknown 400s there, over-quota 429s there, both typed."""
        body: Dict = {"features": np.asarray(x, np.float32).tolist()}
        if deadline_s is not None:
            body["deadline_ms"] = float(deadline_s) * 1e3
        if tenant is not None:
            body["tenant"] = str(tenant)
        payload = self._submit("/model/predict", body, timeout=timeout,
                               request_id=request_id)
        return np.asarray(payload["outputs"], np.float32)

    def predict(self, x, deadline_s: Optional[float] = None,
                timeout: Optional[float] = None,
                request_id: Optional[str] = None,
                tenant: Optional[str] = None) -> np.ndarray:
        return np.argmax(self.predict_proba(x, deadline_s=deadline_s,
                                            timeout=timeout,
                                            request_id=request_id,
                                            tenant=tenant),
                         axis=-1)

    def _lm_affinity_key(self, ids: Sequence[int],
                         session_id: Optional[str]) -> str:
        """The rendezvous key for one LM request: sticky `session_id`
        when the client sent one (a multi-turn chat keeps landing on
        the replica holding its pages — its prompts GROW every turn, so
        prefix hashing alone would eventually re-route it), else the
        prompt's first `affinity_prefix_tokens` tokens."""
        if session_id is not None:
            return f"session:{session_id}"
        return ",".join(map(str, ids[:self.affinity_prefix_tokens]))

    def _note_session_route(self, session_id: Optional[str],
                            replica: Replica) -> None:
        """Router-side sticky-session accounting: a session that lands
        on the same replica as its previous turn is an affinity hit."""
        if session_id is None:
            return
        with self._lock:
            prev = self._session_route.get(session_id)
            if prev is not None:
                self._session_route.move_to_end(session_id)
                if prev == replica.name:
                    self.session_affinity_hits += 1
            self._session_route[session_id] = replica.name
            while len(self._session_route) > self._session_capacity:
                self._session_route.popitem(last=False)

    def _has_role(self, role: str) -> bool:
        with self._lock:
            return any(r.role == role and r.routable()
                       for r in self._replicas)

    def generate_payload(self, prompt_ids: Sequence[int],
                         max_new_tokens: int, temperature: float = 0.0,
                         seed: int = 0, top_k: int = 0, top_p: float = 1.0,
                         beam_size: int = 0,
                         deadline_s: Optional[float] = None,
                         timeout: Optional[float] = None,
                         request_id: Optional[str] = None,
                         session_id: Optional[str] = None,
                         priority: Optional[str] = None,
                         tenant: Optional[str] = None) -> Dict:
        """LM generation with affinity routing and role scheduling.

        Affinity: a sticky `session_id` (when sent) or the first
        `affinity_prefix_tokens` prompt tokens pick the preferred
        DECODE-capable replica via rendezvous hashing, so a shared
        system prompt — or a whole conversation — keeps hitting the
        same replica's prefix cache.  Roles (ISSUE-14): when
        prefill-role workers exist and the prompt is at least
        `disagg_min_prompt` tokens, the request is split — a prefill
        worker chews the prompt and ships the finished KV pages to the
        decode replica picked up front; short prompts go straight to
        decode workers.  A sticky session spilling off its overloaded
        preferred replica is served by page shipping (prefill on the
        replica holding its radix pages, decode on the spill target)
        instead of a cold recompute.  Every ship failure — integrity,
        dead worker, dry pool — falls back down the ladder to a local
        recompute on a decode worker: zero failed requests by
        construction.  Returns the replica's full JSON answer (`ids`,
        plus `score` on the beam path).  top-k / top-p / beam forward
        to the replica's whole-sequence leg; every mode is seeded and
        deterministic, so failover resubmission stays safe."""
        ids = [int(t) for t in prompt_ids]
        key = self._lm_affinity_key(ids, session_id)
        body: Dict = {"prompt_ids": ids,
                      "max_new_tokens": int(max_new_tokens),
                      "temperature": float(temperature), "seed": int(seed)}
        if session_id is not None:
            body["session_id"] = str(session_id)
        if priority is not None:
            # forwarded verbatim: the replica's admission gate owns the
            # vocabulary, so an unknown class 400s there and propagates
            body["priority"] = str(priority)
        if tenant is not None:
            # same verbatim-forward contract (ISSUE-16): the replica's
            # tenant registry owns the vocabulary — unknown 400s there,
            # over-quota 429s there, and both propagate typed
            body["tenant"] = str(tenant)
        if int(top_k):
            body["top_k"] = int(top_k)
        if float(top_p) < 1.0:
            body["top_p"] = float(top_p)
        if int(beam_size) > 1:
            body["beam_size"] = int(beam_size)
        if deadline_s is not None:
            body["deadline_ms"] = float(deadline_s) * 1e3
        whole_sequence = (int(top_k) > 0 or float(top_p) < 1.0
                          or int(beam_size) > 1)
        long_prompt = len(ids) >= self.disagg_min_prompt
        if not whole_sequence and long_prompt:
            if self._has_role(ROLE_PREFILL):
                # role split: prefill workers exist for this prompt
                return self._submit_disagg(body, key, timeout=timeout,
                                           request_id=request_id,
                                           session_id=session_id)
            # spill-over candidacy only matters for long prompts on a
            # shipping-capable fleet — short prompts skip the extra
            # pick entirely and go straight to the submit loop
            replica, spilled, preferred = self._pick_decode(key)
            if (spilled and preferred is not None
                    and self._replica_ships(preferred)):
                # sticky-session spill-over (ISSUE-14): the preferred
                # replica holds this conversation's radix pages but is
                # backed up — prefill THERE (radix-cheap), ship the
                # pages to the spill target instead of recomputing cold
                with self._lock:
                    self.session_spill_ships += 1
                return self._submit_disagg(body, key, timeout=timeout,
                                           request_id=request_id,
                                           session_id=session_id,
                                           prefill_pref=preferred,
                                           decode_pref=replica)
        return self._submit("/lm/generate", body, key=key,
                            timeout=timeout, request_id=request_id,
                            roles=_DECODE_ROLES, session_id=session_id)

    def _pick_decode(self, key: str):
        """The decode-side pick with the spill decision made visible:
        returns (chosen, spilled, preferred) where `spilled` means the
        rendezvous-preferred replica was passed over for load."""
        chosen = self._pick(key=key, roles=_DECODE_ROLES)
        if chosen is None:
            return None, False, None
        with self._lock:
            pool = [r for r in self._replicas
                    if r.routable() and r.role in _DECODE_ROLES]
        if not pool:              # membership raced the pick away
            return chosen, False, None
        rendezvous = max(pool, key=lambda r: self._rendezvous_weight(
            key, r.name))
        spilled = chosen.name != rendezvous.name
        return chosen, spilled, rendezvous

    @staticmethod
    def _replica_ships(replica: Replica) -> bool:
        """Best-effort: can this replica serve /lm/prefill?  Prefill
        workers always can; a both-role replica only when its pool was
        spawned with lm_ship=True — the endpoint answers 400 otherwise
        and the ladder falls back to recompute, so this check is an
        optimization, not a correctness gate."""
        if replica.role == ROLE_PREFILL:
            return True
        srv = getattr(replica.server, "state", None)
        lm = getattr(srv, "lm_server", None) if srv is not None else None
        return bool(getattr(lm, "ship", False)) if lm is not None else True

    def _submit_disagg(self, body: Dict, key: str,
                       timeout: Optional[float] = None,
                       request_id: Optional[str] = None,
                       session_id: Optional[str] = None,
                       prefill_pref: Optional[Replica] = None,
                       decode_pref: Optional[Replica] = None) -> Dict:
        """The disaggregated submit: prefill -> ship -> decode, one
        trace under one X-Request-Id naming the prefill worker, the
        wire hop, and the decode worker.  The failure ladder never
        fails the request: a dead/failing prefill worker resubmits the
        prompt to a peer; no peer (or a rejected/corrupt shipment, or a
        dying decode worker) falls back to a plain /lm/generate on the
        decode pool — recompute, not error."""
        t0 = time.perf_counter()
        rid = request_id or new_request_id()
        spans: List[Dict] = []
        # the client's deadline is a TOTAL budget across the whole
        # prefill -> ship -> decode ladder (same discipline as
        # `_submit`): each leg gets only what remains of it
        deadline_ms = (body.get("deadline_ms")
                       if isinstance(body, dict) else None)

        def _remaining_ms() -> Optional[float]:
            if deadline_ms is None:
                return None
            rem = deadline_ms - (time.perf_counter() - t0) * 1e3
            if rem <= 0:
                self.metrics.record_deadline_missed()
                self.metrics.record_rejected()
                self.tracer.record(trace(
                    rid, "fleet", spans, status="timeout",
                    path="/lm/generate", disagg=True))
                raise DeadlineExceededError(
                    f"deadline of {deadline_ms:.0f}ms exhausted "
                    f"mid-ship")
            return rem

        decode = decode_pref or self._pick(key=key, roles=_DECODE_ROLES)
        if decode is None:
            self.metrics.record_rejected()
            raise ServingUnavailableError(
                "no routable decode-capable replica")
        prefill_body = {k: v for k, v in body.items()
                        if k not in ("top_k", "top_p", "beam_size")}
        excluded: set = set()
        blob = None
        last: Optional[BaseException] = None
        while blob is None:
            rem = _remaining_ms()
            if rem is not None:
                prefill_body["deadline_ms"] = rem
            pre = (prefill_pref
                   if prefill_pref is not None
                   and prefill_pref.name not in excluded
                   and prefill_pref.routable()
                   else self._pick(frozenset(excluded),
                                   roles=_PREFILL_ROLES))
            if pre is None or pre.name == decode.name:
                # no prefill capacity left (or only the decode replica
                # itself): recompute locally on the decode side
                break
            ta = time.perf_counter()
            try:
                blob = self._dispatch(pre, "/lm/prefill", prefill_body,
                                      timeout, request_id=rid,
                                      raw_response=True)
            except FleetClientError as e:
                # the prefill worker ANSWERED 4xx: a 422 is the typed
                # "this worker cannot ship" (kind: page_ship) — fall
                # back to recompute; any other 4xx means the request is
                # bad everywhere (propagate — recomputing would 400 too)
                spans.append(span("dispatch", ta, time.perf_counter(),
                                  replica=pre.name, stage="prefill",
                                  outcome="4xx"))
                if e.status == 422:
                    last = e
                    break
                self.metrics.record_rejected()
                raise
            except _ReplicaDispatchError as e:
                # a dead prefill worker's in-flight prompt resubmits to
                # a peer — the mid-ship-kill acceptance path
                tb = time.perf_counter()
                spans.append(span(
                    "dispatch", ta, tb, replica=pre.name,
                    stage="prefill",
                    outcome=("fault" if e.replica_fault
                             else "unavailable"), error=str(e)[:200]))
                spans.append(span("failover_hop", tb, tb,
                                  excluded=pre.name))
                excluded.add(pre.name)
                with self._lock:
                    self.failovers += 1
                last = e
                continue
            spans.append(span("dispatch", ta, time.perf_counter(),
                              replica=pre.name, stage="prefill",
                              outcome="ok"))
        if blob is not None:
            ts = time.perf_counter()
            try:
                payload = self._dispatch(
                    decode, "/lm/admit_pages", None, timeout,
                    request_id=rid, raw_body=blob,
                    deadline_ms=_remaining_ms())
                td = time.perf_counter()
                spans.append(span("ship", ts, td, bytes=len(blob),
                                  decode=decode.name))
                spans.append(span("dispatch", ts, td,
                                  replica=decode.name, stage="decode",
                                  outcome="ok"))
                with self._lock:
                    self.ships += 1
                self.metrics.record_request(time.perf_counter() - t0)
                self._note_session_route(session_id, decode)
                self.tracer.record(trace(
                    rid, "fleet", spans, status="ok",
                    path="/lm/generate", disagg=True))
                return payload
            except (FleetClientError, _ReplicaDispatchError) as e:
                # rejected shipment (422 integrity/geometry, a pool
                # that cannot admit) or a decode worker dying mid-admit:
                # recompute below — never a failed request
                spans.append(span("dispatch", ts, time.perf_counter(),
                                  replica=decode.name, stage="decode",
                                  outcome="ship_rejected",
                                  error=str(e)[:200]))
                last = e
        # --- recompute ladder: plain generate on the decode pool
        with self._lock:
            self.ship_fallbacks += 1
        spans.append(span("failover_hop", time.perf_counter(),
                          time.perf_counter(), fallback="recompute",
                          error=(str(last)[:200] if last else None)))
        self.tracer.record(trace(rid, "fleet", spans,
                                 status="recompute_fallback",
                                 path="/lm/generate", disagg=True))
        rem = _remaining_ms()
        if rem is not None:
            # hand the recompute only what the ship attempt left over —
            # _submit treats body["deadline_ms"] as a fresh total budget
            body = dict(body, deadline_ms=rem)
        return self._submit("/lm/generate", body, key=key,
                            timeout=timeout, request_id=rid,
                            roles=_DECODE_ROLES, session_id=session_id)

    def open_lm_stream(self, prompt_ids: Sequence[int],
                       max_new_tokens: int, temperature: float = 0.0,
                       seed: int = 0, top_k: int = 0,
                       top_p: float = 1.0, beam_size: int = 0,
                       deadline_s: Optional[float] = None,
                       timeout: Optional[float] = None,
                       request_id: Optional[str] = None,
                       session_id: Optional[str] = None,
                       priority: Optional[str] = None,
                       tenant: Optional[str] = None):
        """Open one SSE token stream against a decode-capable replica
        (affinity-routed like `generate_payload`); returns the raw
        `http.client`-style response object — the caller relays/parses
        the `text/event-stream` bytes and MUST close it (closing also
        records the stream's true duration into the router's request
        latency).  top-k/top-p/beam forward so the replica can answer
        its typed 400 — silently downgrading a sampled stream to
        greedy would serve DIFFERENT generations than the
        single-server surface refuses to.  Failover covers
        connect-time failures only: once events flow, tokens already
        reached the client and a resubmission would replay them — a
        mid-stream death surfaces as a truncated stream."""
        ids = [int(t) for t in prompt_ids]
        key = self._lm_affinity_key(ids, session_id)
        body: Dict = {"prompt_ids": ids,
                      "max_new_tokens": int(max_new_tokens),
                      "temperature": float(temperature),
                      "seed": int(seed), "stream": True}
        if int(top_k):
            body["top_k"] = int(top_k)
        if float(top_p) < 1.0:
            body["top_p"] = float(top_p)
        if int(beam_size) > 1:
            body["beam_size"] = int(beam_size)
        if session_id is not None:
            body["session_id"] = str(session_id)
        if priority is not None:
            body["priority"] = str(priority)
        if tenant is not None:
            body["tenant"] = str(tenant)
        if deadline_s is not None:
            body["deadline_ms"] = float(deadline_s) * 1e3
        rid = request_id or new_request_id()
        excluded: set = set()
        last: Optional[BaseException] = None
        while True:
            replica = self._pick(frozenset(excluded), key,
                                 roles=_DECODE_ROLES)
            if replica is None:
                self.metrics.record_rejected()
                raise ServingUnavailableError(
                    "no routable decode-capable replica for the stream"
                    + (f" (last failure: {last})" if last else ""))
            req = urllib.request.Request(
                replica.url + "/lm/generate",
                data=json.dumps(body).encode(), method="POST",
                headers={"Content-Type": "application/json",
                         "X-Request-Id": rid})
            # streams feed the SAME replica accounting as _dispatch:
            # in_flight for the stream's whole lifetime (least-loaded
            # and spill decisions must see long-lived streams), breaker
            # verdicts per outcome, dispatches on success — an SSE-heavy
            # fleet must not fly blind
            with replica.lock:
                replica.in_flight += 1
            try:
                resp = urllib.request.urlopen(
                    req, timeout=(timeout if timeout is not None
                                  else self.request_timeout_s))
            except urllib.error.HTTPError as e:
                with replica.lock:
                    replica.in_flight -= 1
                detail = b""
                try:
                    detail = e.read()
                except OSError:
                    pass
                if 400 <= e.code < 500:
                    # an answer is liveness evidence, like _dispatch
                    if replica.breaker is not None:
                        replica.breaker.record_success()
                    raise FleetClientError(
                        detail.decode(errors="replace")
                        or f"replica {replica.name} answered {e.code}",
                        status=e.code) from e
                if replica.breaker is not None:
                    if e.code in (503, 504):
                        replica.breaker.abandon_probe()
                    else:
                        replica.breaker.record_failure()
                if e.code not in (503, 504):
                    with replica.lock:
                        replica.failures += 1
                excluded.add(replica.name)
                with self._lock:
                    self.failovers += 1
                last = e
                continue
            except (http.client.HTTPException, OSError) as e:
                with replica.lock:
                    replica.in_flight -= 1
                if replica.breaker is not None:
                    replica.breaker.record_failure()
                with replica.lock:
                    replica.failures += 1
                excluded.add(replica.name)
                with self._lock:
                    self.failovers += 1
                last = e
                continue
            if replica.breaker is not None:
                replica.breaker.record_success()
            with replica.lock:
                replica.dispatches += 1
            with self._lock:
                self._role_requests[replica.role] = (
                    self._role_requests.get(replica.role, 0) + 1)
            self._note_session_route(session_id, replica)
            # at close (idempotent): release the in-flight claim and
            # record the stream's TRUE duration — recording 0.0 at
            # connect would collapse the fleet's latency percentiles
            # for exactly the TTFT-sensitive traffic streaming exists
            # for
            t_open = time.perf_counter()
            orig_close = resp.close
            recorded = []

            def close_and_record():
                if not recorded:
                    recorded.append(True)
                    with replica.lock:
                        replica.in_flight -= 1
                    self.metrics.record_request(
                        time.perf_counter() - t_open)
                orig_close()

            resp.close = close_and_record
            return resp

    def generate(self, prompt_ids: Sequence[int], max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0, beam_size: int = 0,
                 deadline_s: Optional[float] = None,
                 timeout: Optional[float] = None,
                 session_id: Optional[str] = None) -> List[int]:
        payload = self.generate_payload(
            prompt_ids, max_new_tokens, temperature=temperature, seed=seed,
            top_k=top_k, top_p=top_p, beam_size=beam_size,
            deadline_s=deadline_s, timeout=timeout,
            session_id=session_id)
        return list(payload["ids"])

    # ---- health: eject -> probe -> re-admit -------------------------------

    def _probe_readyz(self, replica: Replica) -> bool:
        try:
            status, _ = self._http("GET", replica.url + "/readyz",
                                   timeout=self.probe_timeout_s)
            return status == 200
        except (http.client.HTTPException, OSError, ValueError):
            # HTTPError (e.g. a 503 from a draining/broken replica) is
            # an OSError subclass; ValueError covers a 200 whose body is
            # not JSON.  Any failure mode means not ready — and nothing
            # may escape here, or it would kill the health daemon
            return False

    def poll_health_once(self,
                         _async_autoscale: bool = False) -> Dict[str, bool]:
        """One health sweep: probe every in-rotation replica's /readyz.
        A failed probe is a breaker failure (threshold consecutive
        failures eject); a successful probe on a half-open breaker IS
        the re-admission.  Ejected replicas inside their cooldown are
        skipped — the cooldown elapsing re-opens the probe window.

        A green probe on a CLOSED breaker records nothing: /readyz
        succeeding must not erase dispatch-failure evidence, or a
        replica that 500s every dispatch while its readyz stays green
        would never accumulate the threshold consecutive failures and
        never be ejected.  Successful dispatches already reset the
        streak; the probe only votes to re-admit."""
        with self._lock:
            self.health_polls += 1
            replicas = [r for r in self._replicas
                        if r.state == REPLICA_ACTIVE]
        results: Dict[str, bool] = {}
        # probe concurrently: one wedged replica must cost the sweep one
        # probe_timeout_s, not serialize behind every other probe and
        # degrade the whole fleet's detection cadence
        probe = [r for r in replicas
                 if not (r.breaker is not None and r.breaker.rejecting())]
        if probe:                          # skipped: cooldown not elapsed
            with futures.ThreadPoolExecutor(
                    max_workers=min(8, len(probe))) as pool:
                outcomes = list(pool.map(self._probe_readyz, probe))
            for r, ok in zip(probe, outcomes):
                results[r.name] = ok
                if r.breaker is not None:
                    if ok:
                        if r.breaker.state == BREAKER_HALF_OPEN:
                            r.breaker.record_success()
                    else:
                        r.breaker.record_failure()
        if self.autoscale:
            if _async_autoscale:
                self._spawn_autoscale_tick()
            else:
                self.autoscale_tick()
        return results

    def _spawn_autoscale_tick(self) -> None:
        """Run one autoscale decision OFF the health thread: a
        scale-down drains (seconds of grace) and a scale-up warms every
        bucket (seconds of compilation) — neither may stall /readyz
        probing, or a replica dying during the action would go
        undetected for the whole window.  At most one action runs at a
        time; ticks arriving while one is in flight are dropped (the
        next poll re-evaluates from fresh queue depths)."""
        if not self._autoscale_busy.acquire(blocking=False):
            return

        def run():
            try:
                self.autoscale_tick()
            finally:
                self._autoscale_busy.release()

        threading.Thread(target=run, daemon=True,
                         name="fleet-autoscale").start()

    def start_health_loop(self,
                          interval_s: Optional[float] = None) -> None:
        if interval_s is not None:
            self.health_interval_s = float(interval_s)
        if self._health_thread is not None:
            return
        self._stop_health.clear()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="fleet-health")
        self._health_thread.start()

    def _health_loop(self) -> None:
        # the loop dispatches autoscale actions to a side thread so a
        # drain or a standby warmup can never stall /readyz probing;
        # explicit poll_health_once() callers keep the synchronous tick
        while not self._stop_health.wait(self.health_interval_s):
            self.poll_health_once(_async_autoscale=True)

    def stop_health_loop(self) -> None:
        self._stop_health.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
            self._health_thread = None

    # ---- rolling weight swap ----------------------------------------------

    def rolling_swap(self, factory: Optional[Callable[[str], Replica]]
                     = None, grace_s: float = 10.0) -> List[Dict]:
        """Zero-downtime weight swap.  Per active replica, in order:
        spawn a standby with the new weights (the factory warms every
        bucket before returning, so the standby never compiles on the
        request path), attach it, take the old replica out of rotation,
        drain its in-flight work, stop it.  Traffic keeps flowing the
        whole time — at least N replicas are routable at every instant,
        and a request that raced into the draining replica fails over.
        `factory` (when given) becomes the fleet's replica factory, so
        scale-ups after the swap also serve the new weights."""
        if factory is not None:
            self.factory = factory
        if self.factory is None:
            raise ValueError("rolling_swap needs a replica factory")
        with self._lock:
            self._version += 1
            olds = [r for r in self._replicas
                    if r.state == REPLICA_ACTIVE]
        steps = []
        for old in olds:
            standby = self.add_replica()
            drained = self.remove(old, grace_s)
            steps.append({"retired": old.name, "standby": standby.name,
                          "drained": drained})
        with self._lock:
            self.swaps += 1
        return steps

    # ---- queue-depth-driven scaling ---------------------------------------

    def queue_depth_by_role(self) -> Dict[str, int]:
        """Router-side queue-depth proxy split per replica role
        (ISSUE-15 satellite; the `fleet_queue_depth{role}` gauge): the
        summed in-flight of active replicas in each role that has any.
        The split is what lets autoscaling grow prefill and decode
        pools independently — the aggregate number is decode-biased
        because decode requests live for the whole token loop while
        prefill requests come and go."""
        with self._lock:
            out: Dict[str, int] = {}
            for r in self._replicas:
                if r.state == REPLICA_ACTIVE:
                    out[r.role] = out.get(r.role, 0) + r.in_flight
            return out

    def autoscale_tick(self, grace_s: float = 5.0) -> int:
        """One scaling decision from the router-side queue-depth proxy,
        evaluated PER ROLE (mean in-flight per active replica of that
        role) so a prefill backlog grows the prefill pool and a decode
        backlog the decode pool, independently.  An undifferentiated
        fleet (every replica `both`) is one role group — exactly the
        historic fleet-wide behavior.  At most one action per tick
        (roles evaluated in sorted order, scale-up first): +1 scaled
        up, -1 scaled down through graceful drain, 0 nothing."""
        with self._lock:
            active = [r for r in self._replicas
                      if r.state == REPLICA_ACTIVE]
        if not active:
            return 0
        groups: Dict[str, List[Replica]] = {}
        for r in active:
            groups.setdefault(r.role, []).append(r)
        loads = {role: sum(r.in_flight for r in rs) / len(rs)
                 for role, rs in groups.items()}
        if len(active) < self.max_replicas and self.factory is not None:
            for role in sorted(groups):
                if loads[role] > self.scale_up_depth:
                    self.add_replica(
                        role=role if len(groups) > 1 else None)
                    with self._lock:
                        self.scale_ups += 1
                    return 1
        if len(active) > self.min_replicas:
            for role in sorted(groups):
                rs = groups[role]
                # never drain a role's LAST replica while other roles
                # exist — a disaggregated fleet with zero prefill
                # workers silently loses its split
                if len(rs) < 2 and len(groups) > 1:
                    continue
                if loads[role] < self.scale_down_depth:
                    victim = min(rs, key=lambda r: (r.in_flight, r.name))
                    self.remove(victim, grace_s)
                    with self._lock:
                        self.scale_downs += 1
                    return -1
        return 0

    # ---- stats / lifecycle ------------------------------------------------

    def _replica_stats(self, replica: Replica) -> Optional[Dict]:
        try:
            _, payload = self._http("GET", replica.url + "/serving/stats",
                                    timeout=self.probe_timeout_s)
            return payload
        except (http.client.HTTPException, OSError, ValueError):
            return None

    def fleet_stats(self, include_replica_stats: bool = True) -> Dict:
        """The /fleet/stats payload: fleet-level metrics + per-replica
        breakdown (each replica's own /serving/stats inlined), plus the
        aggregated resilience ledger (`check_fleet_ledger`)."""
        with self._lock:
            counters = {"failovers": self.failovers, "swaps": self.swaps,
                        "scale_ups": self.scale_ups,
                        "scale_downs": self.scale_downs,
                        "health_polls": self.health_polls,
                        "weights_version": self._version}
            disagg = {"ships": self.ships,
                      "ship_fallbacks": self.ship_fallbacks,
                      "session_spill_ships": self.session_spill_ships,
                      "session_affinity_hits": self.session_affinity_hits,
                      "role_requests": dict(self._role_requests)}
            replicas = list(self._replicas)
            retired = {"aggregate": dict(self._retired_agg),
                       "lost": self._retired_lost}
        # fan the per-replica /serving/stats fetches out concurrently:
        # sequentially, one slow replica holds up the whole payload for
        # its probe timeout, and N replicas cost N timeouts end-to-end
        fetch = [r for r in replicas
                 if include_replica_stats and r.state != REPLICA_STOPPED]
        stats_by_name: Dict[str, Optional[Dict]] = {}
        if fetch:
            with futures.ThreadPoolExecutor(
                    max_workers=min(8, len(fetch))) as pool:
                for r, payload in zip(
                        fetch, pool.map(self._replica_stats, fetch)):
                    stats_by_name[r.name] = payload
        entries = []
        for r in replicas:
            entry = r.summary()
            if r.name in stats_by_name:
                entry["stats"] = stats_by_name[r.name]
            entries.append(entry)
        fleet = dict(self.metrics.snapshot())
        fleet["replicas_active"] = sum(
            1 for r in replicas if r.state == REPLICA_ACTIVE)
        fleet["replicas_routable"] = sum(
            1 for r in replicas if r.routable())
        fleet.update(counters)
        # role-split queue-depth proxy (ISSUE-15 satellite): the
        # autoscaler's per-role input, exposed so operators can see
        # WHY a role pool grew (the aggregate is decode-biased)
        fleet["queue_depth_by_role"] = self.queue_depth_by_role()
        # fleet-level LM prefix-reuse view (ISSUE-7): the router's
        # prefix-affinity hashing exists to concentrate shared prompts
        # per replica — this is the number that says whether it worked
        prefix = {"queries": 0, "hits": 0, "tokens_saved": 0}
        for payload in stats_by_name.values():
            lm = (payload or {}).get("lm") or {}
            if lm.get("prefix_queries"):
                prefix["queries"] += int(lm["prefix_queries"])
                prefix["hits"] += int(lm.get("prefix_hits") or 0)
                prefix["tokens_saved"] += int(
                    lm.get("prefix_tokens_saved") or 0)
        if prefix["queries"]:
            prefix["hit_rate"] = round(
                prefix["hits"] / prefix["queries"], 3)
            fleet["lm_prefix"] = prefix
        # fleet-level speculative-decode view (ISSUE-13): drafted vs
        # accepted across every replica's LM pool — the fleet-wide
        # accept rate is what says speculation is paying for itself
        spec = {"drafted": 0, "accepted": 0, "rounds": 0}
        for payload in stats_by_name.values():
            lm = (payload or {}).get("lm") or {}
            if lm.get("spec_drafted"):
                spec["drafted"] += int(lm["spec_drafted"])
                spec["accepted"] += int(lm.get("spec_accepted") or 0)
                spec["rounds"] += int(lm.get("spec_rounds") or 0)
        if spec["drafted"]:
            spec["accept_rate"] = round(
                spec["accepted"] / spec["drafted"], 3)
            fleet["lm_speculate"] = spec
        # fleet-level disaggregation view (ISSUE-14): router-side ship /
        # fallback / session counters plus the per-replica pool ship
        # ledgers (pages_shipped, ship_bytes, ship_ms) and replica-side
        # session affinity hits aggregated through /serving/stats
        ship_agg = {"pages_shipped": 0, "ship_bytes": 0, "out": 0,
                    "in": 0}
        sess_hits = 0
        for payload in stats_by_name.values():
            lm = (payload or {}).get("lm") or {}
            shp = lm.get("ship") or {}
            for k in ship_agg:
                ship_agg[k] += int(shp.get(k) or 0)
            sess_hits += int(lm.get("session_affinity_hits") or 0)
        disagg["replica_session_affinity_hits"] = sess_hits
        if ship_agg["out"] or ship_agg["in"]:
            disagg["pool_ship"] = ship_agg
        if (disagg["ships"] or disagg["ship_fallbacks"]
                or disagg["session_affinity_hits"] or sess_hits
                or any(r.role != ROLE_BOTH for r in replicas)):
            fleet["disagg"] = disagg
        # fleet-level overload-survival view (ISSUE-15): preemption,
        # host-swap, and brownout aggregated across the LM pools —
        # fleet brownout level is the WORST replica's (a fleet is as
        # degraded as its most degraded pool)
        pressure = {"preemptions": 0, "swap_out": 0, "swap_in": 0,
                    "swap_evicted": 0, "swap_corrupt": 0,
                    "brownout_level": 0, "brownout_transitions": 0,
                    "brownout_shed": 0}
        saw_pressure = False
        for payload in stats_by_name.values():
            lm = (payload or {}).get("lm") or {}
            if lm.get("preemptions"):
                pressure["preemptions"] += int(lm["preemptions"])
                saw_pressure = True
            swap = lm.get("swap") or {}
            if swap:
                pressure["swap_out"] += int(swap.get("out") or 0)
                pressure["swap_in"] += int(swap.get("in") or 0)
                pressure["swap_evicted"] += int(
                    swap.get("evicted") or 0)
                pressure["swap_corrupt"] += int(
                    swap.get("corrupt") or 0)
                saw_pressure = True
            br = lm.get("brownout") or {}
            if br:
                pressure["brownout_level"] = max(
                    pressure["brownout_level"], int(br.get("level") or 0))
                pressure["brownout_transitions"] += int(
                    br.get("transitions") or 0)
                pressure["brownout_shed"] += int(br.get("shed") or 0)
                saw_pressure = True
        if saw_pressure:
            fleet["lm_pressure"] = pressure
        # fleet-level tenancy view (ISSUE-16): per-tenant event totals
        # summed across both planes of every replica, burn rate folded
        # as the MAX across replicas — a tenant is as unhealthy as its
        # worst pool's view of it, and averaging would let one melting
        # replica hide behind nine idle ones
        tenant_agg: Dict[str, Dict] = {}
        for payload in stats_by_name.values():
            for plane in ("classifier", "lm"):
                section = (payload or {}).get(plane) or {}
                for tn, cell in (section.get("tenants") or {}).items():
                    slot = tenant_agg.setdefault(tn, {})
                    for event, v in cell.items():
                        if event == "burn_rate":
                            slot["burn_rate"] = max(
                                float(slot.get("burn_rate") or 0.0),
                                float(v))
                        else:
                            slot[event] = (int(slot.get(event) or 0)
                                           + int(v))
        if tenant_agg:
            fleet["tenants"] = tenant_agg
        out = {"fleet": fleet, "replicas": entries, "retired": retired}
        supervisor = self.supervisor
        if supervisor is not None:
            out["supervision"] = supervisor.stats()
        if include_replica_stats:
            out["ledger"] = check_fleet_ledger(out)
        return out

    def begin_drain(self) -> None:
        for r in self.replicas():
            with self._lock:
                r.state = REPLICA_DRAINING
            r.begin_drain()

    def drain(self, grace_s: float = 5.0) -> bool:
        """Fleet-wide graceful drain: every replica stops admission,
        in-flight work gets the (shared) grace window."""
        self.begin_drain()
        deadline = time.perf_counter() + max(0.0, grace_s)
        drained = True
        for r in self.replicas():
            drained &= r.drain(max(0.0, deadline - time.perf_counter()))
        return drained

    def stop(self) -> None:
        self.stop_health_loop()
        for r in self.replicas():
            r.stop()
        with self._lock:
            self._replicas.clear()


def _fold_plane_counts(agg: Dict, payload: Dict) -> None:
    """Add one replica's /serving/stats ledger counts (both planes)
    into the running aggregate."""
    for plane in ("classifier", "lm"):
        section = payload.get(plane)
        if not section:
            continue
        for k in agg:
            agg[k] += int(section.get(k) or 0)


_RECONCILE_EVENTS = ("requests", "rejected", "shed", "deadline_missed")


def _reconcile_breakdowns(name: str, payload: Dict,
                          failures: List[str]) -> None:
    """Per-replica, per-plane breakdown reconciliation (ISSUE-16
    satellite): every accounting site carries its priority-class and
    tenant labels along with the plane total, so within one plane the
    per-class and per-tenant ledgers must each re-add to that plane's
    own counters.  A breakdown that drifts from its total means some
    site bumped a counter without its ride-along (or vice versa) —
    exactly the bug class this check exists to catch.  The breakdown
    sections are fire-once (absent until the plane records a classed /
    tenanted event), so an absent section is vacuously balanced; a
    PRESENT section must account for everything, which is why the
    default priority class and the `default` tenant are real labels
    rather than an untracked remainder."""
    for plane in ("classifier", "lm"):
        section = payload.get(plane)
        if not section:
            continue
        for breakdown in ("priority", "tenants"):
            cells = section.get(breakdown)
            if not cells:
                continue
            for event in _RECONCILE_EVENTS:
                total = int(section.get(event) or 0)
                part = sum(int(c.get(event) or 0)
                           for c in cells.values())
                if part != total:
                    failures.append(
                        f"{name}/{plane}: sum({breakdown}.{event})="
                        f"{part} != {event}={total}")


def check_fleet_ledger(stats: Dict,
                       submitted: Optional[int] = None) -> Dict:
    """Aggregate the per-replica resilience ledgers out of a
    `fleet_stats()` payload and check the cross-layer invariants:

    - every request the fleet answered was answered by exactly ONE
      replica, so `sum(replica requests) == fleet requests` — counting
      replicas the router retired gracefully (rolling swap, scale-down:
      their final counts live in the payload's `retired` aggregate, so
      the invariant keeps holding across membership changes, not just
      for the replicas currently attached);
    - client-side (when `submitted` is passed):
      `submitted == fleet requests + fleet rejected` — a request either
      got an answer or a typed rejection, never silence.

    Replica-side `rejected`/`shed` above the fleet's own counts are the
    failovers: a replica refused or shed work that another replica then
    served.  `balanced` is only asserted when every replica's stats
    were reachable (a killed replica cannot report, and a retired
    process replica's counts die with its SIGTERM — `retired.lost`).

    ISSUE-16 satellite: within each reachable replica's planes, the
    per-class (`priority`) and per-tenant (`tenants`) breakdowns must
    also re-add to the plane's own totals; any drift lands in
    `failures` (naming the replica, plane, and event) and clears
    `balanced` — the /fleet/stats front turns a non-empty `failures`
    list into a typed failure instead of serving corrupt accounting
    with a 200."""
    agg = {"requests": 0, "rejected": 0, "shed": 0, "deadline_missed": 0,
           "poison_isolated": 0}
    retired = stats.get("retired") or {}
    for k, v in (retired.get("aggregate") or {}).items():
        if k in agg:
            agg[k] += int(v or 0)
    reachable = int(retired.get("lost") or 0) == 0
    failures: List[str] = []
    for entry in stats.get("replicas", ()):
        payload = entry.get("stats")
        if payload is None:
            if entry.get("state") != REPLICA_STOPPED:
                reachable = False
            continue
        _fold_plane_counts(agg, payload)
        _reconcile_breakdowns(str(entry.get("name") or "?"), payload,
                              failures)
    fleet = stats.get("fleet", {})
    out = {"aggregate": agg, "replicas_reachable": reachable,
           "fleet_requests": int(fleet.get("requests") or 0),
           "fleet_rejected": int(fleet.get("rejected") or 0),
           "failures": failures}
    out["balanced"] = (reachable and not failures
                       and agg["requests"] == out["fleet_requests"])
    if submitted is not None:
        out["submitted"] = int(submitted)
        out["client_balanced"] = (
            int(submitted) == out["fleet_requests"] + out["fleet_rejected"])
    return out


# ---------------------------------------------------------------------------
# The fleet's own HTTP front


class _FleetHTTPServer(ServingHTTPServer):
    # restart-after-drain socket semantics (SO_REUSEADDR + daemon
    # handler threads) live on the shared base in serving/resilience.py
    pass


class _FleetHandler(ServingHTTPMixin, BaseHTTPRequestHandler):
    # _send/_json/_body/_deadline_s + the typed-failure -> status
    # mapping come from ServingHTTPMixin (serving/resilience.py), shared
    # with ui/server.py's _Handler so the two HTTP contracts cannot
    # drift.

    @property
    def router(self) -> FleetRouter:
        return self.server.fleet_router  # type: ignore[attr-defined]

    def do_GET(self) -> None:  # noqa: N802
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            # Prometheus exposition: fleet-plane serving metrics,
            # per-replica router-side gauges, breaker/page families,
            # compiles_total (ISSUE-8, docs/observability.md)
            registry = self.server.obs_registry  # type: ignore[attr-defined]
            self._send(200, EXPOSITION_CONTENT_TYPE,
                       registry.exposition().encode())
            return
        if path == "/trace/recent":
            traces = self.router.tracer.recent()
            if "format=chrome" in query:
                self._json(200, chrome_trace(traces))
            else:
                self._json(200, {"traces": traces,
                                 "recorded": self.router.tracer.recorded})
            return
        if self.path == "/healthz":
            self._json(200, {"ok": True})
        elif self.path == "/readyz":
            draining = self.server.fleet_draining  # type: ignore[attr-defined]
            if draining:
                self._json(503, {"ready": False, "reasons": ["draining"]},
                           headers={"Retry-After": 1})
            elif not self.router.has_routable():
                self._json(503, {"ready": False,
                                 "reasons": ["no routable replica"]},
                           headers={"Retry-After": 1})
            else:
                self._json(200, {"ready": True})
        elif self.path == "/fleet/stats":
            stats = self.router.fleet_stats()
            failures = (stats.get("ledger") or {}).get("failures") or []
            if failures:
                # one re-poll before declaring drift: a snapshot cut
                # between a plane counter and its breakdown ride-along
                # can be off by one for an instant; REAL drift (an
                # accounting site missing its label) survives the retry
                stats = self.router.fleet_stats()
                failures = (stats.get("ledger")
                            or {}).get("failures") or []
            if failures:
                # drifting ledger = typed failure (ISSUE-16): corrupt
                # accounting must not be served as a healthy 200 — the
                # payload rides along so the operator can see WHERE
                self._json(500, {"error": ("fleet ledger drift: "
                                           + "; ".join(failures)),
                                 "stats": stats})
            else:
                self._json(200, stats)
        elif self.path == "/serving/stats":
            # the cheap fleet-level view (no per-replica HTTP fan-out)
            self._json(200, self.router.fleet_stats(
                include_replica_stats=False))
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self) -> None:  # noqa: N802
        try:
            body = self._body()
        except (ValueError, json.JSONDecodeError) as e:
            self._json(400, {"error": str(e)})
            return
        try:
            if self.server.fleet_draining:  # type: ignore[attr-defined]
                raise ServingUnavailableError(
                    "fleet is draining: admission stopped")
            self._route_post(body)
        except FleetClientError as e:
            # relay a replica's quota 429 with its Retry-After intact —
            # the bucket deficit was computed where the tokens live
            payload = {"error": str(e)}
            headers = None
            if e.retry_after_s is not None:
                payload["retry_after_s"] = e.retry_after_s
                headers = {"Retry-After": max(1, math.ceil(e.retry_after_s))}
            self._json(e.status, payload, headers=headers)
        except Exception as e:  # noqa: BLE001 — the front must keep serving; unexpected -> 500 once, typed stay 4xx/503
            # typed serving failures map via the shared mixin
            # (UnservableShapeError -> 400, DeadlineExceededError -> 504,
            # overload/unavailable -> 503 + Retry-After); a malformed
            # request (bad deadline, wrong field types) is the client's
            # 400; anything else is the fleet front's own fault: 500
            if self.respond_typed_failure(e):
                return
            if isinstance(e, (ValueError, TypeError)):
                self._json(400, {"error": str(e)})
            else:
                self._json(500, {"error": repr(e)})

    def _route_post(self, body) -> None:
        if self.path == "/model/predict":
            feats = body.get("features")
            if not feats:
                self._json(400, {"error": "features required"})
                return
            probs = self.router.predict_proba(
                feats, deadline_s=self._deadline_s(body),
                request_id=self.request_id(),
                tenant=self._tenant(body))
            self._json(200, {
                "predictions": np.argmax(probs, axis=-1).tolist(),
                "outputs": np.asarray(probs).tolist()})
        elif self.path == "/lm/generate":
            prompt = body.get("prompt_ids")
            if not prompt:
                self._json(400, {"error": "prompt_ids required"})
                return
            session_id = body.get("session_id")
            if session_id is not None:
                session_id = str(session_id)
            if bool(body.get("stream", False)):
                # SSE passthrough: relay the decode replica's event
                # stream byte for byte (TTFT reaches the client through
                # the fleet front exactly as it left the pool)
                self._relay_stream(body, session_id)
                return
            # forward the sampling mode too: silently downgrading a
            # top-k/top-p/beam request to greedy would answer 200 with
            # DIFFERENT generations than the single-server surface
            payload = self.router.generate_payload(
                prompt, int(body.get("max_new_tokens", 32)),
                temperature=float(body.get("temperature", 0.0)),
                seed=int(body.get("seed", 0)) & 0x7FFFFFFF,
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 1.0)),
                beam_size=int(body.get("beam_size", 0)),
                deadline_s=self._deadline_s(body),
                request_id=self.request_id(),
                session_id=session_id,
                priority=body.get("priority"),
                tenant=self._tenant(body))
            self._json(200, payload)
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def _relay_stream(self, body, session_id) -> None:
        """Relay one replica SSE stream through the fleet front.
        Pre-stream failures (no routable replica, 4xx) still map to
        proper statuses; once bytes flow, a replica death surfaces as a
        truncated stream — tokens the client already has cannot be
        un-sent, so there is no mid-stream failover."""
        resp = self.router.open_lm_stream(
            body.get("prompt_ids"), int(body.get("max_new_tokens", 32)),
            temperature=float(body.get("temperature", 0.0)),
            seed=int(body.get("seed", 0)) & 0x7FFFFFFF,
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            beam_size=int(body.get("beam_size", 0)),
            deadline_s=self._deadline_s(body),
            request_id=self.request_id(), session_id=session_id,
            priority=body.get("priority"), tenant=self._tenant(body))
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            rid = getattr(self, "_request_id", None)
            if rid is not None:
                self.send_header("X-Request-Id", rid)
            self.end_headers()
            try:
                while True:
                    # read1: hand over whatever bytes are available —
                    # a full read(n) would buffer events and destroy
                    # the TTFT the stream exists to surface
                    chunk = (resp.read1(512) if hasattr(resp, "read1")
                             else resp.read(512))
                    if not chunk:
                        break
                    self.wfile.write(chunk)
                    self.wfile.flush()
            except (http.client.HTTPException, OSError):
                # client went away (BrokenPipe/reset) OR the replica
                # read failed mid-stream (timeout, short read).  The
                # SSE headers are already on the wire, so the ONLY
                # valid move is to stop relaying — answering again
                # would append a second HTTP response into the
                # half-delivered event stream.  Closing resp (finally)
                # propagates the disconnect to the replica, which
                # abandons the lane.
                pass
        finally:
            resp.close()


class FleetServer:
    """The fleet's HTTP front: `FleetServer(router, port=0).start()`;
    `.url` for clients; `.drain()` for the SIGTERM path; `.stop()` to
    halt (stops the router, its health loop and every replica)."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 8080):
        self.router = router
        self._server = _FleetHTTPServer((host, port), _FleetHandler)
        self._server.fleet_router = router  # type: ignore[attr-defined]
        self._server.fleet_draining = False  # type: ignore[attr-defined]
        # observability plane (ISSUE-8): the fleet front's /metrics —
        # fleet-plane serving metrics + per-replica router-side samples
        # + the process-wide compile counter
        self.registry = MetricsRegistry()
        router.metrics.register_into(self.registry, plane="fleet")
        self.registry.register_collector(self._fleet_samples)
        self.registry.register_collector(
            compile_watcher().collector_samples)
        if router.supervisor is not None:
            # process supervision installed before the front: its
            # fleet_process_* counters ride this /metrics (a supervisor
            # attached later registers itself via register_collector)
            self.registry.register_collector(
                router.supervisor.collector_samples)
        self.registry.gauge(
            "server_uptime_seconds", "seconds since server construction",
            fn=lambda: self.registry.uptime_s)
        self._server.obs_registry = self.registry  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="fleet-front")

    def _fleet_samples(self):
        """Collector: router counters + per-replica router-side gauges
        (sampled at scrape time, no HTTP fan-out — the replicas publish
        their own planes on their own /metrics)."""
        router = self.router
        with router._lock:
            counters = (("fleet_failovers_total", "counter",
                         "failed dispatch attempts that moved on",
                         router.failovers),
                        ("fleet_swaps_total", "counter",
                         "completed rolling swaps", router.swaps),
                        ("fleet_scale_ups_total", "counter",
                         "autoscale scale-ups", router.scale_ups),
                        ("fleet_scale_downs_total", "counter",
                         "autoscale scale-downs", router.scale_downs),
                        ("fleet_health_polls_total", "counter",
                         "health sweeps", router.health_polls),
                        ("fleet_weights_version", "gauge",
                         "current rolling-swap weights version",
                         router._version),
                        ("fleet_ships_total", "counter",
                         "KV page shipments routed prefill->decode",
                         router.ships),
                        ("fleet_ship_fallbacks_total", "counter",
                         "shipments that fell back to local recompute",
                         router.ship_fallbacks),
                        ("fleet_session_spill_ships_total", "counter",
                         "sticky-session spill-overs served by shipping",
                         router.session_spill_ships),
                        ("fleet_session_affinity_hits_total", "counter",
                         "session requests routed to their previous "
                         "replica", router.session_affinity_hits))
            role_counts = dict(router._role_requests)
        from deeplearning4j_tpu.serving.metrics import _BREAKER_VALUES

        for name, kind, help, value in counters:
            yield (name, kind, help, {}, float(value))
        for role, n in sorted(role_counts.items()):
            yield ("fleet_role_requests_total", "counter",
                   "successful dispatches by replica role",
                   {"role": role}, float(n))
        # per-role queue-depth gauge (ISSUE-15 satellite): the
        # autoscaler's split input, scrapeable
        for role, depth in sorted(router.queue_depth_by_role().items()):
            yield ("fleet_queue_depth", "gauge",
                   "router-side in-flight requests by replica role",
                   {"role": role}, float(depth))
        for r in router.replicas():
            labels = {"replica": r.name}
            with r.lock:
                samples = (("fleet_replica_in_flight", "gauge",
                            "router-side in-flight requests",
                            r.in_flight),
                           ("fleet_replica_dispatches_total", "counter",
                            "successful dispatches via the router",
                            r.dispatches),
                           ("fleet_replica_failures_total", "counter",
                            "replica-fault dispatch failures",
                            r.failures),
                           ("fleet_replica_ejections_total", "counter",
                            "breaker ejections", r.ejections),
                           ("fleet_replica_readmissions_total", "counter",
                            "breaker re-admissions", r.readmissions))
            for name, kind, help, value in samples:
                yield (name, kind, help, dict(labels), float(value))
            state = r.breaker.state if r.breaker is not None else "closed"
            yield ("fleet_replica_breaker_state", "gauge",
                   "replica breaker (0 closed, 1 open, 2 half_open)",
                   dict(labels), float(_BREAKER_VALUES.get(state, 0)))

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "FleetServer":
        self._thread.start()
        return self

    def begin_drain(self) -> None:
        """Stop admission at the front (new requests 503, /readyz flips)
        and on every replica; queued + in-flight work keeps running."""
        self._server.fleet_draining = True  # type: ignore[attr-defined]
        self.router.begin_drain()

    def drain(self, grace_s: float = 5.0) -> bool:
        """Fleet-wide graceful drain; the front keeps answering
        /healthz, /readyz and /fleet/stats throughout."""
        self._server.fleet_draining = True  # type: ignore[attr-defined]
        return self.router.drain(grace_s)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self.router.stop()


__all__ = [
    "FleetClientError",
    "FleetRouter",
    "FleetServer",
    "REPLICA_ACTIVE",
    "REPLICA_DRAINING",
    "REPLICA_STOPPED",
    "ROLE_BOTH",
    "ROLE_DECODE",
    "ROLE_PREFILL",
    "ROLES",
    "Replica",
    "check_fleet_ledger",
    "spawn_local_replica",
]
