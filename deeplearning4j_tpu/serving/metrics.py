"""Serving metrics: the numbers that tell you whether batching is working.

Per the serving cost model (docs/performance.md): throughput is bought
with batch occupancy (real rows per dispatch) and bounded compiles;
latency is spent in queue wait plus device compute.  `ServingMetrics`
tracks both sides — per-request latency percentiles via
`runtime.profiler.LatencyRecorder`, and per-dispatch occupancy / queue
depth / token counts — and snapshots them for `GET /serving/stats` and
the bench rows.

Since ISSUE-8 the cells themselves are `obs.registry` metric objects
(counters/gauges/histograms), so one source of truth feeds BOTH the
stats endpoints (`snapshot()`) and the Prometheus exposition at
``GET /metrics``: `register_into(registry, plane=...)` publishes every
cell under a plane label — no parallel snapshot dicts.  End-to-end
latency is additionally SPLIT into queue-wait and dispatch-compute
histograms (the batcher/LM pool stamp both timestamps), and every
snapshot carries ``uptime_s`` plus a monotonic ``snapshot_at`` so
scrapers can compute rates without client-side clocks.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from deeplearning4j_tpu.runtime.profiler import LatencyRecorder
from deeplearning4j_tpu.serving.pressure import PRIORITY_CLASSES

# the per-class resilience events snapshot()/exposition break out —
# the existing deadline/shed/breaker discipline, preserved per class
_CLASS_EVENTS = ("requests", "rejected", "shed", "deadline_missed",
                 "preempted")

# the per-tenant traffic-shaping events (ISSUE-16): the per-class set
# plus `throttled` (quota 429s — a tenant-only concept; priority
# classes are never metered).  Tenant names are an OPEN vocabulary
# fixed at serve time, so unlike `class_counters` the cells are
# created lazily on first record (see `record_tenant`).
_TENANT_EVENTS = _CLASS_EVENTS + ("throttled",)

# breaker state -> gauge value (the exposition's numeric encoding;
# the string stays in /serving/stats)
_BREAKER_VALUES = {"closed": 0, "open": 1, "half_open": 2}


def _ms(summary: Dict[str, float]) -> Dict[str, float]:
    """A Histogram.summary() (seconds) as the stats-endpoint ms shape."""
    if not summary.get("count"):
        return {"count": 0}
    return {"count": summary["count"],
            "mean_ms": round(summary["mean"] * 1e3, 3),
            "p50_ms": round(summary["p50"] * 1e3, 3),
            "p95_ms": round(summary["p95"] * 1e3, 3),
            "p99_ms": round(summary["p99"] * 1e3, 3)}


class ServingMetrics:
    """Thread-safe counters shared by the micro-batcher and LM server."""

    def __init__(self, latency_window: int = 4096):
        self._lock = threading.Lock()
        self.latency = LatencyRecorder(window=latency_window)
        # ---- registry-native cells (ISSUE-8): the same objects render
        # /serving/stats and /metrics
        self.requests_total = Counter(
            "serving_requests_total", "requests served to completion")
        self.dispatches_total = Counter(
            "serving_dispatches_total", "device dispatches")
        self.rows_total = Counter(
            "serving_rows_total", "real example rows dispatched")
        self.padded_rows_total = Counter(
            "serving_padded_rows_total",
            "bucket capacity dispatched (incl. padding)")
        self.tokens_total = Counter(
            "serving_tokens_total", "LM tokens emitted")
        self.queue_depth_gauge = Gauge(
            "serving_queue_depth", "requests waiting in the queue")
        # resilience ledger (ISSUE-4): submitted == requests + rejected
        # + shed + other-errors
        self.rejected_total = Counter(
            "serving_rejected_total",
            "refused at admission (overload/breaker/draining)")
        self.shed_total = Counter(
            "serving_shed_total", "removed from a queue before dispatch")
        self.deadline_missed_total = Counter(
            "serving_deadline_missed_total",
            "failed because the deadline passed")
        self.poison_isolated_total = Counter(
            "serving_poison_isolated_total",
            "requests isolated as poison by bisection")
        self.breaker_state_gauge = Gauge(
            "serving_breaker_state",
            "circuit breaker state (0 closed, 1 open, 2 half_open)")
        self.breaker_opens_total = Counter(
            "serving_breaker_opens_total", "breaker open transitions")
        # paged-KV / prefix-reuse ledger (ISSUE-7)
        self.prefix_queries_total = Counter(
            "serving_prefix_queries_total", "LM admissions radix-queried")
        self.prefix_hits_total = Counter(
            "serving_prefix_hits_total", "admissions that reused pages")
        self.prefix_tokens_saved_total = Counter(
            "serving_prefix_tokens_saved_total",
            "prefill steps skipped via cached prefixes")
        self.pages_in_use_gauge = Gauge(
            "serving_kv_pages_in_use", "KV pages currently refcounted")
        self.pages_free_gauge = Gauge(
            "serving_kv_pages_free", "KV pages on the free list")
        self.pages_total_gauge = Gauge(
            "serving_kv_pages_total", "KV pool size (0 = not paged)")
        # speculative-decode ledger (ISSUE-13): tokens-per-dispatch is
        # bought with accepted drafts — E[tokens/round] = accept + 1
        self.decode_rounds_total = Counter(
            "serving_lm_decode_lane_rounds_total",
            "decode-phase lane-dispatches (each emits >= 1 token)")
        self.decode_tokens_total = Counter(
            "serving_lm_decode_tokens_total",
            "tokens emitted by decode-phase lane-dispatches")
        self.spec_rounds_total = Counter(
            "serving_spec_rounds_total",
            "lane-dispatches that verified >= 1 draft token")
        self.spec_drafted_total = Counter(
            "serving_spec_drafted_total",
            "draft tokens proposed to the verify step")
        self.spec_accepted_total = Counter(
            "serving_spec_accepted_total",
            "draft tokens the target model accepted")
        # disaggregated-serving ledger (ISSUE-14): KV page shipping in
        # and out of this pool, time-to-first-token, and sticky-session
        # affinity — the numbers that say whether the prefill/decode
        # split and the session routing are paying for themselves
        self.ships_out_total = Counter(
            "serving_kv_ships_out_total",
            "lanes exported as KV page shipments")
        self.ships_in_total = Counter(
            "serving_kv_ships_in_total",
            "lanes admitted from KV page shipments")
        self.pages_shipped_total = Counter(
            "serving_kv_pages_shipped_total",
            "KV pages moved through shipments (both directions)")
        self.ship_bytes_total = Counter(
            "serving_kv_ship_bytes_total",
            "KV page payload bytes moved through shipments")
        self.ship_hist = Histogram(
            "serving_kv_ship_seconds",
            "device-side gather/install time per shipment")
        self.ttft_hist = Histogram(
            "serving_lm_ttft_seconds",
            "admission to first committed token")
        self.session_queries_total = Counter(
            "serving_session_queries_total",
            "LM requests that carried a session_id")
        self.session_affinity_hits_total = Counter(
            "serving_session_affinity_hits_total",
            "session_id requests that landed on a pool that had "
            "already served the session")
        # overload-survival ledger (ISSUE-15): priority classes,
        # preemption with host swap-out, and the brownout ladder
        self.class_counters = {
            (event, cls): Counter(
                f"serving_lm_class_{event}_total",
                f"LM {event} by priority class")
            for event in _CLASS_EVENTS for cls in PRIORITY_CLASSES}
        self.preemptions_total = Counter(
            "serving_lm_preemptions_total",
            "lanes preempted so higher-priority work could admit")
        self.swap_out_total = Counter(
            "serving_kv_swap_out_total",
            "preempted lanes swapped out to the host store")
        self.swap_in_total = Counter(
            "serving_kv_swap_in_total",
            "preempted lanes restored from the host store")
        self.swap_pages_total = Counter(
            "serving_kv_swap_pages_total",
            "KV pages moved through host swap (both directions)")
        self.swap_bytes_total = Counter(
            "serving_kv_swap_bytes_total",
            "serialized bytes moved through host swap")
        self.swap_evicted_total = Counter(
            "serving_kv_swap_evicted_total",
            "swapped lanes whose state the byte-capped store dropped "
            "(restore recomputes from the prompt)")
        self.swap_corrupt_total = Counter(
            "serving_kv_swap_corrupt_total",
            "swapped lanes whose state failed the SHA-256 restore "
            "check (restore recomputes from the prompt)")
        # tiered-state hibernation ledger (ISSUE-19): idle sticky
        # sessions parked on the host/disk hierarchy and resumed later,
        # plus the compression ledger (at-rest vs exact bytes — the
        # quantized tiers' ~4x claim is verified against these)
        self.hibernated_total = Counter(
            "serving_kv_hibernated_total",
            "idle sessions hibernated to the tiered state store")
        self.resumed_total = Counter(
            "serving_kv_resumed_total",
            "sessions resumed from the tiered state store")
        self.hibernate_pages_total = Counter(
            "serving_kv_hibernate_pages_total",
            "KV pages moved through hibernation (both directions)")
        self.hibernate_bytes_total = Counter(
            "serving_kv_hibernate_bytes_total",
            "at-rest bytes moved through hibernation (quantized when on)")
        self.hibernate_exact_bytes_total = Counter(
            "serving_kv_hibernate_exact_bytes_total",
            "exact-dtype-equivalent bytes of hibernated pages (the "
            "compression ratio's denominator)")
        self.hibernate_evicted_total = Counter(
            "serving_kv_hibernate_evicted_total",
            "hibernated sessions whose state fell off the byte-capped "
            "tiers (resume recomputes from the prompt)")
        self.hibernate_corrupt_total = Counter(
            "serving_kv_hibernate_corrupt_total",
            "hibernated sessions whose blob failed its integrity check "
            "at resume (recompute from the prompt)")
        self.brownout_level_gauge = Gauge(
            "serving_brownout_level",
            "degradation-ladder level (0 healthy .. 4 shedding)")
        self.brownout_transitions_total = Counter(
            "serving_brownout_transitions_total",
            "degradation-ladder level changes (both directions)")
        self.brownout_shed_total = Counter(
            "serving_brownout_shed_total",
            "best_effort admissions refused by ladder level 4")
        # multi-tenant ledger (ISSUE-16): tenant names are an OPEN
        # vocabulary (fixed by the registry at serve time, unknown
        # here), so the per-tenant cells are created lazily on first
        # record and LATE-registered onto every registry this plane
        # already published into — `register_into` remembers its
        # (registry, labels) pairs for exactly that
        self.tenant_counters: Dict = {}      # (event, tenant) -> Counter
        self.tenant_burn_gauges: Dict = {}   # tenant -> Gauge
        self._tenant_registrations: list = []
        # latency: end-to-end histogram + the queue-wait vs
        # dispatch-compute split (ISSUE-8 satellite — the batcher knows
        # both timestamps; before this they were collapsed into one
        # end-to-end number)
        self.latency_hist = Histogram(
            "serving_request_seconds", "end-to-end request latency")
        self.queue_wait_hist = Histogram(
            "serving_queue_wait_seconds",
            "admission to dispatch-start wait")
        self.compute_hist = Histogram(
            "serving_compute_seconds",
            "dispatch-start to dispatch-end (device compute + pad)")
        # ---- plain fields (cross-cell state the snapshot reads)
        self._queue_depth = 0
        self._max_occupancy = 0
        self._started: Optional[float] = None
        self._created = time.monotonic()
        self._breaker_state = "closed"

    def register_into(self, registry: MetricsRegistry,
                      **labels) -> "ServingMetrics":
        """Publish every cell on `registry` under `labels` (e.g.
        ``plane="classifier"``).  Re-registering the same labels (a
        rolling swap's replacement engine) takes over the series."""
        for m in (self.requests_total, self.dispatches_total,
                  self.rows_total, self.padded_rows_total,
                  self.tokens_total, self.queue_depth_gauge,
                  self.rejected_total, self.shed_total,
                  self.deadline_missed_total, self.poison_isolated_total,
                  self.breaker_state_gauge, self.breaker_opens_total,
                  self.prefix_queries_total, self.prefix_hits_total,
                  self.prefix_tokens_saved_total, self.pages_in_use_gauge,
                  self.pages_free_gauge, self.pages_total_gauge,
                  self.decode_rounds_total, self.decode_tokens_total,
                  self.spec_rounds_total, self.spec_drafted_total,
                  self.spec_accepted_total,
                  self.ships_out_total, self.ships_in_total,
                  self.pages_shipped_total, self.ship_bytes_total,
                  self.ship_hist, self.ttft_hist,
                  self.session_queries_total,
                  self.session_affinity_hits_total,
                  self.preemptions_total, self.swap_out_total,
                  self.swap_in_total, self.swap_pages_total,
                  self.swap_bytes_total, self.swap_evicted_total,
                  self.swap_corrupt_total,
                  self.hibernated_total, self.resumed_total,
                  self.hibernate_pages_total, self.hibernate_bytes_total,
                  self.hibernate_exact_bytes_total,
                  self.hibernate_evicted_total,
                  self.hibernate_corrupt_total,
                  self.brownout_level_gauge,
                  self.brownout_transitions_total,
                  self.brownout_shed_total,
                  self.latency_hist, self.queue_wait_hist,
                  self.compute_hist):
            registry.register(m, **labels)
        for (_event, cls), m in self.class_counters.items():
            registry.register(m, priority=cls, **labels)
        with self._lock:
            self._tenant_registrations.append((registry, dict(labels)))
            tenant_cells = ([(tn, m) for (_e, tn), m
                             in self.tenant_counters.items()]
                            + list(self.tenant_burn_gauges.items()))
        for tn, m in tenant_cells:
            registry.register(m, tenant=tn, **labels)
        return self

    # ---- recording --------------------------------------------------------

    def _touch(self) -> None:
        # unlocked fast path: after the first request this is a single
        # attribute read per record call (the slow path's lock still
        # makes the one assignment race-free) — per-record lock traffic
        # is exactly what the bench obs row's 3% budget polices
        if self._started is not None:  # noqa: LCK101 — DCL fast path; write is locked below
            return
        with self._lock:
            if self._started is None:
                self._started = time.perf_counter()

    def record_dispatch(self, n_real: int, n_padded: int,
                        queue_depth: Optional[int] = None) -> None:
        self._touch()
        self.dispatches_total.inc()
        self.rows_total.inc(int(n_real))
        self.padded_rows_total.inc(int(n_padded))
        with self._lock:
            if queue_depth is not None:  # None = depth owned by the queue
                self._queue_depth = int(queue_depth)
                self.queue_depth_gauge.set(queue_depth)
            self._max_occupancy = max(self._max_occupancy, int(n_real))

    def record_request(self, latency_s: float,
                       queue_wait_s: Optional[float] = None,
                       compute_s: Optional[float] = None) -> None:
        """One request served to completion.  `queue_wait_s` (admission
        to dispatch start) and `compute_s` (dispatch start to end) feed
        the split histograms when the queue owner knows them."""
        self._touch()
        self.requests_total.inc()
        self.latency.record(latency_s)
        self.latency_hist.observe(latency_s)
        if queue_wait_s is not None:
            self.queue_wait_hist.observe(max(0.0, queue_wait_s))
        if compute_s is not None:
            self.compute_hist.observe(max(0.0, compute_s))

    def record_tokens(self, n: int) -> None:
        self._touch()
        self.tokens_total.inc(int(n))

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = int(depth)
        self.queue_depth_gauge.set(depth)

    def record_rejected(self, n: int = 1) -> None:
        self._touch()
        self.rejected_total.inc(int(n))

    def record_shed(self, n: int = 1) -> None:
        self._touch()
        self.shed_total.inc(int(n))

    def record_deadline_missed(self, n: int = 1) -> None:
        self._touch()
        self.deadline_missed_total.inc(int(n))

    def record_poison_isolated(self, n: int = 1) -> None:
        self._touch()
        self.poison_isolated_total.inc(int(n))

    def record_decode_round(self, emitted: int, drafted: int = 0,
                            accepted: int = 0) -> None:
        """One decode-phase lane-dispatch: `emitted` tokens committed
        (1 + accepted with speculation; always 1 without), plus the
        round's drafted/accepted counts when a draft was verified."""
        self._touch()
        self.decode_rounds_total.inc()
        self.decode_tokens_total.inc(int(emitted))
        if drafted > 0:
            self.spec_rounds_total.inc()
            self.spec_drafted_total.inc(int(drafted))
            self.spec_accepted_total.inc(int(accepted))

    def record_ship(self, direction: str, pages: int, nbytes: int,
                    seconds: float) -> None:
        """One KV page shipment through this pool: `direction` is
        "out" (a lane exported at prefill completion) or "in" (a lane
        admitted from shipped pages); `seconds` is the device-side
        gather/install cost, the wire hop belongs to the router."""
        self._touch()
        (self.ships_out_total if direction == "out"
         else self.ships_in_total).inc()
        self.pages_shipped_total.inc(int(pages))
        self.ship_bytes_total.inc(int(nbytes))
        self.ship_hist.observe(max(0.0, float(seconds)))

    def record_class(self, event: str, priority: str,
                     n: int = 1) -> None:
        """Per-priority-class resilience accounting (ISSUE-15): `event`
        is one of requests/rejected/shed/deadline_missed.  An unknown
        class is counted as interactive rather than raised — the typed
        validation already happened at admission; accounting must
        never fail a request."""
        key = (event, priority if priority in PRIORITY_CLASSES
               else PRIORITY_CLASSES[0])
        counter = self.class_counters.get(key)
        if counter is not None:
            counter.inc(int(n))

    def record_preemption(self, priority: str) -> None:
        """One lane preempted (its class is the victim's — the
        per-class row is how an operator verifies ladder level 3
        only ever preempts best_effort)."""
        self._touch()
        self.preemptions_total.inc()
        self.record_class("preempted", priority)

    def _tenant_counter(self, event: str, tenant: str) -> Counter:
        key = (event, tenant)
        c = self.tenant_counters.get(key)  # noqa: LCK101 — DCL fast path; creation is locked below
        if c is None:
            regs = None
            with self._lock:
                c = self.tenant_counters.get(key)
                if c is None:
                    c = Counter(f"serving_lm_tenant_{event}_total",
                                f"LM {event} by tenant")
                    regs = list(self._tenant_registrations)
                    self.tenant_counters[key] = c
            if regs is not None:
                # publish outside the lock: registry.register takes the
                # registry's own lock, and this cell is already visible
                for registry, labels in regs:
                    registry.register(c, tenant=tenant, **labels)
        return c

    def record_tenant(self, event: str, tenant: str, n: int = 1) -> None:
        """Per-tenant traffic-shaping accounting (ISSUE-16): `event` is
        one of requests/rejected/shed/deadline_missed/preempted/
        throttled, mirroring `record_class` so the fleet ledger can
        reconcile submitted == Σ tenants == Σ classes.  Cells
        materialize on first use (`serving_lm_tenant_{event}_total`,
        label ``tenant=``) and are published onto every registry this
        plane registered into — accounting must never fail a request,
        so like `record_class` this raises nothing on the record
        path."""
        self._tenant_counter(str(event), str(tenant)).inc(int(n))

    def set_tenant_burn(self, tenant: str, value: float) -> None:
        """Publish one tenant's SLO burn rate: the windowed fraction of
        its requests over its latency target, divided by its error
        budget — > 1.0 means the tenant is burning budget and is first
        in line when the brownout ladder picks victims (ISSUE-16)."""
        tenant = str(tenant)
        g = self.tenant_burn_gauges.get(tenant)  # noqa: LCK101 — DCL fast path; creation is locked below
        if g is None:
            regs = None
            with self._lock:
                g = self.tenant_burn_gauges.get(tenant)
                if g is None:
                    g = Gauge("serving_lm_tenant_slo_burn_rate",
                              "per-tenant SLO burn rate (>1 = burning "
                              "error budget)")
                    regs = list(self._tenant_registrations)
                    self.tenant_burn_gauges[tenant] = g
            if regs is not None:
                for registry, labels in regs:
                    registry.register(g, tenant=tenant, **labels)
        g.set(float(value))

    def record_swap(self, direction: str, pages: int,
                    nbytes: int) -> None:
        """One lane swapped 'out' to (or restored 'in' from) the host
        store — the preemption analog of `record_ship`."""
        self._touch()
        (self.swap_out_total if direction == "out"
         else self.swap_in_total).inc()
        self.swap_pages_total.inc(int(pages))
        self.swap_bytes_total.inc(int(nbytes))

    def record_swap_lost(self, kind: str) -> None:
        """A swapped lane's state was unusable at restore: `kind` is
        'evicted' (byte-cap LRU dropped it) or 'corrupt' (SHA-256 or
        frame check failed).  Either way the lane recomputes from its
        prompt — deterministic decode keeps the output byte-identical,
        so only this ledger ever sees the loss."""
        self._touch()
        (self.swap_corrupt_total if kind == "corrupt"
         else self.swap_evicted_total).inc()

    def record_hibernate(self, direction: str, pages: int, nbytes: int,
                         exact_nbytes: int) -> None:
        """One session hibernated 'out' to (or resumed 'in' from) the
        tiered state store.  `nbytes` is the at-rest frame size
        (quantized when the knob is on), `exact_nbytes` the same pages
        at their exact dtype — the pair is the compression ledger the
        hibernate bench row's <= 0.3x gate reads (ISSUE-19)."""
        self._touch()
        (self.hibernated_total if direction == "out"
         else self.resumed_total).inc()
        self.hibernate_pages_total.inc(int(pages))
        self.hibernate_bytes_total.inc(int(nbytes))
        self.hibernate_exact_bytes_total.inc(int(exact_nbytes))

    def record_hibernate_lost(self, kind: str) -> None:
        """A hibernated session's state was unusable at resume: `kind`
        is 'evicted' (fell off a byte-capped tier) or 'corrupt'
        (checksum/manifest/frame failure).  The session recomputes from
        its prompt — byte-identical output, ledger-only loss."""
        self._touch()
        (self.hibernate_corrupt_total if kind == "corrupt"
         else self.hibernate_evicted_total).inc()

    def record_brownout(self, level: int, transitions: int = 0) -> None:
        """Publish the current ladder level; `transitions` new level
        changes since the last call (counted, per the ISSUE-15
        every-transition-counted contract)."""
        self.brownout_level_gauge.set(int(level))
        if transitions:
            self.brownout_transitions_total.inc(int(transitions))

    def record_brownout_shed(self) -> None:
        self._touch()
        self.brownout_shed_total.inc()

    def record_first_token(self, seconds: float) -> None:
        """Time-to-first-token for one request: admission to the first
        committed token (the disagg bench's first-class column)."""
        self.ttft_hist.observe(max(0.0, float(seconds)))

    def record_session(self, hit: bool) -> None:
        """One session_id-carrying request; `hit` when this pool had
        already served the session (sticky affinity worked)."""
        self._touch()
        self.session_queries_total.inc()
        if hit:
            self.session_affinity_hits_total.inc()

    def record_prefix_query(self, tokens_saved: int) -> None:
        """One LM admission's radix-cache outcome: `tokens_saved` prompt
        tokens were served from cached pages (0 = miss)."""
        self._touch()
        self.prefix_queries_total.inc()
        if tokens_saved > 0:
            self.prefix_hits_total.inc()
            self.prefix_tokens_saved_total.inc(int(tokens_saved))

    def set_pages(self, in_use: int, free: int, total: int) -> None:
        self.pages_in_use_gauge.set(in_use)
        self.pages_free_gauge.set(free)
        self.pages_total_gauge.set(total)

    def set_breaker_state(self, state: str) -> None:
        with self._lock:
            if state == "open" and self._breaker_state != "open":
                self.breaker_opens_total.inc()
            self._breaker_state = str(state)
        self.breaker_state_gauge.set(_BREAKER_VALUES.get(str(state), 0))

    # ---- reading ----------------------------------------------------------

    @property
    def dispatches(self) -> int:
        return int(self.dispatches_total.value)

    @property
    def max_occupancy(self) -> int:
        """Largest real-row count observed in one dispatch."""
        with self._lock:
            return self._max_occupancy

    def snapshot(self) -> Dict:
        with self._lock:
            elapsed = (time.perf_counter() - self._started
                       if self._started is not None else 0.0)
            depth = self._queue_depth
            max_occ = self._max_occupancy
            breaker_state = self._breaker_state
            uptime = time.monotonic() - self._created
        dispatches = int(self.dispatches_total.value)
        requests = int(self.requests_total.value)
        rows = int(self.rows_total.value)
        padded = int(self.padded_rows_total.value)
        tokens = int(self.tokens_total.value)
        pq = int(self.prefix_queries_total.value)
        out = {
            "requests": requests,
            "dispatches": dispatches,
            "rows": rows,
            "queue_depth": depth,
            "rejected": int(self.rejected_total.value),
            "shed": int(self.shed_total.value),
            "deadline_missed": int(self.deadline_missed_total.value),
            "poison_isolated": int(self.poison_isolated_total.value),
            "breaker_state": breaker_state,
            "breaker_opens": int(self.breaker_opens_total.value),
            "latency": self.latency.summary(),
            # scrape-friendly timing (ISSUE-8 satellite): rates without
            # client-side clocks — uptime since construction plus the
            # monotonic clock this snapshot was cut at
            "uptime_s": round(uptime, 3),
            "snapshot_at": time.monotonic(),
        }
        qw = _ms(self.queue_wait_hist.summary())
        comp = _ms(self.compute_hist.summary())
        if qw["count"]:
            out["queue_wait"] = qw
        if comp["count"]:
            out["compute"] = comp
        dec_rounds = int(self.decode_rounds_total.value)
        if dec_rounds:
            out["decode_rounds"] = dec_rounds
            out["tokens_per_decode_round"] = round(
                int(self.decode_tokens_total.value) / dec_rounds, 3)
        drafted = int(self.spec_drafted_total.value)
        if drafted:
            out["spec_rounds"] = int(self.spec_rounds_total.value)
            out["spec_drafted"] = drafted
            out["spec_accepted"] = int(self.spec_accepted_total.value)
            out["spec_accept_rate"] = round(
                out["spec_accepted"] / drafted, 3)
        ttft = _ms(self.ttft_hist.summary())
        if ttft["count"]:
            out["ttft"] = ttft
        ships = (int(self.ships_out_total.value)
                 + int(self.ships_in_total.value))
        if ships:
            out["ship"] = {
                "out": int(self.ships_out_total.value),
                "in": int(self.ships_in_total.value),
                "pages_shipped": int(self.pages_shipped_total.value),
                "ship_bytes": int(self.ship_bytes_total.value),
                **{k: v for k, v in
                   _ms(self.ship_hist.summary()).items() if k != "count"}}
        sq = int(self.session_queries_total.value)
        if sq:
            out["session_queries"] = sq
            out["session_affinity_hits"] = int(
                self.session_affinity_hits_total.value)
        # overload-survival sections (ISSUE-15), present only once the
        # plane has actually fired so pre-existing snapshots are stable
        classes = {}
        for cls in PRIORITY_CLASSES:
            vals = {e: int(self.class_counters[(e, cls)].value)
                    for e in _CLASS_EVENTS}
            if any(vals.values()):
                classes[cls] = vals
        if classes:
            out["priority"] = classes
        # per-tenant ledger (ISSUE-16), same fire-once contract: the
        # section appears only once some tenant has recorded an event
        with self._lock:
            tenant_cells = dict(self.tenant_counters)
            burn_cells = dict(self.tenant_burn_gauges)
        tenants: Dict = {}
        for (event, tn), m in tenant_cells.items():
            v = int(m.value)
            if v:
                tenants.setdefault(tn, {})[event] = v
        for tn, g in burn_cells.items():
            if tn in tenants:
                tenants[tn]["burn_rate"] = round(float(g.value), 4)
        if tenants:
            out["tenants"] = tenants
        if int(self.preemptions_total.value):
            out["preemptions"] = int(self.preemptions_total.value)
        swaps = (int(self.swap_out_total.value)
                 + int(self.swap_in_total.value)
                 + int(self.swap_evicted_total.value)
                 + int(self.swap_corrupt_total.value))
        if swaps:
            out["swap"] = {
                "out": int(self.swap_out_total.value),
                "in": int(self.swap_in_total.value),
                "pages": int(self.swap_pages_total.value),
                "bytes": int(self.swap_bytes_total.value),
                "evicted": int(self.swap_evicted_total.value),
                "corrupt": int(self.swap_corrupt_total.value)}
        hib = (int(self.hibernated_total.value)
               + int(self.resumed_total.value)
               + int(self.hibernate_evicted_total.value)
               + int(self.hibernate_corrupt_total.value))
        if hib:
            at_rest = int(self.hibernate_bytes_total.value)
            exact = int(self.hibernate_exact_bytes_total.value)
            out["hibernate"] = {
                "out": int(self.hibernated_total.value),
                "in": int(self.resumed_total.value),
                "pages": int(self.hibernate_pages_total.value),
                "bytes": at_rest,
                "exact_bytes": exact,
                "bytes_ratio": (round(at_rest / exact, 4) if exact
                                else 1.0),
                "evicted": int(self.hibernate_evicted_total.value),
                "corrupt": int(self.hibernate_corrupt_total.value)}
        if (int(self.brownout_transitions_total.value)
                or int(self.brownout_level_gauge.value)):
            out["brownout"] = {
                "level": int(self.brownout_level_gauge.value),
                "transitions": int(
                    self.brownout_transitions_total.value),
                "shed": int(self.brownout_shed_total.value)}
        if pq:
            out["prefix_queries"] = pq
            out["prefix_hits"] = int(self.prefix_hits_total.value)
            out["prefix_tokens_saved"] = int(
                self.prefix_tokens_saved_total.value)
            out["prefix_hit_rate"] = round(out["prefix_hits"] / pq, 3)
        if int(self.pages_total_gauge.value):
            out["pages_in_use"] = int(self.pages_in_use_gauge.value)
            out["pages_free"] = int(self.pages_free_gauge.value)
            out["pages_total"] = int(self.pages_total_gauge.value)
        if dispatches:
            out["mean_batch_occupancy"] = round(rows / dispatches, 3)
            out["max_batch_occupancy"] = max_occ
            # fraction of dispatched device rows that were real examples
            out["pad_efficiency"] = round(rows / max(padded, 1), 3)
        if elapsed > 0:
            out["requests_per_sec"] = round(requests / elapsed, 1)
            if tokens:
                out["tokens_per_sec"] = round(tokens / elapsed, 1)
        if tokens:
            out["tokens"] = tokens
        return out
