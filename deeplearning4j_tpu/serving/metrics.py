"""Serving metrics: the numbers that tell you whether batching is working.

Per the serving cost model (docs/performance.md): throughput is bought
with batch occupancy (real rows per dispatch) and bounded compiles;
latency is spent in queue wait plus device compute.  `ServingMetrics`
tracks both sides — per-request latency percentiles via
`runtime.profiler.LatencyRecorder`, and per-dispatch occupancy / queue
depth / token counts — and snapshots them for `GET /serving/stats` and
the bench rows.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.runtime.profiler import LatencyRecorder


class ServingMetrics:
    """Thread-safe counters shared by the micro-batcher and LM server."""

    def __init__(self, latency_window: int = 4096):
        self._lock = threading.Lock()
        self.latency = LatencyRecorder(window=latency_window)
        self._dispatches = 0
        self._requests = 0
        self._rows = 0          # real examples dispatched
        self._padded_rows = 0   # bucket capacity dispatched (incl. padding)
        self._tokens = 0        # LM tokens emitted
        self._queue_depth = 0
        self._max_occupancy = 0
        self._started: Optional[float] = None
        # resilience counters (ISSUE-4): the admission/shedding ledger —
        # submitted == requests + rejected + shed + other-errors
        self._rejected = 0         # refused at admission (overload/breaker)
        self._shed = 0             # removed from a queue before dispatch
        self._deadline_missed = 0  # failed because the deadline passed
        self._poison_isolated = 0  # requests isolated as poison by bisection
        self._breaker_state = "closed"
        self._breaker_opens = 0
        # paged-KV / prefix-reuse ledger (ISSUE-7): every admitted LM
        # request is one prefix query; a hit means cached prompt pages
        # were reused and `tokens_saved` prefill steps were skipped
        self._prefix_queries = 0
        self._prefix_hits = 0
        self._prefix_tokens_saved = 0
        self._pages_in_use = 0     # gauge: KV pages currently refcounted
        self._pages_free = 0
        self._pages_total = 0      # 0 = not a paged pool

    # ---- recording --------------------------------------------------------

    def _touch(self) -> None:
        if self._started is None:
            self._started = time.perf_counter()

    def record_dispatch(self, n_real: int, n_padded: int,
                        queue_depth: Optional[int] = None) -> None:
        with self._lock:
            self._touch()
            self._dispatches += 1
            self._rows += int(n_real)
            self._padded_rows += int(n_padded)
            if queue_depth is not None:  # None = depth owned by the queue
                self._queue_depth = int(queue_depth)
            self._max_occupancy = max(self._max_occupancy, int(n_real))

    def record_request(self, latency_s: float) -> None:
        with self._lock:
            self._touch()
            self._requests += 1
        self.latency.record(latency_s)

    def record_tokens(self, n: int) -> None:
        with self._lock:
            self._touch()
            self._tokens += int(n)

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = int(depth)

    def record_rejected(self, n: int = 1) -> None:
        with self._lock:
            self._touch()
            self._rejected += int(n)

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self._touch()
            self._shed += int(n)

    def record_deadline_missed(self, n: int = 1) -> None:
        with self._lock:
            self._touch()
            self._deadline_missed += int(n)

    def record_poison_isolated(self, n: int = 1) -> None:
        with self._lock:
            self._touch()
            self._poison_isolated += int(n)

    def record_prefix_query(self, tokens_saved: int) -> None:
        """One LM admission's radix-cache outcome: `tokens_saved` prompt
        tokens were served from cached pages (0 = miss)."""
        with self._lock:
            self._touch()
            self._prefix_queries += 1
            if tokens_saved > 0:
                self._prefix_hits += 1
                self._prefix_tokens_saved += int(tokens_saved)

    def set_pages(self, in_use: int, free: int, total: int) -> None:
        with self._lock:
            self._pages_in_use = int(in_use)
            self._pages_free = int(free)
            self._pages_total = int(total)

    def set_breaker_state(self, state: str) -> None:
        with self._lock:
            if state == "open" and self._breaker_state != "open":
                self._breaker_opens += 1
            self._breaker_state = str(state)

    # ---- reading ----------------------------------------------------------

    @property
    def dispatches(self) -> int:
        with self._lock:
            return self._dispatches

    @property
    def max_occupancy(self) -> int:
        """Largest real-row count observed in one dispatch."""
        with self._lock:
            return self._max_occupancy

    def snapshot(self) -> Dict:
        with self._lock:
            elapsed = (time.perf_counter() - self._started
                       if self._started is not None else 0.0)
            dispatches, requests = self._dispatches, self._requests
            rows, padded = self._rows, self._padded_rows
            tokens, depth = self._tokens, self._queue_depth
            max_occ = self._max_occupancy
            rejected, shed = self._rejected, self._shed
            deadline_missed = self._deadline_missed
            poison = self._poison_isolated
            breaker_state = self._breaker_state
            breaker_opens = self._breaker_opens
            pq, ph = self._prefix_queries, self._prefix_hits
            psaved = self._prefix_tokens_saved
            pages = (self._pages_in_use, self._pages_free,
                     self._pages_total)
        out = {
            "requests": requests,
            "dispatches": dispatches,
            "rows": rows,
            "queue_depth": depth,
            "rejected": rejected,
            "shed": shed,
            "deadline_missed": deadline_missed,
            "poison_isolated": poison,
            "breaker_state": breaker_state,
            "breaker_opens": breaker_opens,
            "latency": self.latency.summary(),
        }
        if pq:
            out["prefix_queries"] = pq
            out["prefix_hits"] = ph
            out["prefix_tokens_saved"] = psaved
            out["prefix_hit_rate"] = round(ph / pq, 3)
        if pages[2]:
            out["pages_in_use"], out["pages_free"], out["pages_total"] = pages
        if dispatches:
            out["mean_batch_occupancy"] = round(rows / dispatches, 3)
            out["max_batch_occupancy"] = max_occ
            # fraction of dispatched device rows that were real examples
            out["pad_efficiency"] = round(rows / max(padded, 1), 3)
        if elapsed > 0:
            out["requests_per_sec"] = round(requests / elapsed, 1)
            if tokens:
                out["tokens_per_sec"] = round(tokens / elapsed, 1)
        if tokens:
            out["tokens"] = tokens
        return out
