"""Drafter plane for speculative multi-token decoding (ISSUE-13).

The LM pool's verify side (`parallel.generation.make_spec_step`) scores
a lane's drafted chunk in one wide dispatch and accepts/rolls back
IN-JIT; this module owns the other half — where the drafts come from.
A `Drafter` proposes up to `budget` continuation tokens per lane per
round from the lane's committed history (prompt + generated so far).
Draft QUALITY only moves throughput: the verify step's accept rule
guarantees greedy output is byte-identical to 1-token decode whatever
the drafter proposes, so a drafter can be wrong, cheap, and simple.

Two stdlib-cheap implementations:

- `NgramDrafter` — n-gram / prompt-lookup drafting: suffix-match the
  lane's recent tokens against its OWN earlier history and propose the
  continuation that followed the most recent prior occurrence.  Pure
  host Python, ZERO extra device programs — the free drafter, and
  strong on exactly the traffic continuous batching concentrates
  (shared system prompts, template continuations, greedy decode loops).
- `ModelDrafter` — a small zoo model (a tiny transformer config, or the
  target model itself for self-speculation tests) decoding greedily in
  its OWN dense slot cache via the existing `make_slot_step` program.
  Costs ~(catch_up + budget) 1-wide draft-model dispatches per round —
  worth it only when the draft model is much smaller than the target
  (docs/performance.md "The speculative decode cost model").

Threading: a drafter instance is owned by the LM pool's WORKER THREAD
(the single mutator, same contract as `serving/paged.py`); `propose`
is called from the worker's lock-free dispatch path only.
"""

from __future__ import annotations

from typing import List, Optional, Protocol, Sequence, runtime_checkable


@runtime_checkable
class Drafter(Protocol):
    """One round of proposals for the whole slot pool.

    `histories[i]` is lane i's committed tokens (prompt + generated), or
    None for lanes that must not be drafted for (inactive, sampling, or
    out of budget); `budgets[i]` caps lane i's proposal length.  Returns
    one proposal list per lane — possibly empty, never longer than the
    budget, and None-lanes always get [].
    """

    name: str

    def propose(self, histories: Sequence[Optional[Sequence[int]]],
                budgets: Sequence[int]) -> List[List[int]]:
        ...  # pragma: no cover — protocol signature only

    def reset(self) -> None:
        """Forget all lane state (the pool was rebuilt)."""
        ...  # pragma: no cover — protocol signature only

    def compiled_programs(self) -> int:
        """Device programs this drafter adds to the serving ladder."""
        ...  # pragma: no cover — protocol signature only


class NgramDrafter:
    """Prompt-lookup / n-gram drafting over each lane's own history.

    For the longest n in [min_ngram, max_ngram] whose history suffix
    re-occurs EARLIER in the history, propose the tokens that followed
    the most recent prior occurrence, up to the budget.  Degenerate
    inputs (empty history, history shorter than min_ngram, no prior
    occurrence, nothing after the occurrence) propose zero tokens —
    the lane falls back to plain 1-token decode for that round.
    """

    name = "ngram"

    def __init__(self, max_ngram: int = 4, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, got "
                             f"{min_ngram}..{max_ngram}")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def _propose_one(self, hist: Sequence[int], budget: int) -> List[int]:
        h = list(hist)
        n_hist = len(h)
        for n in range(min(self.max_ngram, n_hist - 1),
                       self.min_ngram - 1, -1):
            suffix = h[n_hist - n:]
            # most recent PRIOR occurrence whose continuation fills the
            # budget; an occurrence too close to the end only yields a
            # truncated continuation (for a periodic tail — greedy
            # decode loops, templated text — the nearest match is
            # always the overlapping one), so keep scanning and fall
            # back to the longest continuation seen
            best: List[int] = []
            for i in range(n_hist - n - 1, -1, -1):
                if h[i] == suffix[0] and h[i:i + n] == suffix:
                    cont = h[i + n:i + n + budget]
                    if len(cont) == budget:
                        return cont
                    if len(cont) > len(best):
                        best = cont
            if best:
                return best
        return []

    def propose(self, histories, budgets) -> List[List[int]]:
        out: List[List[int]] = []
        for hist, budget in zip(histories, budgets):
            if hist is None or budget < 1:
                out.append([])
            else:
                out.append(self._propose_one(hist, int(budget)))
        return out

    def reset(self) -> None:
        pass                        # stateless — history rides each call

    def compiled_programs(self) -> int:
        return 0


class ModelDrafter:
    """Small-model drafting: a draft LM greedily rolls out `budget`
    tokens per lane in its OWN dense slot cache (one
    `make_slot_step` program, 1-wide dispatches).

    Lane state self-heals from the histories handed to `propose`: each
    call rewinds a lane to the longest common prefix of what was fed
    and the new committed history (rejected drafts and freed/reused
    slots fall out naturally — the dense cache's position mask hides
    everything past `pos`, so rewinding is a host-side counter move),
    teacher-forces the missing suffix, then rolls out proposals.  Lanes
    mid-teacher-forcing idle by RE-FEEDING their last token at its own
    position — k/v at a position are a pure function of (token,
    position, earlier history), so the re-write is byte-idempotent.
    """

    name = "model"

    def __init__(self, cfg, params, slots: int, target_vocab: int = 0,
                 target_max_len: int = 0):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if target_vocab and cfg.vocab_size < target_vocab:
            raise ValueError(
                f"draft model vocab ({cfg.vocab_size}) smaller than the "
                f"target's ({target_vocab}): drafts could never cover "
                f"the target's tokens")
        if target_max_len and cfg.max_len < target_max_len:
            raise ValueError(
                f"draft model max_len ({cfg.max_len}) smaller than the "
                f"target's ({target_max_len}): a lane's history would "
                f"outgrow the draft cache mid-request")
        self.cfg = cfg
        self.params = params
        self.n_slots = int(slots)
        self._step = None
        self._cache = None          # (k, v) donated device buffers
        self._fed: List[List[int]] = [[] for _ in range(self.n_slots)]

    # ---- device plumbing --------------------------------------------------

    def _ensure_started(self) -> None:
        if self._step is not None:
            return
        from deeplearning4j_tpu.parallel.generation import (
            init_slot_cache,
            make_slot_step,
        )

        self._step = make_slot_step(self.cfg)
        cache = init_slot_cache(self.cfg, self.n_slots)
        self._cache = (cache["k"], cache["v"])

    def warmup(self) -> None:
        """Compile the draft-model program before traffic (the LM
        pool's `warmup()` calls this so the zero-compile-after-warmup
        contract covers the drafter too)."""
        import numpy as np

        self._ensure_started()
        zi = np.zeros((self.n_slots,), np.int32)
        self._dispatch(zi, zi)
        self.reset()                # the warm write clobbered pos 0

    def _dispatch(self, tokens, pos):
        """One 1-wide draft-model step; returns [B] greedy next tokens.
        Sampling inputs are all-zero: temperature 0 = argmax rows."""
        import numpy as np

        from deeplearning4j_tpu.obs.compilewatch import compile_scope

        zi = np.zeros((self.n_slots,), np.int32)
        zf = np.zeros((self.n_slots,), np.float32)
        with compile_scope("lm:draft"):
            nxt, k, v = self._step(self.params, *self._cache, pos, tokens,
                                   zf, zi, zi)
        self._cache = (k, v)
        return np.asarray(nxt)

    # ---- drafting ---------------------------------------------------------

    def propose(self, histories, budgets) -> List[List[int]]:
        import numpy as np

        if len(histories) != self.n_slots:
            raise ValueError(f"expected {self.n_slots} lane histories, "
                             f"got {len(histories)}")
        budgets = [int(b) for b in budgets]
        if not any(b > 0 and h is not None
                   for h, b in zip(histories, budgets)):
            return [[] for _ in histories]
        self._ensure_started()
        pending: List[List[int]] = []
        for i, hist in enumerate(histories):
            if hist is None:
                pending.append([])
                continue
            h = [int(t) for t in hist]
            cp = 0
            fed = self._fed[i]
            for a, b in zip(fed, h):
                if a != b:
                    break
                cp += 1
            self._fed[i] = fed[:cp]        # rewind = pointer move
            pending.append(h[cp:])
        # a history the draft cache cannot hold (custom construction
        # bypassing the factory's max_len validation) must not scatter
        # at clamped positions and silently corrupt the cache: the lane
        # simply sits this round out (no proposal is always safe)
        for i in range(self.n_slots):
            if (histories[i] is not None
                    and len(self._fed[i]) + len(pending[i])
                    > self.cfg.max_len):
                pending[i] = []
                budgets[i] = 0
        if not any(b > 0 and h is not None
                   for h, b in zip(histories, budgets)):
            return [[] for _ in histories]
        # teacher-force the missing suffixes in lockstep; at least one
        # round always runs so every drafted lane's last committed
        # token has been (re-)fed and its next-token prediction is live
        rounds = max(1, max(len(p) for p in pending))
        pred = None
        for _ in range(rounds):
            tokens = np.zeros((self.n_slots,), np.int32)
            pos = np.zeros((self.n_slots,), np.int32)
            for i in range(self.n_slots):
                if pending[i]:
                    tokens[i] = pending[i].pop(0)
                    pos[i] = len(self._fed[i])
                    self._fed[i].append(int(tokens[i]))
                elif self._fed[i]:             # idle: byte-idempotent re-feed
                    tokens[i] = self._fed[i][-1]
                    pos[i] = len(self._fed[i]) - 1
            pred = self._dispatch(tokens, pos)
        # greedy rollout: feed each round's prediction back in
        out: List[List[int]] = [[] for _ in range(self.n_slots)]
        k_max = max(budgets)
        for t in range(k_max):
            for i in range(self.n_slots):
                if (histories[i] is not None and self._fed[i]
                        and t < budgets[i]):
                    out[i].append(int(pred[i]))
            if t + 1 >= k_max:
                break
            tokens = np.zeros((self.n_slots,), np.int32)
            pos = np.zeros((self.n_slots,), np.int32)
            for i in range(self.n_slots):
                if (histories[i] is not None and self._fed[i]
                        and t + 1 < budgets[i]
                        and len(self._fed[i]) < self.cfg.max_len):
                    tokens[i] = pred[i]
                    pos[i] = len(self._fed[i])
                    self._fed[i].append(int(pred[i]))
                elif self._fed[i]:
                    tokens[i] = self._fed[i][-1]
                    pos[i] = len(self._fed[i]) - 1
            pred = self._dispatch(tokens, pos)
        return [p[:b] for p, b in zip(out, budgets)]

    def reset(self) -> None:
        self._fed = [[] for _ in range(self.n_slots)]

    def compiled_programs(self) -> int:
        return 1


def make_drafter(mode: str, cfg, params, slots: int,
                 draft_model=None) -> Optional[Drafter]:
    """The LM pool's drafter factory: `mode` in {"off", "ngram",
    "model"}.  For "model", `draft_model` is an optional (cfg, params)
    pair — default is SELF-speculation against the target's own
    weights (100% greedy accept; useful for parity tests and wiring
    validation, not a throughput win — see docs/performance.md)."""
    if mode == "off":
        return None
    if mode == "ngram":
        return NgramDrafter()
    if mode == "model":
        d_cfg, d_params = (draft_model if draft_model is not None
                           else (cfg, params))
        return ModelDrafter(d_cfg, d_params, slots,
                            target_vocab=cfg.vocab_size,
                            target_max_len=cfg.max_len)
    raise ValueError(
        f"speculate must be 'off', 'ngram' or 'model', got {mode!r}")


__all__ = ["Drafter", "ModelDrafter", "NgramDrafter", "make_drafter"]
