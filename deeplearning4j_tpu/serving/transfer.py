"""KV page shipping: the disaggregated-serving wire plane (ISSUE-14).

Disaggregated prefill/decode serving splits one request across two
worker processes: a PREFILL worker chews the prompt chunk-by-chunk
(compute-bound, bursty) and a DECODE worker runs the token loop
(latency-bound, steady).  The state that has to cross the wire between
them is the lane's finished KV pages — the same gather/re-split
redistribution discipline the elastic checkpoint plane proved for
optimizer state (`parallel/partition.py`, arXiv 2112.01075), applied
live between serving processes at page granularity.

This module owns the WIRE FORMAT only; it is deliberately import-light
(numpy + stdlib, no jax) so both HTTP fronts can parse and verify a
shipment without touching a device:

- `PageExport` — everything a decode worker needs to continue a lane
  exactly where the prefill worker left it: the request contract
  (prompt/max_new/temperature/seed), the committed tokens so far (the
  prefill worker samples the FIRST token — the last prompt token's
  logits produce it, so shipping without it would redo a dispatch), the
  next cache position, and the page stacks `[L, n_pages, ps, H, K]` for
  k and v.
- `serialize_export` / `deserialize_export` — one binary frame: magic,
  length-prefixed JSON header, raw page payload.  The header carries
  the SHA-256 of the payload (checked like checkpoint shards) plus the
  `model_signature` of the exporting pool, so a flipped byte on the
  wire or a mismatched deployment becomes a typed `PageShipError` the
  router answers by RECOMPUTING locally — never silent garbage KV.
- `check_compatible` — the import gate: layer/head/dtype/page-size
  geometry must match bit-for-bit or the pages mean nothing to the
  importing pool.

Sharing is sound for the same reason the radix cache is: KV at
position t is a deterministic function of tokens[0..t] and the
weights, so an installed page holds byte-identical k/v to what the
decode worker would have computed itself — shipped-lane output is
byte-identical to a locally-prefilled lane, greedy or seeded sampling.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import struct
from typing import Dict, List, Optional

import numpy as np

# frame magic + format version: bump WIRE_VERSION on any header/payload
# layout change so a mixed-version fleet fails typed, not misparsed
MAGIC = b"DL4JKVS\x01"
WIRE_VERSION = 1

# header fields every frame must carry (missing = typed, not KeyError)
_REQUIRED = ("version", "prompt", "max_new", "temperature", "seed",
             "committed", "pos", "page_size", "n_pages", "dtype",
             "shape", "sha256", "model")


class PageShipError(RuntimeError):
    """A KV page shipment could not be accepted: truncated/misframed
    bytes, a failed SHA-256 integrity check, or geometry incompatible
    with the importing pool.  The failure ladder is RECOMPUTE, never
    trust: the router falls back to a local prefill on the decode
    worker (docs/robustness.md "Disaggregated serving")."""


def model_signature(cfg, page_size: int) -> Dict:
    """The geometry a shipped page stack is only meaningful under.
    `max_len`/`vocab_size` ride along for request re-validation on the
    importing side; the KV-shape fields are the hard compatibility
    gate."""
    return {"n_layers": int(cfg.n_layers), "n_heads": int(cfg.n_heads),
            "head_dim": int(cfg.head_dim), "dtype": str(cfg.dtype),
            "max_len": int(cfg.max_len),
            "vocab_size": int(cfg.vocab_size),
            "page_size": int(page_size)}


@dataclasses.dataclass
class PageExport:
    """One lane's shippable state at prefill completion."""

    prompt: List[int]
    max_new: int
    temperature: float
    seed: int
    committed: List[int]        # tokens generated so far (>= 1)
    pos: int                    # next cache position (== len(prompt))
    page_size: int
    pages_k: np.ndarray         # [L, n_pages, ps, H, K]
    pages_v: np.ndarray
    model: Dict                 # model_signature of the exporting pool
    session_id: Optional[str] = None
    # admission class (ISSUE-15): rides the frame so a shipped or
    # swapped lane keeps its priority on the pool it lands in; absent
    # in pre-ISSUE-15 frames -> interactive (the historical behavior)
    priority: str = "interactive"
    # billing identity (ISSUE-16): same ride-along contract — a
    # shipped or swapped lane stays charged to its tenant on the pool
    # it lands in; absent in older frames -> the default tenant
    tenant: str = "default"

    @property
    def n_pages(self) -> int:
        return int(self.pages_k.shape[1])

    def nbytes(self) -> int:
        return int(self.pages_k.nbytes + self.pages_v.nbytes)


def serialize_export(ex: PageExport) -> bytes:
    """PageExport -> one wire frame: MAGIC + u32 header length + JSON
    header + raw page payload (k then v, C-order).  The header's sha256
    covers the payload bytes exactly as framed."""
    pk = np.ascontiguousarray(ex.pages_k)
    pv = np.ascontiguousarray(ex.pages_v)
    if pk.shape != pv.shape:
        raise ValueError(f"pages_k {pk.shape} != pages_v {pv.shape}")
    payload = pk.tobytes() + pv.tobytes()
    header = {
        "version": WIRE_VERSION,
        "prompt": [int(t) for t in ex.prompt],
        "max_new": int(ex.max_new),
        "temperature": float(ex.temperature),
        "seed": int(ex.seed),
        "committed": [int(t) for t in ex.committed],
        "pos": int(ex.pos),
        "page_size": int(ex.page_size),
        "n_pages": int(pk.shape[1]),
        "dtype": str(pk.dtype),
        "shape": list(pk.shape),
        "sha256": hashlib.sha256(payload).hexdigest(),
        "model": dict(ex.model),
    }
    if ex.session_id is not None:
        header["session_id"] = str(ex.session_id)
    if ex.priority != "interactive":
        header["priority"] = str(ex.priority)
    if ex.tenant != "default":
        header["tenant"] = str(ex.tenant)
    hj = json.dumps(header).encode()
    return MAGIC + struct.pack(">I", len(hj)) + hj + payload


def deserialize_export(data: bytes) -> PageExport:
    """One wire frame -> PageExport, integrity-verified.  EVERY malformed
    input — wrong magic, truncated header or payload, non-JSON header,
    missing fields, shape/byte-count mismatch, failed SHA-256 — raises
    `PageShipError` naming what broke, so the import path has exactly
    one failure type to map to its recompute ladder."""
    pre = len(MAGIC) + 4
    if len(data) < pre or data[:len(MAGIC)] != MAGIC:
        raise PageShipError(
            f"not a KV page shipment: bad magic/short frame "
            f"({len(data)} bytes)")
    (hlen,) = struct.unpack(">I", data[len(MAGIC):pre])
    if len(data) < pre + hlen:
        raise PageShipError(
            f"truncated shipment header ({len(data)} bytes, header "
            f"needs {pre + hlen})")
    try:
        header = json.loads(data[pre:pre + hlen])
    except ValueError as e:
        raise PageShipError(f"shipment header is not JSON: {e}") from e
    missing = [k for k in _REQUIRED if k not in header]
    if missing:
        raise PageShipError(f"shipment header missing {missing}")
    if int(header["version"]) != WIRE_VERSION:
        raise PageShipError(
            f"shipment wire version {header['version']} != "
            f"{WIRE_VERSION}")
    payload = data[pre + hlen:]
    digest = hashlib.sha256(payload).hexdigest()
    if digest != header["sha256"]:
        raise PageShipError(
            f"shipment integrity check failed: sha256 {digest[:12]}… != "
            f"header {str(header['sha256'])[:12]}…")
    shape = tuple(int(d) for d in header["shape"])
    try:
        dt = np.dtype(header["dtype"])
    except TypeError as e:
        raise PageShipError(
            f"shipment dtype {header['dtype']!r} unknown") from e
    want = 2 * int(np.prod(shape)) * dt.itemsize
    if len(payload) != want:
        raise PageShipError(
            f"shipment payload {len(payload)} bytes != {want} for "
            f"2 x {shape} {dt}")
    half = want // 2
    pk = np.frombuffer(payload[:half], dt).reshape(shape)
    pv = np.frombuffer(payload[half:], dt).reshape(shape)
    return PageExport(
        prompt=[int(t) for t in header["prompt"]],
        max_new=int(header["max_new"]),
        temperature=float(header["temperature"]),
        seed=int(header["seed"]),
        committed=[int(t) for t in header["committed"]],
        pos=int(header["pos"]),
        page_size=int(header["page_size"]),
        pages_k=pk, pages_v=pv, model=dict(header["model"]),
        session_id=header.get("session_id"),
        priority=str(header.get("priority", "interactive")),
        tenant=str(header.get("tenant", "default")))


def check_compatible(ex: PageExport, cfg, page_size: int,
                     mid_decode: bool = False) -> None:
    """The import gate: shipped geometry must equal the importing
    pool's, field for field — a page stack cut for different
    layers/heads/dtype/page-size would install as silent garbage.
    Raises `PageShipError` naming every mismatched field.

    ``mid_decode`` relaxes the prefill-boundary invariant for the
    overload-survival plane (ISSUE-15): a PREEMPTED lane swaps out
    mid-decode, so its ``pos`` sits anywhere past the prompt — but the
    page-count and committed-token invariants still hold exactly."""
    local = model_signature(cfg, page_size)
    bad = [f"{k}: shipped {ex.model.get(k)!r} != local {v!r}"
           for k, v in local.items() if ex.model.get(k) != v]
    if bad:
        raise PageShipError(
            "shipment incompatible with this pool — " + "; ".join(bad))
    want = (local["n_layers"], ex.n_pages, local["page_size"],
            local["n_heads"], local["head_dim"])
    if tuple(ex.pages_k.shape) != want:
        raise PageShipError(
            f"shipment page stack {tuple(ex.pages_k.shape)} != "
            f"{want} for this pool's geometry")
    if mid_decode:
        if ex.pos < len(ex.prompt):
            raise PageShipError(
                f"swapped lane pos {ex.pos} < prompt length "
                f"{len(ex.prompt)}: only post-prefill lanes swap")
    elif ex.pos != len(ex.prompt):
        raise PageShipError(
            f"shipment pos {ex.pos} != prompt length "
            f"{len(ex.prompt)}: only prefill-complete lanes ship")
    if not ex.committed:
        raise PageShipError(
            "shipment carries no committed token: prefill completion "
            "always samples the first one")
    if ex.n_pages != -(-ex.pos // local["page_size"]):
        raise PageShipError(
            f"shipment has {ex.n_pages} pages for pos {ex.pos} at "
            f"page_size {local['page_size']}")


__all__ = [
    "MAGIC",
    "PageExport",
    "PageShipError",
    "WIRE_VERSION",
    "check_compatible",
    "deserialize_export",
    "model_signature",
    "serialize_export",
]
