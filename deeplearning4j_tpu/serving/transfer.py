"""KV page shipping: the disaggregated-serving wire plane (ISSUE-14).

Disaggregated prefill/decode serving splits one request across two
worker processes: a PREFILL worker chews the prompt chunk-by-chunk
(compute-bound, bursty) and a DECODE worker runs the token loop
(latency-bound, steady).  The state that has to cross the wire between
them is the lane's finished KV pages — the same gather/re-split
redistribution discipline the elastic checkpoint plane proved for
optimizer state (`parallel/partition.py`, arXiv 2112.01075), applied
live between serving processes at page granularity.

This module owns the WIRE FORMAT only; it is deliberately import-light
(numpy + stdlib, no jax) so both HTTP fronts can parse and verify a
shipment without touching a device:

- `PageExport` — everything a decode worker needs to continue a lane
  exactly where the prefill worker left it: the request contract
  (prompt/max_new/temperature/seed), the committed tokens so far (the
  prefill worker samples the FIRST token — the last prompt token's
  logits produce it, so shipping without it would redo a dispatch), the
  next cache position, and the page stacks `[L, n_pages, ps, H, K]` for
  k and v.
- `serialize_export` / `deserialize_export` — one binary frame: magic,
  length-prefixed JSON header, raw page payload.  The header carries
  the SHA-256 of the payload (checked like checkpoint shards) plus the
  `model_signature` of the exporting pool, so a flipped byte on the
  wire or a mismatched deployment becomes a typed `PageShipError` the
  router answers by RECOMPUTING locally — never silent garbage KV.
- `check_compatible` — the import gate: layer/head/dtype/page-size
  geometry must match bit-for-bit or the pages mean nothing to the
  importing pool.

Sharing is sound for the same reason the radix cache is: KV at
position t is a deterministic function of tokens[0..t] and the
weights, so an installed page holds byte-identical k/v to what the
decode worker would have computed itself — shipped-lane output is
byte-identical to a locally-prefilled lane, greedy or seeded sampling.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import struct
from typing import Dict, List, Optional

import numpy as np

# frame magic + format version: bump WIRE_VERSION on any header/payload
# layout change so a mixed-version fleet fails typed, not misparsed.
# v1: raw k‖v page payload.  v2 (ISSUE-19): adds an optional "quant"
# header section — payload is int8 k‖v followed by the float32
# per-(layer, page, head) scale stacks.  Exact-mode frames still
# serialize as v1 byte-for-byte, so a pre-ISSUE-19 reader keeps working
# until it meets a quantized frame, which it rejects TYPED by version.
MAGIC = b"DL4JKVS\x01"
WIRE_VERSION = 2
_KNOWN_VERSIONS = (1, 2)

# header fields every frame must carry (missing = typed, not KeyError)
_REQUIRED = ("version", "prompt", "max_new", "temperature", "seed",
             "committed", "pos", "page_size", "n_pages", "dtype",
             "shape", "sha256", "model")


class PageShipError(RuntimeError):
    """A KV page shipment could not be accepted: truncated/misframed
    bytes, a failed SHA-256 integrity check, or geometry incompatible
    with the importing pool.  The failure ladder is RECOMPUTE, never
    trust: the router falls back to a local prefill on the decode
    worker (docs/robustness.md "Disaggregated serving")."""


def model_signature(cfg, page_size: int) -> Dict:
    """The geometry a shipped page stack is only meaningful under.
    `max_len`/`vocab_size` ride along for request re-validation on the
    importing side; the KV-shape fields are the hard compatibility
    gate."""
    return {"n_layers": int(cfg.n_layers), "n_heads": int(cfg.n_heads),
            "head_dim": int(cfg.head_dim), "dtype": str(cfg.dtype),
            "max_len": int(cfg.max_len),
            "vocab_size": int(cfg.vocab_size),
            "page_size": int(page_size)}


@dataclasses.dataclass
class PageExport:
    """One lane's shippable state at prefill completion."""

    prompt: List[int]
    max_new: int
    temperature: float
    seed: int
    committed: List[int]        # tokens generated so far (>= 1)
    pos: int                    # next cache position (== len(prompt))
    page_size: int
    pages_k: np.ndarray         # [L, n_pages, ps, H, K]
    pages_v: np.ndarray
    model: Dict                 # model_signature of the exporting pool
    session_id: Optional[str] = None
    # admission class (ISSUE-15): rides the frame so a shipped or
    # swapped lane keeps its priority on the pool it lands in; absent
    # in pre-ISSUE-15 frames -> interactive (the historical behavior)
    priority: str = "interactive"
    # billing identity (ISSUE-16): same ride-along contract — a
    # shipped or swapped lane stays charged to its tenant on the pool
    # it lands in; absent in older frames -> the default tenant
    tenant: str = "default"
    # compression (ISSUE-19): when `quant` is set, pages_k/pages_v are
    # int8 and scales_k/scales_v carry the per-(layer, page, head)
    # float32 scales; `quant["exact_dtype"]` remembers what the pages
    # dequantize back to.  None = exact-bytes frame (v1 layout).
    quant: Optional[Dict] = None
    scales_k: Optional[np.ndarray] = None
    scales_v: Optional[np.ndarray] = None

    @property
    def n_pages(self) -> int:
        return int(self.pages_k.shape[1])

    @property
    def quantized(self) -> bool:
        return self.quant is not None

    def nbytes(self) -> int:
        """Bytes this export actually carries (the at-rest/wire size):
        int8 pages + scales when quantized, raw pages when exact."""
        n = int(self.pages_k.nbytes + self.pages_v.nbytes)
        if self.scales_k is not None:
            n += int(self.scales_k.nbytes + self.scales_v.nbytes)
        return n

    def exact_nbytes(self) -> int:
        """Bytes the same pages occupy un-quantized (the 4x-denominator
        the compression ledger reports against)."""
        if self.quant is None:
            return int(self.pages_k.nbytes + self.pages_v.nbytes)
        itemsize = np.dtype(self.quant["exact_dtype"]).itemsize
        return int(2 * self.pages_k.size * itemsize)

    def dequantized(self) -> "PageExport":
        """A new exact PageExport with pages restored to
        `quant["exact_dtype"]` (identity when already exact).  Install
        paths call this ONCE at the host boundary so the device install
        program is the same one exact shipments use."""
        if self.quant is None:
            return self
        from deeplearning4j_tpu.precision.quantize import (
            dequantize_kv_pages,
        )

        dt = np.dtype(self.quant["exact_dtype"])
        return dataclasses.replace(
            self,
            pages_k=dequantize_kv_pages(self.pages_k, self.scales_k, dt),
            pages_v=dequantize_kv_pages(self.pages_v, self.scales_v, dt),
            quant=None, scales_k=None, scales_v=None)


def quantize_export(ex: PageExport) -> PageExport:
    """Exact PageExport -> per-page int8 quantized PageExport (identity
    when already quantized).  Positions at/past `ex.pos` are zeroed
    before the scales are computed (stale tail-page garbage must not
    crush the live rows' precision — `quantize_kv_pages`)."""
    if ex.quant is not None:
        return ex
    from deeplearning4j_tpu.precision.quantize import quantize_kv_pages

    qk, sk = quantize_kv_pages(ex.pages_k, valid=ex.pos)
    qv, sv = quantize_kv_pages(ex.pages_v, valid=ex.pos)
    return dataclasses.replace(
        ex, pages_k=qk, pages_v=qv, scales_k=sk, scales_v=sv,
        quant={"mode": "int8", "exact_dtype": str(ex.pages_k.dtype)})


def serialize_export(ex: PageExport) -> bytes:
    """PageExport -> one wire frame: MAGIC + u32 header length + JSON
    header + raw page payload (k then v, C-order; a quantized export
    appends its float32 scale stacks after the int8 pages).  The
    header's sha256 covers the payload bytes exactly as framed.  Exact
    exports frame as v1 — byte-identical to the pre-ISSUE-19 format —
    so quantize-off pools interoperate with old readers unchanged."""
    pk = np.ascontiguousarray(ex.pages_k)
    pv = np.ascontiguousarray(ex.pages_v)
    if pk.shape != pv.shape:
        raise ValueError(f"pages_k {pk.shape} != pages_v {pv.shape}")
    payload = pk.tobytes() + pv.tobytes()
    if ex.quant is not None:
        sk = np.ascontiguousarray(ex.scales_k, np.float32)
        sv = np.ascontiguousarray(ex.scales_v, np.float32)
        payload += sk.tobytes() + sv.tobytes()
    header = {
        "version": WIRE_VERSION if ex.quant is not None else 1,
        "prompt": [int(t) for t in ex.prompt],
        "max_new": int(ex.max_new),
        "temperature": float(ex.temperature),
        "seed": int(ex.seed),
        "committed": [int(t) for t in ex.committed],
        "pos": int(ex.pos),
        "page_size": int(ex.page_size),
        "n_pages": int(pk.shape[1]),
        "dtype": str(pk.dtype),
        "shape": list(pk.shape),
        "sha256": hashlib.sha256(payload).hexdigest(),
        "model": dict(ex.model),
    }
    if ex.quant is not None:
        header["quant"] = {"mode": str(ex.quant["mode"]),
                           "exact_dtype": str(ex.quant["exact_dtype"]),
                           "scale_shape": list(ex.scales_k.shape)}
    if ex.session_id is not None:
        header["session_id"] = str(ex.session_id)
    if ex.priority != "interactive":
        header["priority"] = str(ex.priority)
    if ex.tenant != "default":
        header["tenant"] = str(ex.tenant)
    hj = json.dumps(header).encode()
    return MAGIC + struct.pack(">I", len(hj)) + hj + payload


def deserialize_export(data: bytes) -> PageExport:
    """One wire frame -> PageExport, integrity-verified.  EVERY malformed
    input — wrong magic, truncated header or payload, non-JSON header,
    missing fields, shape/byte-count mismatch, failed SHA-256 — raises
    `PageShipError` naming what broke, so the import path has exactly
    one failure type to map to its recompute ladder."""
    pre = len(MAGIC) + 4
    if len(data) < pre or data[:len(MAGIC)] != MAGIC:
        raise PageShipError(
            f"not a KV page shipment: bad magic/short frame "
            f"({len(data)} bytes)")
    (hlen,) = struct.unpack(">I", data[len(MAGIC):pre])
    if len(data) < pre + hlen:
        raise PageShipError(
            f"truncated shipment header ({len(data)} bytes, header "
            f"needs {pre + hlen})")
    try:
        header = json.loads(data[pre:pre + hlen])
    except ValueError as e:
        raise PageShipError(f"shipment header is not JSON: {e}") from e
    missing = [k for k in _REQUIRED if k not in header]
    if missing:
        raise PageShipError(f"shipment header missing {missing}")
    if int(header["version"]) not in _KNOWN_VERSIONS:
        raise PageShipError(
            f"shipment wire version {header['version']} not in "
            f"{_KNOWN_VERSIONS}")
    payload = data[pre + hlen:]
    digest = hashlib.sha256(payload).hexdigest()
    if digest != header["sha256"]:
        raise PageShipError(
            f"shipment integrity check failed: sha256 {digest[:12]}… != "
            f"header {str(header['sha256'])[:12]}…")
    shape = tuple(int(d) for d in header["shape"])
    try:
        dt = np.dtype(header["dtype"])
    except TypeError as e:
        raise PageShipError(
            f"shipment dtype {header['dtype']!r} unknown") from e
    quant = header.get("quant")
    sk = sv = None
    if quant is not None:
        if quant.get("mode") != "int8":
            raise PageShipError(
                f"shipment quantization mode {quant.get('mode')!r} "
                f"unknown (this reader speaks int8 only)")
        if dt != np.dtype(np.int8):
            raise PageShipError(
                f"quantized shipment payload dtype {dt} != int8")
        try:
            np.dtype(quant.get("exact_dtype"))
        except TypeError as e:
            raise PageShipError(
                f"shipment exact_dtype {quant.get('exact_dtype')!r} "
                f"unknown") from e
        sshape = tuple(int(d) for d in quant.get("scale_shape", ()))
        if len(sshape) != 3 or sshape[:2] != (shape[0], shape[1]) or \
                sshape[2] != shape[3]:
            raise PageShipError(
                f"shipment scale stack {sshape} != per-(layer, page, "
                f"head) for pages {shape}")
        sbytes = int(np.prod(sshape)) * 4
    else:
        sbytes = 0
    half = int(np.prod(shape)) * dt.itemsize
    want = 2 * half + 2 * sbytes
    if len(payload) != want:
        raise PageShipError(
            f"shipment payload {len(payload)} bytes != {want} for "
            f"2 x {shape} {dt}"
            + (f" + 2 x {sshape} float32 scales" if quant else ""))
    pk = np.frombuffer(payload[:half], dt).reshape(shape)
    pv = np.frombuffer(payload[half:2 * half], dt).reshape(shape)
    if quant is not None:
        sk = np.frombuffer(
            payload[2 * half:2 * half + sbytes], np.float32
        ).reshape(sshape)
        sv = np.frombuffer(payload[2 * half + sbytes:], np.float32
                           ).reshape(sshape)
        quant = {"mode": "int8",
                 "exact_dtype": str(quant["exact_dtype"])}
    return PageExport(
        prompt=[int(t) for t in header["prompt"]],
        max_new=int(header["max_new"]),
        temperature=float(header["temperature"]),
        seed=int(header["seed"]),
        committed=[int(t) for t in header["committed"]],
        pos=int(header["pos"]),
        page_size=int(header["page_size"]),
        pages_k=pk, pages_v=pv, model=dict(header["model"]),
        session_id=header.get("session_id"),
        priority=str(header.get("priority", "interactive")),
        tenant=str(header.get("tenant", "default")),
        quant=quant, scales_k=sk, scales_v=sv)


def check_compatible(ex: PageExport, cfg, page_size: int,
                     mid_decode: bool = False,
                     prefix: bool = False) -> None:
    """The import gate: shipped geometry must equal the importing
    pool's, field for field — a page stack cut for different
    layers/heads/dtype/page-size would install as silent garbage.
    Raises `PageShipError` naming every mismatched field.

    ``mid_decode`` relaxes the prefill-boundary invariant for the
    overload-survival plane (ISSUE-15): a PREEMPTED lane swaps out
    mid-decode, so its ``pos`` sits anywhere past the prompt — but the
    page-count and committed-token invariants still hold exactly.

    ``prefix`` gates HIBERNATION frames (ISSUE-19): not a live lane but
    a whole-page prompt prefix — ``prompt`` is exactly the covered
    tokens, ``pos`` sits on a page boundary, and ``committed`` is empty
    (nothing was mid-flight; the resuming lane re-runs its own tail)."""
    local = model_signature(cfg, page_size)
    bad = [f"{k}: shipped {ex.model.get(k)!r} != local {v!r}"
           for k, v in local.items() if ex.model.get(k) != v]
    if bad:
        raise PageShipError(
            "shipment incompatible with this pool — " + "; ".join(bad))
    want = (local["n_layers"], ex.n_pages, local["page_size"],
            local["n_heads"], local["head_dim"])
    if tuple(ex.pages_k.shape) != want:
        raise PageShipError(
            f"shipment page stack {tuple(ex.pages_k.shape)} != "
            f"{want} for this pool's geometry")
    if prefix:
        if ex.pos != len(ex.prompt):
            raise PageShipError(
                f"hibernated prefix pos {ex.pos} != covered tokens "
                f"{len(ex.prompt)}: a prefix frame stores exactly what "
                f"its pages hold")
        if ex.pos % local["page_size"] != 0:
            raise PageShipError(
                f"hibernated prefix pos {ex.pos} is not a multiple of "
                f"page_size {local['page_size']}: only FULL pages rest")
        if ex.committed:
            raise PageShipError(
                f"hibernated prefix carries {len(ex.committed)} "
                f"committed tokens: prefix frames hold pages, not lanes")
    elif mid_decode:
        if ex.pos < len(ex.prompt):
            raise PageShipError(
                f"swapped lane pos {ex.pos} < prompt length "
                f"{len(ex.prompt)}: only post-prefill lanes swap")
    elif ex.pos != len(ex.prompt):
        raise PageShipError(
            f"shipment pos {ex.pos} != prompt length "
            f"{len(ex.prompt)}: only prefill-complete lanes ship")
    if not prefix and not ex.committed:
        raise PageShipError(
            "shipment carries no committed token: prefill completion "
            "always samples the first one")
    if ex.n_pages != -(-ex.pos // local["page_size"]):
        raise PageShipError(
            f"shipment has {ex.n_pages} pages for pos {ex.pos} at "
            f"page_size {local['page_size']}")


__all__ = [
    "MAGIC",
    "PageExport",
    "PageShipError",
    "WIRE_VERSION",
    "check_compatible",
    "deserialize_export",
    "model_signature",
    "quantize_export",
    "serialize_export",
]
