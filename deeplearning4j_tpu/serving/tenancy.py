"""Multi-tenant traffic shaping & SLO policy for the serving planes.

PR 15's overload-survival plane (`serving/pressure.py`) made the pool
degrade gracefully, but every knob is GLOBAL: one flooding client and a
latency-sensitive one share the same queue bound, the same brownout
rungs, the same preemption ordering.  ROADMAP item 3's missing policy
layer is WHO: per-tenant fairness, quotas, and SLO-aware victim
selection, so an adversarial tenant's 5x-quota flood cannot move a
compliant tenant's p99.  This module owns the four policy pieces; like
`pressure.py` it is plain host Python (stdlib-only — the HTTP fronts
import the tenant vocabulary without touching numpy/jax):

- **`TenantSpec` / `TenantRegistry`** — the per-tenant policy record
  (WFQ weight, token-rate quota + burst, SLO latency target) and the
  open registry of them.  Unlike priority classes the vocabulary is
  OPEN (operators mint tenants via ``serve -tenants``), but validation
  is just as hard: `TenantRegistry.normalize` is THE gate — None means
  the client sent nothing and maps to the built-in ``default`` tenant
  (unmetered, weight 1, no SLO — every pre-tenancy client keeps its
  exact behavior); an unknown tenant is the client's 400, never a
  silent default.

- **`TokenBucketMeter`** — per-tenant token buckets with tokens-in /
  tokens-out ledgers.  Admission charges the request's token cost
  (prompt + decode budget for the LM pool, rows for the classifier);
  an empty bucket is a typed quota refusal whose ``retry_after_s`` is
  DERIVED FROM THE BUCKET'S OWN REFILL (deficit / rate) — never a
  constant, so a client backing off exactly as told will find tokens
  waiting.  The meter also remembers recent refusals per tenant: the
  ``over_quota`` signal the brownout ladder's victim selection reads.

- **`FairQueueClock`** — weighted-fair queuing as virtual finish
  times.  `stamp(tenant, cost)` assigns
  ``vft = max(v_now, tenant_last_finish) + cost / weight``; the pool's
  queue sorts by ``(priority rank, vft, enqueued)`` so priority always
  dominates (PR 15's contract) and WFQ only interleaves WITHIN a
  class.  With one tenant the vft is strictly increasing in stamp
  order, so the composed key degenerates to the historic
  (rank, enqueued) FIFO — pinned by test.

- **`SLOTracker`** — per-tenant latency windows against the spec's
  SLO target, reduced to a BURN RATE: the fraction of recent requests
  over target divided by the error budget (burn 1.0 = spending budget
  exactly as fast as allowed; > 1 = burning).  Victim selection
  (`TenantRegistry.badness`) orders preemption/shed candidates by
  (over-quota, burn rate) so the ladder's L3/L4 rungs take from the
  worst offender first and never touch a compliant tenant while an
  offender has lanes to give.

docs/robustness.md "Tenancy & SLOs" has the WFQ ordering contract, the
quota/429 semantics, and the burn-rate -> victim-selection table.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import threading
import time
from typing import Dict, Iterable, Optional, Tuple

# the typed 429 lives in the resilience taxonomy (one
# respond_typed_failure mapping serves both HTTP fronts); re-exported
# here so tenancy callers import one module
from deeplearning4j_tpu.serving.resilience import TenantQuotaError

# The built-in tenant every request without a tenant label belongs to.
# Unmetered, weight 1.0, no SLO target: pre-tenancy clients keep their
# exact admission behavior (no quota 429s, FIFO within their class).
DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's policy record.

    ``weight`` is the WFQ share within a priority class (2.0 drains
    twice as fast as 1.0 when both are backlogged).  ``rate`` is the
    token-rate quota in tokens/second (0 = unmetered); ``burst`` is
    the bucket capacity (default: 4 seconds of rate, so short spikes
    ride through while sustained floods meter down to ``rate``).
    ``slo_ms`` is the per-request latency target (0 = no SLO) and
    ``slo_budget`` the tolerated fraction of requests over target —
    the denominator of the burn rate."""

    name: str
    weight: float = 1.0
    rate: float = 0.0
    burst: float = 0.0
    slo_ms: float = 0.0
    slo_budget: float = 0.05

    def __post_init__(self):
        if not self.name or not str(self.name).strip():
            raise ValueError("tenant name must be non-empty")
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, got "
                f"{self.weight}")
        if self.rate < 0:
            raise ValueError(
                f"tenant {self.name!r}: rate must be >= 0 tokens/s, "
                f"got {self.rate}")
        if self.burst < 0:
            raise ValueError(
                f"tenant {self.name!r}: burst must be >= 0 tokens, "
                f"got {self.burst}")
        if self.slo_ms < 0:
            raise ValueError(
                f"tenant {self.name!r}: slo_ms must be >= 0, got "
                f"{self.slo_ms}")
        if not 0 < self.slo_budget <= 1:
            raise ValueError(
                f"tenant {self.name!r}: slo_budget must be in (0, 1], "
                f"got {self.slo_budget}")

    @property
    def capacity(self) -> float:
        """Bucket capacity in tokens: explicit burst, else 4s of rate."""
        if self.rate <= 0:
            return 0.0
        return self.burst if self.burst > 0 else 4.0 * self.rate

    @property
    def metered(self) -> bool:
        return self.rate > 0


class TenantRegistry:
    """The open tenant vocabulary plus its runtime policy state.

    Construction takes specs (or plain dicts); the built-in ``default``
    tenant is always present so a registry-less deployment and an
    empty ``-tenants {}`` behave identically.  The registry composes
    the three runtime pieces — meter, WFQ clock, SLO tracker — so the
    pool wires ONE object through admission, victim selection, and
    stats.  Mutation discipline matches the pool: admission-path calls
    run under the server's condition lock; the meter carries its own
    small lock because the MicroBatcher front shares instances with
    client threads."""

    def __init__(self, specs: Optional[Iterable] = None):
        self._specs: Dict[str, TenantSpec] = {}
        self.add(TenantSpec(DEFAULT_TENANT))
        for spec in specs or ():
            self.add(spec if isinstance(spec, TenantSpec)
                     else TenantSpec(**dict(spec)))
        self.meter = TokenBucketMeter(self)
        self.wfq = FairQueueClock(self)
        self.slo = SLOTracker(self)

    @classmethod
    def from_json(cls, text: str) -> "TenantRegistry":
        """Parse the ``serve -tenants`` JSON knob:
        ``{"name": {"weight": 4, "rate": 200, "slo_ms": 250}, ...}``.
        Field validation is `TenantSpec`'s; a non-object payload or
        non-object entry is a ValueError (the CLI's SystemExit)."""
        try:
            payload = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"tenants JSON does not parse: {e}") from e
        if not isinstance(payload, dict):
            raise ValueError(
                f"tenants JSON must be an object mapping tenant name "
                f"-> spec fields, got {type(payload).__name__}")
        specs = []
        for name, fields in payload.items():
            if not isinstance(fields, dict):
                raise ValueError(
                    f"tenant {name!r}: spec must be an object, got "
                    f"{type(fields).__name__}")
            specs.append(TenantSpec(name=str(name), **fields))
        return cls(specs)

    @classmethod
    def coerce(cls, tenants) -> Optional["TenantRegistry"]:
        """The ONE constructor-argument contract every plane shares:
        None stays None (tenancy off — zero overhead), a registry
        passes through, a dict of specs or a JSON string builds one."""
        if tenants is None or isinstance(tenants, TenantRegistry):
            return tenants
        if isinstance(tenants, str):
            return cls.from_json(tenants)
        if isinstance(tenants, dict):
            return cls(TenantSpec(name=str(n), **dict(f))
                       for n, f in tenants.items())
        return cls(tenants)

    def add(self, spec: TenantSpec) -> None:
        self._specs[spec.name] = spec

    def names(self) -> Tuple[str, ...]:
        return tuple(self._specs)

    def spec(self, tenant: str) -> TenantSpec:
        return self._specs[tenant]

    def __contains__(self, tenant: str) -> bool:
        return tenant in self._specs

    def normalize(self, tenant: Optional[str]) -> str:
        """THE tenant-validation gate, shared by the HTTP fronts (as
        400s) and the pools (as ValueErrors).  None means the client
        sent nothing: the built-in default tenant — a pre-tenancy
        caller must keep its exact behavior, not silently inherit
        someone's quota."""
        if tenant is None:
            return DEFAULT_TENANT
        t = str(tenant)
        if t not in self._specs:
            raise ValueError(
                f"unknown tenant {t!r} (registered: "
                f"{sorted(self._specs)})")
        return t

    # ---- victim selection (brownout L3/L4 integration) --------------------

    def badness(self, tenant: str,
                now: Optional[float] = None) -> Tuple[int, float]:
        """Sort key for preemption/shed victim ordering: larger =
        worse = taken from first.  (over_quota, burn_rate) — a tenant
        currently hitting its quota outranks any burn rate, matching
        the docs table.  `now` is injectable for tests."""
        t = tenant if tenant in self._specs else DEFAULT_TENANT
        return (1 if self.meter.over_quota(t, now=now) else 0,
                self.slo.burn_rate(t))

    def compliant(self, tenant: str,
                  now: Optional[float] = None) -> bool:
        """A tenant inside its quota and not burning SLO budget.  The
        ladder's rungs must never take from a compliant tenant while a
        non-compliant one has lanes/admissions to give."""
        over, burn = self.badness(tenant, now=now)
        return not over and burn <= 1.0

    def any_offender(self, now: Optional[float] = None) -> bool:
        """True when some tenant is currently non-compliant — the
        predicate that switches the L3/L4 rungs from PR 15's global
        behavior to offender-first selection."""
        return any(not self.compliant(t, now=now) for t in self._specs)

    def stats(self) -> Dict:
        """Per-tenant policy + runtime numbers for /serving/stats and
        the fleet aggregation (plain ints/floats, JSON-clean)."""
        out: Dict = {}
        for name, spec in self._specs.items():
            entry: Dict = {"weight": spec.weight}
            if spec.metered:
                entry.update({"rate": spec.rate,
                              "burst": spec.capacity})
            if spec.slo_ms > 0:
                entry.update({"slo_ms": spec.slo_ms,
                              "slo_budget": spec.slo_budget,
                              "burn_rate": round(
                                  self.slo.burn_rate(name), 3)})
            entry.update(self.meter.ledger(name))
            out[name] = entry
        return out


class TokenBucketMeter:
    """Per-tenant token buckets + tokens-in/out ledgers.

    One bucket per metered tenant: capacity = the spec's burst,
    refill = ``rate`` tokens/second, charged at admission with the
    request's token cost.  `charge` raises `TenantQuotaError` with a
    retry derived from the bucket's own refill — the seconds until the
    deficit refills at ``rate``, so the 429's Retry-After is honest by
    construction.  Thread-safe under its own lock (the MicroBatcher
    front charges from client threads; the LM pool charges under the
    server lock)."""

    def __init__(self, registry: TenantRegistry):
        self._registry = registry
        self._lock = threading.Lock()
        self._tokens: Dict[str, float] = {}
        self._stamp: Dict[str, float] = {}
        self._throttled_at: Dict[str, float] = {}
        # ledgers: admitted token cost in, generated/served tokens out,
        # admissions and quota refusals — per tenant
        self.tokens_in: Dict[str, int] = collections.defaultdict(int)
        self.tokens_out: Dict[str, int] = collections.defaultdict(int)
        self.admitted: Dict[str, int] = collections.defaultdict(int)
        self.throttled: Dict[str, int] = collections.defaultdict(int)

    def _refill_locked(self, tenant: str, spec: TenantSpec,
                       now: float) -> float:
        cap = spec.capacity
        tokens = self._tokens.get(tenant, cap)
        last = self._stamp.get(tenant, now)
        tokens = min(cap, tokens + (now - last) * spec.rate)
        self._tokens[tenant] = tokens
        self._stamp[tenant] = now
        return tokens

    def charge(self, tenant: str, cost: int,
               now: Optional[float] = None) -> None:
        """Admit `cost` tokens for `tenant` or raise `TenantQuotaError`
        whose retry_after_s is the bucket's own refill time for the
        deficit.  Unmetered tenants always pass (ledgers still count)."""
        now = time.monotonic() if now is None else now
        cost = max(1, int(cost))
        spec = self._registry.spec(
            tenant if tenant in self._registry else DEFAULT_TENANT)
        with self._lock:
            if not spec.metered:
                self.tokens_in[tenant] += cost
                self.admitted[tenant] += 1
                return
            tokens = self._refill_locked(tenant, spec, now)
            if tokens >= cost:
                self._tokens[tenant] = tokens - cost
                self.tokens_in[tenant] += cost
                self.admitted[tenant] += 1
                return
            self.throttled[tenant] += 1
            self._throttled_at[tenant] = now
            deficit = cost - tokens
            retry = deficit / spec.rate
        raise TenantQuotaError(
            f"tenant {tenant!r} over token-rate quota: {cost} tokens "
            f"requested, {tokens:.0f} in the bucket (rate "
            f"{spec.rate:g}/s); retry in {retry:.2f}s",
            retry_after_s=retry)

    def record_out(self, tenant: str, n: int) -> None:
        with self._lock:
            self.tokens_out[tenant] += int(n)

    def over_quota(self, tenant: str,
                   window_s: float = 5.0,
                   now: Optional[float] = None) -> bool:
        """True when `tenant` was refused for quota within `window_s`
        (or its bucket is currently empty) — the offender signal the
        ladder's victim selection reads.  Unmetered tenants are never
        over quota."""
        spec = self._registry.spec(
            tenant if tenant in self._registry else DEFAULT_TENANT)
        if not spec.metered:
            return False
        now = time.monotonic() if now is None else now
        with self._lock:
            at = self._throttled_at.get(tenant)
            if at is not None and now - at <= window_s:
                return True
            return self._refill_locked(tenant, spec, now) < 1.0

    def ledger(self, tenant: str) -> Dict:
        with self._lock:
            return {"tokens_in": self.tokens_in.get(tenant, 0),
                    "tokens_out": self.tokens_out.get(tenant, 0),
                    "admitted": self.admitted.get(tenant, 0),
                    "throttled": self.throttled.get(tenant, 0)}


class FairQueueClock:
    """Weighted-fair queuing as virtual finish times.

    `stamp(tenant, cost)` returns the request's vft; the queue sorts
    by (priority rank, vft, enqueued).  `advance(vft)` moves the
    virtual clock when the pool services a request, so a tenant idle
    for a while re-enters at v_now instead of with banked credit.
    Single-mutator: the pool calls both under its condition lock, the
    MicroBatcher never stamps (its queue is not WFQ-ordered — the
    classifier's quota gate is the only tenancy there)."""

    def __init__(self, registry: TenantRegistry):
        self._registry = registry
        self.vclock = 0.0
        self._last_finish: Dict[str, float] = {}
        self.stamps = 0

    def stamp(self, tenant: str, cost: int) -> float:
        spec = self._registry.spec(
            tenant if tenant in self._registry else DEFAULT_TENANT)
        start = max(self.vclock, self._last_finish.get(tenant, 0.0))
        vft = start + max(1, int(cost)) / spec.weight
        self._last_finish[tenant] = vft
        self.stamps += 1
        return vft

    def advance(self, vft: float) -> None:
        if vft > self.vclock:
            self.vclock = vft


class SLOTracker:
    """Per-tenant latency windows -> SLO burn rate.

    `record(tenant, latency_s)` appends to a bounded window;
    `burn_rate(tenant)` is the window's over-target fraction divided
    by the spec's error budget.  0.0 for tenants without an SLO (they
    cannot be selected as burn-rate victims — only quota makes them
    offenders).  Single-mutator like the clock (pool lock)."""

    def __init__(self, registry: TenantRegistry, window: int = 256):
        self._registry = registry
        self._window = int(window)
        self._lat: Dict[str, collections.deque] = {}

    def record(self, tenant: str, latency_s: float) -> None:
        dq = self._lat.get(tenant)
        if dq is None:
            dq = self._lat[tenant] = collections.deque(
                maxlen=self._window)
        dq.append(float(latency_s))

    def burn_rate(self, tenant: str) -> float:
        spec = self._registry.spec(
            tenant if tenant in self._registry else DEFAULT_TENANT)
        if spec.slo_ms <= 0:
            return 0.0
        dq = self._lat.get(tenant)
        if not dq:
            return 0.0
        target = spec.slo_ms / 1e3
        over = sum(1 for v in dq if v > target)
        return (over / len(dq)) / spec.slo_budget


__all__ = [
    "DEFAULT_TENANT",
    "FairQueueClock",
    "SLOTracker",
    "TenantQuotaError",
    "TenantRegistry",
    "TenantSpec",
    "TokenBucketMeter",
]
