"""Serving-plane resilience: typed failures and the circuit breaker.

The training plane got its fault-tolerance layer in the resilience
subsystem (supervisor, retry, chaos — docs/robustness.md); this module
is the serving-side counterpart.  It owns the *taxonomy* — every way a
request can fail for a reason that is not the client's payload gets a
typed exception the HTTP layer can map to the right status code — and
the circuit breaker that turns "the device is failing every dispatch"
into fast 503s instead of a queue full of doomed work.

The enforcement sites live where the queues live (`batcher.py`,
`lm.py`): bounded admission, deadline shedding before dispatch, and
poison-request bisection.  This module stays import-light (stdlib only)
so the exception types are usable from the HTTP layer without pulling
in numpy/jax.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class ServingError(RuntimeError):
    """Base class for serving-plane failures that are the *server's*
    condition, not the request payload (those stay ValueError -> 400)."""


class ServingOverloadError(ServingError):
    """Admission refused: the queue is at `max_queue_depth`.  Maps to
    HTTP 503 with a `Retry-After` hint — the client should back off,
    not the server buffer unboundedly."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class CircuitOpenError(ServingOverloadError):
    """Admission refused because the circuit breaker is open: recent
    dispatches failed wholesale, so queueing more work only builds a
    backlog of doomed requests.  503 + Retry-After(remaining cooldown)."""


class ServingUnavailableError(ServingError):
    """The serving worker is stopped or draining — the request was (or
    would be) abandoned without dispatch.  503: a load balancer should
    route elsewhere; this replaces the untyped ``RuntimeError("batcher
    stopped")`` that used to surface as a 500."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceededError(ServingError, TimeoutError):
    """The request's deadline passed before (or while) it could be
    served; expired work is shed *before* dispatch so timed-out clients
    stop costing device time.  Subclasses TimeoutError so existing
    ``except TimeoutError`` clients keep working; HTTP maps it to 504."""


# Breaker states (the closed vocabulary /serving/stats and tests use):
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure circuit breaker for the dispatch path.

    - CLOSED: dispatches flow; `failure_threshold` *consecutive*
      whole-dispatch failures trip it OPEN.
    - OPEN: admission fast-fails (`CircuitOpenError`) and `/readyz`
      reports not-ready; after `cooldown_s` the next dispatch attempt
      is admitted as the half-open probe.
    - HALF_OPEN: exactly one probe dispatch is in flight; its success
      closes the breaker, its failure re-opens it (fresh cooldown).

    Thread-safe; `clock` is injectable so tests drive the cooldown
    without wall-clock sleeps.  `on_transition(state)` fires on every
    state change (the metrics hook).
    """

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable[[str], None]] = None):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1, got "
                             f"{failure_threshold}")
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._listeners = [] if on_transition is None else [on_transition]
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._opens = 0

    # ---- internal ---------------------------------------------------------

    def add_listener(self, fn: Callable[[str], None]) -> None:
        """Subscribe to state transitions (idempotent per callable) —
        how the serving metrics mirror `breaker_state` without claiming
        exclusive ownership of a caller-supplied breaker."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def _set_state_locked(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        if state == BREAKER_OPEN:
            self._opens += 1
        for fn in self._listeners:
            fn(state)

    # ---- reading ----------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            # the cooldown elapsing IS the open -> half-open transition;
            # commit it here (firing on_transition) so readiness and the
            # stats ledger agree without waiting for the next dispatch
            if (self._state == BREAKER_OPEN
                    and self._clock() - self._opened_at >= self.cooldown_s):
                self._set_state_locked(BREAKER_HALF_OPEN)
            return self._state

    @property
    def opens(self) -> int:
        """How many times the breaker has tripped open (monotonic)."""
        with self._lock:
            return self._opens

    def retry_after_s(self) -> float:
        """Remaining cooldown (>= a small floor) — the Retry-After hint."""
        with self._lock:
            if self._state != BREAKER_OPEN:
                return 0.05
            remaining = self.cooldown_s - (self._clock() - self._opened_at)
            return max(0.05, remaining)

    # ---- admission / dispatch gates ---------------------------------------

    def rejecting(self) -> bool:
        """True while admission should fast-fail: OPEN inside the
        cooldown window.  After the cooldown, admission resumes so a
        queued request can become the half-open probe."""
        with self._lock:
            return (self._state == BREAKER_OPEN
                    and self._clock() - self._opened_at < self.cooldown_s)

    def allow_dispatch(self) -> bool:
        """Gate one dispatch attempt.  CLOSED: always.  OPEN: only once
        the cooldown elapsed, transitioning to HALF_OPEN and claiming
        the probe.  HALF_OPEN: only if no probe is already in flight."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN:
                if self._clock() - self._opened_at < self.cooldown_s:
                    return False
                self._set_state_locked(BREAKER_HALF_OPEN)
                self._probe_in_flight = True
                return True
            # HALF_OPEN
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    # ---- outcome recording ------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probe_in_flight = False
            self._set_state_locked(BREAKER_CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            probe_failed = self._state == BREAKER_HALF_OPEN
            if probe_failed or self._consecutive >= self.failure_threshold:
                self._probe_in_flight = False
                self._opened_at = self._clock()
                # re-opening from HALF_OPEN must count as a fresh open
                if self._state == BREAKER_HALF_OPEN:
                    self._state = BREAKER_CLOSED  # force the transition
                self._set_state_locked(BREAKER_OPEN)


def check_admission(*, accepting: bool, breaker: Optional[CircuitBreaker],
                    queue_depth: int, max_queue_depth: Optional[int],
                    metrics, retry_after_s: Callable[[], float],
                    what: str = "serving") -> None:
    """THE admission gate, shared by `MicroBatcher.submit` and
    `ContinuousLMServer.generate` (call with the owner's lock held).
    Checks in blast-radius order — draining, breaker, queue bound —
    raising the matching typed error and counting the rejection.
    `retry_after_s` is a thunk so the backlog estimate is only computed
    when a rejection actually happens."""
    if not accepting:
        metrics.record_rejected()
        raise ServingUnavailableError(
            f"{what} is draining: admission stopped")
    if breaker is not None and breaker.rejecting():
        metrics.record_rejected()
        raise CircuitOpenError(
            f"circuit breaker open: recent {what} dispatches failed "
            f"wholesale; backing off",
            retry_after_s=breaker.retry_after_s())
    if max_queue_depth is not None and queue_depth >= max_queue_depth:
        metrics.record_rejected()
        raise ServingOverloadError(
            f"{what} queue full ({queue_depth} >= max_queue_depth "
            f"{max_queue_depth})",
            retry_after_s=retry_after_s())


__all__ = [
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadlineExceededError",
    "ServingError",
    "ServingOverloadError",
    "ServingUnavailableError",
    "check_admission",
]
