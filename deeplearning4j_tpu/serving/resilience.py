"""Serving-plane resilience: typed failures and the circuit breaker.

The training plane got its fault-tolerance layer in the resilience
subsystem (supervisor, retry, chaos — docs/robustness.md); this module
is the serving-side counterpart.  It owns the *taxonomy* — every way a
request can fail for a reason that is not the client's payload gets a
typed exception the HTTP layer can map to the right status code — and
the circuit breaker that turns "the device is failing every dispatch"
into fast 503s instead of a queue full of doomed work.

The enforcement sites live where the queues live (`batcher.py`,
`lm.py`): bounded admission, deadline shedding before dispatch, and
poison-request bisection.  This module stays import-light (stdlib only)
so the exception types are usable from the HTTP layer without pulling
in numpy/jax.
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Callable, Optional


class ServingError(RuntimeError):
    """Base class for serving-plane failures that are the *server's*
    condition, not the request payload (those stay ValueError -> 400)."""


class ServingOverloadError(ServingError):
    """Admission refused: the queue is at `max_queue_depth`.  Maps to
    HTTP 503 with a `Retry-After` hint — the client should back off,
    not the server buffer unboundedly."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class CircuitOpenError(ServingOverloadError):
    """Admission refused because the circuit breaker is open: recent
    dispatches failed wholesale, so queueing more work only builds a
    backlog of doomed requests.  503 + Retry-After(remaining cooldown)."""


class ServingUnavailableError(ServingError):
    """The serving worker is stopped or draining — the request was (or
    would be) abandoned without dispatch.  503: a load balancer should
    route elsewhere; this replaces the untyped ``RuntimeError("batcher
    stopped")`` that used to surface as a 500."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class TenantQuotaError(ServingError):
    """Admission refused because the request's TENANT is over its
    token-rate quota (ISSUE-16) — raised by the tenancy meter BEFORE
    the shared admission gate, so one flooding tenant's refusals never
    consume the queue bound every tenant shares.  Maps to HTTP 429 +
    Retry-After; ``retry_after_s`` is derived from the tenant's own
    token-bucket refill (deficit / rate), never a constant — a client
    backing off exactly as told finds tokens waiting.  Distinct from
    `ServingOverloadError` (503) on purpose: 503 means the SERVER is
    out of capacity (retry elsewhere), 429 means THIS CLIENT is out of
    budget (slow down — a failover retry would be refused identically
    on every replica sharing the registry)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceededError(ServingError, TimeoutError):
    """The request's deadline passed before (or while) it could be
    served; expired work is shed *before* dispatch so timed-out clients
    stop costing device time.  Subclasses TimeoutError so existing
    ``except TimeoutError`` clients keep working; HTTP maps it to 504."""


class UnservableShapeError(ServingError, ValueError):
    """The request's dispatch shape falls outside the warmed bucket
    ladder (the compile-count guard refused to mint program #N+1).  This
    is the *client's* payload shape, not a server fault, so it also
    subclasses ValueError and the HTTP layer maps it to 400 — never a
    500.  Replaces the untyped ``RuntimeError`` the guard used to raise
    (``ServingError`` keeps it a RuntimeError subclass for
    backward-compatible ``except`` clauses)."""


# Breaker states (the closed vocabulary /serving/stats and tests use):
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure circuit breaker for the dispatch path.

    - CLOSED: dispatches flow; `failure_threshold` *consecutive*
      whole-dispatch failures trip it OPEN.
    - OPEN: admission fast-fails (`CircuitOpenError`) and `/readyz`
      reports not-ready; after `cooldown_s` the next dispatch attempt
      is admitted as the half-open probe.
    - HALF_OPEN: exactly one probe dispatch is in flight; its success
      closes the breaker, its failure re-opens it (fresh cooldown).

    Thread-safe; `clock` is injectable so tests drive the cooldown
    without wall-clock sleeps.  `on_transition(state)` fires on every
    state change (the metrics hook).
    """

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable[[str], None]] = None):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1, got "
                             f"{failure_threshold}")
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._listeners = [] if on_transition is None else [on_transition]
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._opens = 0

    # ---- internal ---------------------------------------------------------

    def add_listener(self, fn: Callable[[str], None]) -> None:
        """Subscribe to state transitions (idempotent per callable) —
        how the serving metrics mirror `breaker_state` without claiming
        exclusive ownership of a caller-supplied breaker."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def _set_state_locked(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        if state == BREAKER_OPEN:
            self._opens += 1
        for fn in self._listeners:
            fn(state)

    # ---- reading ----------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            # the cooldown elapsing IS the open -> half-open transition;
            # commit it here (firing on_transition) so readiness and the
            # stats ledger agree without waiting for the next dispatch
            if (self._state == BREAKER_OPEN
                    and self._clock() - self._opened_at >= self.cooldown_s):
                self._set_state_locked(BREAKER_HALF_OPEN)
            return self._state

    @property
    def opens(self) -> int:
        """How many times the breaker has tripped open (monotonic)."""
        with self._lock:
            return self._opens

    def retry_after_s(self) -> float:
        """Remaining cooldown (>= a small floor) — the Retry-After hint."""
        with self._lock:
            if self._state != BREAKER_OPEN:
                return 0.05
            remaining = self.cooldown_s - (self._clock() - self._opened_at)
            return max(0.05, remaining)

    # ---- admission / dispatch gates ---------------------------------------

    def rejecting(self) -> bool:
        """True while admission should fast-fail: OPEN inside the
        cooldown window.  After the cooldown, admission resumes so a
        queued request can become the half-open probe."""
        with self._lock:
            return (self._state == BREAKER_OPEN
                    and self._clock() - self._opened_at < self.cooldown_s)

    def allow_dispatch(self) -> bool:
        """Gate one dispatch attempt.  CLOSED: always.  OPEN: only once
        the cooldown elapsed, transitioning to HALF_OPEN and claiming
        the probe.  HALF_OPEN: only if no probe is already in flight."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN:
                if self._clock() - self._opened_at < self.cooldown_s:
                    return False
                self._set_state_locked(BREAKER_HALF_OPEN)
                self._probe_in_flight = True
                return True
            # HALF_OPEN
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    def abandon_probe(self) -> None:
        """Release a probe claim WITHOUT a verdict: the probed target
        answered alive-but-unavailable (503 draining/overload, 504
        deadline) — neither re-admission evidence nor a fault.  Keeps
        the half-open window open for the next probe instead of wedging
        it shut behind an in-flight claim that will never resolve."""
        with self._lock:
            self._probe_in_flight = False

    # ---- outcome recording ------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probe_in_flight = False
            self._set_state_locked(BREAKER_CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            probe_failed = self._state == BREAKER_HALF_OPEN
            if probe_failed or self._consecutive >= self.failure_threshold:
                self._probe_in_flight = False
                self._opened_at = self._clock()
                # re-opening from HALF_OPEN must count as a fresh open
                if self._state == BREAKER_HALF_OPEN:
                    self._state = BREAKER_CLOSED  # force the transition
                self._set_state_locked(BREAKER_OPEN)


def check_admission(*, accepting: bool, breaker: Optional[CircuitBreaker],
                    queue_depth: int, max_queue_depth: Optional[int],
                    metrics, retry_after_s: Callable[[], float],
                    what: str = "serving") -> None:
    """THE admission gate, shared by `MicroBatcher.submit` and
    `ContinuousLMServer.generate` (call with the owner's lock held).
    Checks in blast-radius order — draining, breaker, queue bound —
    raising the matching typed error and counting the rejection.
    `retry_after_s` is a thunk so the backlog estimate is only computed
    when a rejection actually happens."""
    if not accepting:
        metrics.record_rejected()
        raise ServingUnavailableError(
            f"{what} is draining: admission stopped")
    if breaker is not None and breaker.rejecting():
        metrics.record_rejected()
        raise CircuitOpenError(
            f"circuit breaker open: recent {what} dispatches failed "
            f"wholesale; backing off",
            retry_after_s=breaker.retry_after_s())
    if max_queue_depth is not None and queue_depth >= max_queue_depth:
        metrics.record_rejected()
        raise ServingOverloadError(
            f"{what} queue full ({queue_depth} >= max_queue_depth "
            f"{max_queue_depth})",
            retry_after_s=retry_after_s())


class ServingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with restart-after-drain semantics made
    explicit, shared by both serving fronts (`ui/server.py`'s
    `_UiHTTPServer` and `serving/fleet.py`'s `_FleetHTTPServer`):
    SO_REUSEADDR so a drained-and-stopped server's port can be re-bound
    by its replacement immediately (the rolling-swap / restart path must
    not wait out TIME_WAIT), and daemon handler threads so a wedged
    client connection cannot hold the process open."""

    allow_reuse_address = True
    daemon_threads = True


class ServingHTTPMixin:
    """Shared HTTP mechanics for the serving fronts — `ui/server.py`'s
    `_Handler` and `serving/fleet.py`'s `_FleetHandler` mix this into
    their `BaseHTTPRequestHandler`.  One copy of the JSON response
    plumbing, the `deadline_ms`/`X-Deadline-Ms` deadline parse, and the
    typed-failure -> status mapping this module's taxonomy promises, so
    the two fronts cannot drift: a new typed error added here is mapped
    once, in `respond_typed_failure`, and both fronts pick it up.

    Stays stdlib-only (json/math) like the rest of the module; the
    handler attributes used (`send_response`, `send_header`,
    `end_headers`, `wfile`, `rfile`, `headers`) are
    `BaseHTTPRequestHandler`'s."""

    def log_message(self, fmt, *args):  # noqa: D102 — silence stderr
        pass

    def request_id(self) -> str:
        """The request's ``X-Request-Id``: taken from the incoming
        header when the client (or an upstream fleet router) set one,
        minted otherwise.  Stored so `_send` echoes it on the response —
        the client always learns the id its trace is filed under."""
        rid = getattr(self, "_request_id", None)
        if rid is None:
            rid = self.headers.get("X-Request-Id")
            if not rid:
                from deeplearning4j_tpu.obs.trace import new_request_id

                rid = new_request_id()
            self._request_id = str(rid)[:64]
        return self._request_id

    def _send(self, code: int, ctype: str, data: bytes,
              headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        rid = getattr(self, "_request_id", None)
        if rid is not None:
            self.send_header("X-Request-Id", rid)
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(data)

    def _json(self, code: int, payload,
              headers: Optional[dict] = None) -> None:
        self._send(code, "application/json", json.dumps(payload).encode(),
                   headers=headers)

    def _body(self):
        """Parse the JSON request body ({} when empty).  Raises
        ValueError/JSONDecodeError on malformed JSON — the caller maps
        it to 400."""
        length = int(self.headers.get("Content-Length", 0))
        if not length:
            return {}
        return json.loads(self.rfile.read(length))

    def _deadline_s(self, body) -> Optional[float]:
        """Per-request deadline from the `deadline_ms` body field or the
        `X-Deadline-Ms` header (body wins); None = no deadline.  A
        malformed value is a client error (ValueError -> 400)."""
        raw = None
        if isinstance(body, dict) and body.get("deadline_ms") is not None:
            raw = body["deadline_ms"]
        elif self.headers.get("X-Deadline-Ms"):
            raw = self.headers["X-Deadline-Ms"]
        if raw is None:
            return None
        ms = float(raw)
        if not math.isfinite(ms) or ms <= 0:
            raise ValueError(f"deadline_ms must be a positive finite "
                             f"number of milliseconds, got {raw!r}")
        return ms / 1e3

    def _tenant(self, body) -> Optional[str]:
        """Per-request tenant identity (ISSUE-16): the JSON ``tenant``
        field wins, the ``X-Tenant`` header is the no-body-change
        fallback — shared by the single-server front and the fleet
        front (like `_deadline_s`) so clients write ONE payload shape.
        Returns None when the client named no tenant (-> the default
        tenant downstream); a malformed value is the client's 400.
        UNKNOWN-tenant validation happens against the serving plane's
        registry (`TenantRegistry.normalize`), so the 400 names the
        registered vocabulary."""
        tn = body.get("tenant") if isinstance(body, dict) else None
        if tn is None:
            tn = self.headers.get("X-Tenant")
        if tn is None:
            return None
        if not isinstance(tn, (str, int)):
            raise ValueError(
                f"tenant must be a string, got {type(tn).__name__}")
        tn = str(tn)
        if not 0 < len(tn) <= 128:
            raise ValueError("tenant must be 1..128 characters")
        return tn

    def respond_typed_failure(self, e: BaseException) -> bool:
        """Map this module's typed serving failures to their promised
        status codes and answer the request; returns False (no response
        written) for anything else so the caller applies its own
        fallback policy.  Order matters: `UnservableShapeError` is a
        ValueError and `DeadlineExceededError` a TimeoutError, so they
        are matched before any broader clauses a caller might add."""
        if isinstance(e, UnservableShapeError):
            # the request's shape falls outside the warmed bucket ladder
            # — the client's payload, not a server fault: 400, never 500
            self._json(400, {"error": str(e)})
            return True
        if isinstance(e, DeadlineExceededError):
            # the request's deadline passed before it could be served
            self._json(504, {"error": str(e)})
            return True
        if isinstance(e, TenantQuotaError):
            # the request's TENANT is over its token-rate quota: 429 +
            # Retry-After from the bucket's own refill (ISSUE-16) —
            # matched before the 503 clause because this is the
            # client's budget, not the server's capacity
            retry_after = max(1, math.ceil(
                getattr(e, "retry_after_s", 1.0)))
            self._json(429, {"error": str(e),
                             "retry_after_s": retry_after},
                       headers={"Retry-After": retry_after})
            return True
        if isinstance(e, (ServingOverloadError, ServingUnavailableError)):
            # admission refused (queue full / breaker open / draining):
            # 503 + Retry-After so well-behaved clients back off
            retry_after = max(1, math.ceil(
                getattr(e, "retry_after_s", 1.0)))
            self._json(503, {"error": str(e),
                             "retry_after_s": retry_after},
                       headers={"Retry-After": retry_after})
            return True
        return False


__all__ = [
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadlineExceededError",
    "ServingError",
    "ServingHTTPMixin",
    "ServingHTTPServer",
    "ServingOverloadError",
    "ServingUnavailableError",
    "TenantQuotaError",
    "UnservableShapeError",
    "check_admission",
]
