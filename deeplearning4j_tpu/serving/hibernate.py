"""Tiered KV state hierarchy: host LRU tier spilling to disk (ISSUE-19).

PR 15's `SwapStore` bounded preempted-lane state by HOST memory; this
module generalizes it into the device → host → disk hierarchy ROADMAP
item 2 calls for.  `TieredStateStore` keeps the exact `SwapStore`
surface (`put`/`take`/`discard`/`clear`, typed `SwapEvictedError`, peak
high-waters) so the LM server's preemption plane drops in unchanged,
but an entry pushed out of the host tier SPILLS to a disk tier instead
of vanishing — per-user session capacity becomes bounded by disk, not
HBM or RAM.  Idle sticky sessions hibernate here (`serving/lm.py`),
keyed by a digest of their token prefix so a FRESH process over the
same directory resumes them hours later, byte-identically.

The disk tier is built on the elastic-checkpoint plane's durability
discipline (ISSUE-12): every blob is written stage-then-rename atomic
(tmp file, flush+fsync, rename, fsync the directory) and recorded in a
`MANIFEST.json` that carries its SHA-256, itself rewritten with the
same two-phase dance.  A kill -9 at ANY byte leaves either the old
manifest + an orphan file (garbage-collected, counted, on the next
open) or the new manifest + a fully-fsynced blob — never a readable
half-write.  `take` re-hashes the blob against the manifest, so
torn/truncated/bit-flipped/missing files surface as a typed
`PageShipError` (and a missing KEY as `SwapEvictedError`), which the
server answers exactly like a corrupt swap blob: deterministic
recompute from the prompt, an error on the victim's trace alone, never
garbage KV (docs/robustness.md "The state hierarchy").

Single-mutator like `SwapStore`/`PagePool`: the LM worker thread under
the server's condition lock owns every call; the store takes no locks
of its own.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence

from deeplearning4j_tpu.serving.pressure import SwapEvictedError
from deeplearning4j_tpu.serving.transfer import PageShipError

MANIFEST_NAME = "MANIFEST.json"
_TMP_PREFIX = ".tmp-"
_SAFE_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def prefix_key(tokens: Sequence[int]) -> str:
    """The stable hibernation key for a token prefix: a SHA-256 over
    the token ids.  Content-addressed on purpose — the key survives
    process restarts (resume opens a fresh manifest), and two sessions
    that converged to the same prefix share one blob."""
    h = hashlib.sha256()
    for t in tokens:
        h.update(int(t).to_bytes(4, "little", signed=True))
    return "hib-" + h.hexdigest()[:40]


def _blob_name(key: str) -> str:
    """Key -> on-disk filename: keys are already filesystem-safe for
    everything this plane generates ("hib-<hex>", "swap-<n>"); anything
    else is content-addressed defensively."""
    if key and all(c in _SAFE_CHARS for c in key):
        return key + ".kvblob"
    return "k-" + hashlib.sha256(key.encode()).hexdigest()[:40] + ".kvblob"


class DiskTier:
    """The bottom tier: checksummed blob files + an atomic manifest.

    LRU over the manifest's insertion order, byte-capped like the host
    tier; eviction DELETES the oldest blob (there is nothing below disk
    to spill to — the victim's session recomputes from its prompt).
    `open()` reconciles directory against manifest: unreferenced blobs
    and stage files from a crashed predecessor are unlinked and
    counted, manifest entries whose file vanished are dropped.
    """

    def __init__(self, directory: str, capacity_bytes: int):
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1, got {capacity_bytes}")
        self.dir = str(directory)
        self.capacity_bytes = int(capacity_bytes)
        # key -> {"file", "sha256", "bytes"}; insertion order is LRU age
        self._index: "collections.OrderedDict[str, Dict]" = (
            collections.OrderedDict())
        self.bytes_stored = 0
        self.peak_bytes = 0
        self.puts = 0
        self.takes = 0
        self.evicted = 0        # entries deleted to make room
        self.corrupt = 0        # failed sha256 / torn / missing file
        self.write_failed = 0   # ENOSPC & friends: blob dropped, typed
        self.gc_orphans = 0     # unreferenced blobs / stage files GC'd
        self.gc_stale = 0       # manifested entries GC'd by prefix
        self.open()

    # ---- manifest durability ---------------------------------------------

    def _fsync_dir(self) -> None:
        fd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _write_atomic(self, final_path: str, data: bytes) -> None:
        """Stage -> fsync -> rename -> fsync dir.  The ONLY way bytes
        reach this tier; chaos_disk shadows it to model ENOSPC,
        truncation, bit-flips and kill -9 between write and rename."""
        tmp = os.path.join(
            self.dir, _TMP_PREFIX + os.path.basename(final_path))
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, final_path)
        self._fsync_dir()

    def _save_manifest(self) -> None:
        doc = {"version": 1,
               "entries": [dict(meta, key=key)
                           for key, meta in self._index.items()]}
        self._write_atomic(os.path.join(self.dir, MANIFEST_NAME),
                           json.dumps(doc).encode())

    def open(self) -> None:
        """(Re)load the manifest and reconcile it with the directory —
        the crash-recovery edge every restart walks."""
        os.makedirs(self.dir, exist_ok=True)
        self._index.clear()
        self.bytes_stored = 0
        path = os.path.join(self.dir, MANIFEST_NAME)
        entries: List[Dict] = []
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    doc = json.loads(f.read())
                entries = list(doc.get("entries", []))
            except (ValueError, OSError):
                # an unreadable manifest orphans every blob: they are
                # unlinked below and sessions recompute from prompt
                entries = []
        referenced = set()
        dirty = False
        for meta in entries:
            key = str(meta.get("key", ""))
            fname = str(meta.get("file", ""))
            fpath = os.path.join(self.dir, fname)
            if not key or not fname or not os.path.exists(fpath):
                self.gc_orphans += 1   # manifest points at nothing
                dirty = True
                continue
            referenced.add(fname)
            self._index[key] = {"file": fname,
                                "sha256": str(meta.get("sha256", "")),
                                "bytes": int(meta.get("bytes", 0))}
            self.bytes_stored += int(meta.get("bytes", 0))
        for fname in sorted(os.listdir(self.dir)):
            if fname == MANIFEST_NAME or fname in referenced:
                continue
            if fname.startswith(_TMP_PREFIX) or fname.endswith(".kvblob"):
                try:
                    os.unlink(os.path.join(self.dir, fname))
                    self.gc_orphans += 1
                except OSError:
                    pass  # best-effort GC of crash debris
        self.peak_bytes = max(self.peak_bytes, self.bytes_stored)
        if dirty:
            try:
                self._save_manifest()
            except OSError:
                pass  # next successful put rewrites it anyway

    # ---- the byte economy -------------------------------------------------

    def __len__(self) -> int:
        return len(self._index)

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def keys(self) -> Iterable[str]:
        return self._index.keys()

    def _unlink_entry(self, key: str) -> None:
        meta = self._index.pop(key, None)
        if meta is None:
            return
        self.bytes_stored -= int(meta["bytes"])
        try:
            os.unlink(os.path.join(self.dir, meta["file"]))
        except OSError:
            pass  # already gone: manifest rewrite below is the truth

    def put(self, key: str, blob: bytes) -> Optional[List[str]]:
        """Persist `blob` under `key`.  Same contract as
        `SwapStore.put`: returns the keys evicted to make room, or None
        when the blob alone exceeds the cap (refused).  A failed write
        (ENOSPC, chaos) drops THIS key — counted `write_failed`, the
        caller treats it as an eviction of exactly this entry."""
        size = len(blob)
        if size > self.capacity_bytes:
            return None
        evicted: List[str] = []
        if key in self._index:
            self._unlink_entry(key)
        while self.bytes_stored + size > self.capacity_bytes:
            old_key = next(iter(self._index))
            self._unlink_entry(old_key)
            self.evicted += 1
            evicted.append(old_key)
        fname = _blob_name(key)
        try:
            self._write_atomic(os.path.join(self.dir, fname), blob)
            self._index[key] = {
                "file": fname,
                "sha256": hashlib.sha256(blob).hexdigest(),
                "bytes": size}
            self.bytes_stored += size
            self._save_manifest()
        except OSError as e:
            # the blob (or the manifest naming it) never became durable:
            # forget the entry entirely and surface the key as lost
            self.write_failed += 1
            self._index.pop(key, None)
            self.bytes_stored = sum(int(m["bytes"])
                                    for m in self._index.values())
            evicted.append(key)
            try:
                self._save_manifest()
            except OSError:
                pass  # disk still failing; open() reconciles later
            del e
        else:
            self.puts += 1
            self.peak_bytes = max(self.peak_bytes, self.bytes_stored)
        return evicted

    def take(self, key: str) -> bytes:
        """Read, verify and remove the blob under `key`.
        `SwapEvictedError` when the key is not manifested;
        `PageShipError` when the manifested file is missing, torn,
        truncated or fails its SHA-256 — the integrity half of the
        recompute ladder."""
        meta = self._index.get(key)
        if meta is None:
            raise SwapEvictedError(
                f"hibernated state {key!r} is gone (evicted from the "
                f"{self.capacity_bytes}-byte disk tier)")
        fpath = os.path.join(self.dir, meta["file"])
        try:
            with open(fpath, "rb") as f:
                blob = f.read()
        except OSError as e:
            self.corrupt += 1
            self._drop_after_failure(key)
            raise PageShipError(
                f"hibernated blob {meta['file']!r} unreadable: {e}"
            ) from e
        if (len(blob) != int(meta["bytes"])
                or hashlib.sha256(blob).hexdigest() != meta["sha256"]):
            self.corrupt += 1
            self._drop_after_failure(key)
            raise PageShipError(
                f"hibernated blob {meta['file']!r} failed its integrity "
                f"check ({len(blob)} bytes vs manifest "
                f"{meta['bytes']}): torn or corrupt at rest")
        self._unlink_entry(key)
        self.takes += 1
        try:
            self._save_manifest()
        except OSError:
            pass  # blob already consumed; open() reconciles the index
        return blob

    def _drop_after_failure(self, key: str) -> None:
        self._unlink_entry(key)
        try:
            self._save_manifest()
        except OSError:
            pass  # disk is the thing failing; open() reconciles later

    def discard(self, key: str) -> None:
        if key in self._index:
            self._unlink_entry(key)
            try:
                self._save_manifest()
            except OSError:
                pass  # entry gone from the index either way

    def gc(self, prefix: str) -> int:
        """Drop every manifested entry whose key starts with `prefix`
        (a crashed predecessor's process-local swap keys, say) —
        counted separately from crash-debris GC."""
        victims = [k for k in self._index if k.startswith(prefix)]
        for k in victims:
            self._unlink_entry(k)
            self.gc_stale += 1
        if victims:
            try:
                self._save_manifest()
            except OSError:
                pass  # open() reconciles; files are already unlinked
        return len(victims)

    def clear(self) -> None:
        for k in list(self._index):
            self._unlink_entry(k)
        try:
            self._save_manifest()
        except OSError:
            pass  # directory emptied; manifest catches up on next put

    def stats(self) -> Dict:
        return {"entries": len(self._index),
                "bytes": self.bytes_stored,
                "capacity_bytes": self.capacity_bytes,
                "peak_bytes": self.peak_bytes,
                "puts": self.puts, "takes": self.takes,
                "evicted": self.evicted, "corrupt": self.corrupt,
                "write_failed": self.write_failed,
                "gc_orphans": self.gc_orphans,
                "gc_stale": self.gc_stale}


class TieredStateStore:
    """Host LRU tier spilling its oldest entries to a `DiskTier`.

    Drop-in for `SwapStore` on the preemption plane, PLUS the
    hibernation plane's durable bottom.  `put` lands in host memory;
    entries pushed past the host cap spill DOWN (newest-spills-oldest),
    and only what falls off the disk cap — or fails to become durable —
    is reported evicted.  `take` checks host then disk; a disk
    integrity failure propagates as `PageShipError`, a key missing from
    both tiers as `SwapEvictedError`.  Without a disk tier configured
    it degrades to exactly the `SwapStore` economy.
    """

    def __init__(self, host_bytes: int, disk_dir: Optional[str] = None,
                 disk_bytes: int = 1 << 30):
        if host_bytes < 1:
            raise ValueError(f"host_bytes must be >= 1, got {host_bytes}")
        self.capacity_bytes = int(host_bytes)   # SwapStore-compatible name
        self._blobs: "collections.OrderedDict[str, bytes]" = (
            collections.OrderedDict())
        self.bytes_stored = 0
        self.peak_bytes = 0
        self.puts = 0
        self.takes = 0
        self.evicted = 0
        self.rejected = 0
        self.spills = 0          # host -> disk demotions
        self.disk: Optional[DiskTier] = (
            DiskTier(disk_dir, disk_bytes) if disk_dir is not None
            else None)

    def __len__(self) -> int:
        return len(self._blobs) + (len(self.disk) if self.disk else 0)

    def __contains__(self, key: str) -> bool:
        return key in self._blobs or (self.disk is not None
                                      and key in self.disk)

    def _spill_or_evict(self, key: str, blob: bytes,
                        evicted: List[str]) -> None:
        if self.disk is None:
            self.evicted += 1
            evicted.append(key)
            return
        lost = self.disk.put(key, blob)
        self.spills += 1
        if lost is None:                 # larger than the whole disk cap
            self.evicted += 1
            evicted.append(key)
        else:
            for k in lost:
                self.evicted += 1
                evicted.append(k)

    def put(self, key: str, blob: bytes) -> Optional[List[str]]:
        """Store `blob` in the host tier, spilling the oldest host
        entries to disk to make room.  Returns keys evicted from the
        WHOLE hierarchy (the caller marks those lanes
        recompute-from-prompt), or None when the blob alone exceeds the
        host cap — same refusal contract as `SwapStore.put`, because a
        blob too big for host memory would only thrash the tiers."""
        size = len(blob)
        if size > self.capacity_bytes:
            self.rejected += 1
            return None
        evicted: List[str] = []
        if key in self._blobs:           # overwrite: drop the old bytes
            self.bytes_stored -= len(self._blobs.pop(key))
        elif self.disk is not None and key in self.disk:
            self.disk.discard(key)
        while self.bytes_stored + size > self.capacity_bytes:
            old_key, old = self._blobs.popitem(last=False)
            self.bytes_stored -= len(old)
            self._spill_or_evict(old_key, old, evicted)
        self._blobs[key] = blob
        self.bytes_stored += size
        self.peak_bytes = max(self.peak_bytes, self.bytes_stored)
        self.puts += 1
        return evicted

    def take(self, key: str) -> bytes:
        """Remove and return the freshest copy of `key`: host tier
        first, then disk (integrity-verified there).  Typed errors as
        documented on the class."""
        blob = self._blobs.pop(key, None)
        if blob is not None:
            self.bytes_stored -= len(blob)
            self.takes += 1
            return blob
        if self.disk is not None and key in self.disk:
            blob = self.disk.take(key)   # may raise PageShipError
            self.takes += 1
            return blob
        raise SwapEvictedError(
            f"swapped-out lane state {key!r} is gone (evicted from "
            f"the {self.capacity_bytes}-byte store)")

    def discard(self, key: str) -> None:
        blob = self._blobs.pop(key, None)
        if blob is not None:
            self.bytes_stored -= len(blob)
        elif self.disk is not None:
            self.disk.discard(key)

    def gc(self, prefix: str) -> int:
        """Drop entries by key prefix across both tiers (stale
        process-local keys a restart can never resume)."""
        n = 0
        for k in [k for k in self._blobs if k.startswith(prefix)]:
            self.bytes_stored -= len(self._blobs.pop(k))
            n += 1
        if self.disk is not None:
            n += self.disk.gc(prefix)
        return n

    def flush_to_disk(self) -> int:
        """Demote every host-tier entry to disk (drain/shutdown path,
        and the bench's forced-cold-resume lever).  Entries that fall
        off the disk cap are simply gone — counted evicted."""
        n = 0
        while self._blobs:
            key, blob = self._blobs.popitem(last=False)
            self.bytes_stored -= len(blob)
            self._spill_or_evict(key, blob, [])
            n += 1
        return n

    def clear(self, prefix: Optional[str] = None) -> None:
        """Drop host entries (all, or by key prefix).  The DISK tier is
        deliberately left alone unless explicitly asked: hibernated
        prefixes stay valid across a pool reset — KV is a deterministic
        function of tokens — so a device-side failure must not torch
        the durable tier."""
        if prefix is None:
            self._blobs.clear()
            self.bytes_stored = 0
        else:
            for k in [k for k in self._blobs if k.startswith(prefix)]:
                self.bytes_stored -= len(self._blobs.pop(k))
            if self.disk is not None:
                self.disk.gc(prefix)

    def stats(self) -> Dict:
        out = {"entries": len(self._blobs),
               "bytes": self.bytes_stored,
               "capacity_bytes": self.capacity_bytes,
               "peak_bytes": self.peak_bytes,
               "puts": self.puts, "takes": self.takes,
               "evicted": self.evicted, "rejected": self.rejected,
               "spills": self.spills}
        if self.disk is not None:
            out["disk"] = self.disk.stats()
        return out


__all__ = [
    "DiskTier",
    "MANIFEST_NAME",
    "TieredStateStore",
    "prefix_key",
]
