"""Host-side state for the paged KV cache: page allocator + radix cache.

The device side (`parallel.generation.make_paged_step`) addresses one
fixed pool of `[pages, page_size, H, K]` KV pages per layer through a
per-slot block table.  This module owns which physical page holds what:

- `PagePool` — a refcounted free-list allocator over the page ids.
  Page 0 is the reserved NULL page (masked lanes write it, unallocated
  block-table entries point at it) and is never handed out.  Pages are
  allocated on admission and refcount-freed on completion, so device
  capacity is sum-of-actual-lengths instead of `slots * max_len`.
- `RadixPrefixCache` — a page-granular radix tree over prompt token
  prefixes.  Each node covers exactly one FULL page (`page_size`
  tokens); a request whose prompt extends a cached prefix shares those
  pages (refcounted) and skips prefill for them entirely.  A prefix
  that diverges mid-page is served copy-on-write: `match()` hands back
  the divergence page + matched offset, the server copies it into a
  fresh page on device and overwrites from the divergence point.
  Un-shared cached pages (refcount 1 — held only by the tree) are
  evicted LRU-leaf-first when the pool runs dry.

Everything here is plain host Python with no locking of its own: the
LM server's WORKER THREAD is the single mutator (admission under the
server's condition lock; completion frees, radix inserts and CoW
releases in the worker's lock-free fold path).  Single-thread ownership
— not the lock — is the invariant; a second mutator path would corrupt
the refcount ledger even if it took the server's lock.

KV values at position t are a deterministic function of tokens[0..t]
and the weights, which is what makes sharing sound: a reused page holds
byte-identical k/v to what the new request would have written.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Dict, List, Optional, Sequence, Tuple


class PageLeakError(AssertionError):
    """The page ledger stopped balancing: allocated != in_use + free."""


class PagePool:
    """Refcounted fixed pool of KV page ids.

    `alloc(n)` hands out n pages with refcount 1 (or None when the free
    list is short — the caller decides whether to evict or queue);
    `retain`/`release` move shared pages' refcounts; a page whose
    refcount reaches 0 returns to the free list.  Page 0 (null) is
    outside the economy entirely.
    """

    def __init__(self, pages: int, page_size: int):
        if pages < 2:
            raise ValueError(f"pages must be >= 2 (page 0 is the "
                             f"reserved null page), got {pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.pages = int(pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed pages are re-used first, which
        # keeps the working set of touched pages small
        self._free: List[int] = list(range(self.pages - 1, 0, -1))
        self._ref = [0] * self.pages

    @property
    def usable(self) -> int:
        """Allocatable pages (total minus the null page)."""
        return self.pages - 1

    @property
    def free(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.usable - len(self._free)

    def refcount(self, page: int) -> int:
        return self._ref[page]

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh pages at refcount 1, or None when fewer than n are
        free (all-or-nothing: a partial grant would deadlock two lanes
        each holding half of what the other needs)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        return out

    def retain(self, page_ids: Sequence[int]) -> None:
        for p in page_ids:
            if not 0 < p < self.pages or self._ref[p] <= 0:
                raise PageLeakError(
                    f"retain of un-allocated page {p} (ref "
                    f"{self._ref[p] if 0 <= p < self.pages else '?'})")
            self._ref[p] += 1

    def release(self, page_ids: Sequence[int]) -> None:
        for p in page_ids:
            if not 0 < p < self.pages or self._ref[p] <= 0:
                raise PageLeakError(
                    f"release of un-held page {p} (ref "
                    f"{self._ref[p] if 0 <= p < self.pages else '?'})")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)

    def check_ledger(self) -> Dict:
        """The page-accounting invariant (chaos tests assert it):
        allocated == in_use + free, every free page at refcount 0,
        every non-free page at refcount > 0."""
        held = sum(1 for p in range(1, self.pages) if self._ref[p] > 0)
        free_refs_ok = all(self._ref[p] == 0 for p in self._free)
        out = {"pages": self.usable, "free": self.free,
               "in_use": self.in_use, "held": held,
               "balanced": (held == self.in_use
                            and self.free + held == self.usable
                            and free_refs_ok)}
        return out


class _RadixNode:
    __slots__ = ("key", "page", "children", "last_used", "parent")

    def __init__(self, key: Optional[Tuple[int, ...]], page: Optional[int],
                 parent: Optional["_RadixNode"]):
        self.key = key                  # page_size tokens this page holds
        self.page = page                # physical page id
        self.children: Dict[Tuple[int, ...], "_RadixNode"] = {}
        self.last_used = 0
        self.parent = parent


def _common_prefix(a: Sequence[int], b: Sequence[int]) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class RadixPrefixCache:
    """Page-granular radix tree: prompt token prefix -> cached page run.

    Sharing granularity is one full page, so only prompts of at least
    `page_size` tokens ever create reusable nodes; the divergence page
    is served copy-on-write by the caller.  The tree holds ONE refcount
    on every cached page; `evict()` drops LRU leaves whose page nobody
    else holds, returning capacity without ever invalidating a page an
    active lane still reads.
    """

    def __init__(self, pool: PagePool):
        self.pool = pool
        self.ps = pool.page_size
        self.root = _RadixNode(None, None, None)
        self._clock = itertools.count(1)
        self.nodes = 0

    # ---- lookup -----------------------------------------------------------

    def match(self, tokens: Sequence[int]
              ) -> Tuple[List[int], Optional[Tuple[int, int]]]:
        """Longest cached prefix of `tokens`.

        Returns `(full_pages, partial)`: the page ids covering whole
        matched pages, plus `(page_id, matched_len)` when the next page
        matches only its first `matched_len` tokens (the copy-on-write
        divergence page).  EVERY returned page is retained (+1 ref) so
        eviction cannot free it between match and use — the caller
        releases the partial page after copying, and the full pages
        when the lane completes.  Callers cap reuse by passing
        `tokens[:plen-1]`: the last prompt token must always be re-fed
        to produce the first sampled logits."""
        tick = next(self._clock)
        node, pages, i = self.root, [], 0
        partial: Optional[Tuple[int, int]] = None
        while True:
            chunk = tuple(int(t) for t in tokens[i:i + self.ps])
            child = (node.children.get(chunk)
                     if len(chunk) == self.ps else None)
            if child is not None:
                child.last_used = tick
                pages.append(child.page)
                node, i = child, i + self.ps
                continue
            if chunk:
                best, blen = None, 0
                for key, cand in node.children.items():
                    m = _common_prefix(key, chunk)
                    if m > blen:
                        best, blen = cand, m
                if best is not None:
                    best.last_used = tick
                    partial = (best.page, blen)
            break
        if pages:
            self.pool.retain(pages)
        if partial is not None:
            self.pool.retain([partial[0]])
        return pages, partial

    # ---- insert -----------------------------------------------------------

    def insert(self, tokens: Sequence[int], page_ids: Sequence[int]) -> int:
        """Register a lane's full prompt pages once its prefill is done:
        `page_ids[i]` holds tokens `[i*ps, (i+1)*ps)`.  Nodes already
        present (e.g. the shared pages this lane itself reused, or a
        concurrent identical prompt that prefilled first) are kept;
        genuinely new pages get +1 tree refcount.  Returns how many
        pages the tree newly took ownership of."""
        tick = next(self._clock)
        node, inserted = self.root, 0
        for i, page in enumerate(page_ids):
            chunk = tuple(int(t) for t in tokens[i * self.ps:
                                                 (i + 1) * self.ps])
            if len(chunk) < self.ps:
                raise ValueError("insert() takes only FULL prompt pages")
            child = node.children.get(chunk)
            if child is None:
                child = _RadixNode(chunk, int(page), node)
                node.children[chunk] = child
                self.pool.retain([int(page)])
                self.nodes += 1
                inserted += 1
            child.last_used = tick
            node = child
        return inserted

    # ---- eviction ---------------------------------------------------------

    def _leaves(self) -> List[_RadixNode]:
        out, stack = [], list(self.root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    def evictable(self) -> int:
        """Pages eviction could reclaim if run to exhaustion: nodes the
        tree alone holds (refcount 1) whose whole subtree is likewise
        tree-only — eviction is leaf-first, so a shared descendant pins
        every ancestor above it.  Admission uses this to decide whether
        evicting can possibly satisfy a request BEFORE destroying any
        cached prefix (an eviction that cannot free enough pages would
        wipe the cache and still admit nothing)."""

        def count(node: _RadixNode) -> Tuple[int, bool]:
            n, ok = 0, True
            for child in node.children.values():
                cn, cok = count(child)
                n += cn
                ok = ok and cok
            if node is self.root:
                return n, ok
            if ok and self.pool.refcount(node.page) == 1:
                return n + 1, True
            return n, False

        return count(self.root)[0]

    def evict(self, need_free: int) -> int:
        """Drop LRU nodes whose page only the tree holds until the pool
        has `need_free` pages free (or nothing evictable remains),
        leaf-first so a freed child can expose its parent.  Returns the
        number of pages evicted.  Pages an active lane still shares
        (refcount > 1) are skipped: releasing the tree's ref on them
        frees no capacity and only destroys future reuse.  One heap
        pass — candidates are collected once and parents pushed as
        their last child goes, not a full tree re-walk per page."""
        if self.pool.free >= need_free:
            return 0
        tie = itertools.count()
        heap: List[Tuple[int, int, _RadixNode]] = []

        def push(node: _RadixNode) -> None:
            if not node.children and self.pool.refcount(node.page) == 1:
                heapq.heappush(heap, (node.last_used, next(tie), node))

        for leaf in self._leaves():
            push(leaf)
        evicted = 0
        while heap and self.pool.free < need_free:
            _, _, victim = heapq.heappop(heap)
            # a node may sit in the heap twice (pushed as a leaf, again
            # as an emptied parent) or have been pinned since: re-check
            if (victim.children
                    or victim.parent.children.get(victim.key) is not victim
                    or self.pool.refcount(victim.page) != 1):
                continue
            del victim.parent.children[victim.key]
            self.pool.release([victim.page])
            self.nodes -= 1
            evicted += 1
            if victim.parent is not self.root:
                push(victim.parent)
        return evicted

    def forget(self, tokens: Sequence[int]) -> int:
        """Drop the tree's hold on the full-page chain covering
        `tokens`, deepest-first — the hibernation sweep's targeted
        eviction (ISSUE-19): once a session's pages rest on the state
        store, the tree's refcount is the only thing keeping them on
        device.  A node is dropped only while it is a leaf the tree
        alone holds (refcount 1); the walk stops at the first node that
        is still shared or still has children (which also pins every
        ancestor above it, exactly like `evict()`).  Returns pages
        released."""
        node, chain = self.root, []
        i = 0
        while True:
            chunk = tuple(int(t) for t in tokens[i:i + self.ps])
            child = (node.children.get(chunk)
                     if len(chunk) == self.ps else None)
            if child is None:
                break
            chain.append(child)
            node, i = child, i + self.ps
        dropped = 0
        for victim in reversed(chain):
            if victim.children or self.pool.refcount(victim.page) != 1:
                break
            del victim.parent.children[victim.key]
            self.pool.release([victim.page])
            self.nodes -= 1
            dropped += 1
        return dropped

    def clear(self) -> int:
        """Release every tree-held page back to THIS pool.  Diagnostic
        /test helper only: the server's real reset path
        (`ContinuousLMServer._reset_pool_locked`) discards the pool and tree
        wholesale instead, because after a failed dispatch the device
        page CONTENTS are gone too and per-slot bookkeeping must reset
        with them — clear() alone would leave that state stale."""
        dropped = 0
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self.pool.release([n.page])
            dropped += 1
        self.root = _RadixNode(None, None, None)
        self.nodes = 0
        return dropped


__all__ = ["PageLeakError", "PagePool", "RadixPrefixCache"]
