"""Continuous batching for LM generation.

`generate()` decodes one request (or one fixed batch) to completion:
requests arriving mid-decode wait for the whole previous decode.  The
continuous server instead keeps a fixed pool of `slots` decode lanes
over ONE `[L, slots, max_len, H, K]` KV cache and advances every active
lane one token per device step (`parallel.generation.make_slot_step`):

- a finished sequence frees its slot immediately;
- a queued prompt joins mid-flight — its slot restarts at position 0 and
  its prompt tokens are teacher-forced through the same per-token step
  (prefill-as-decode), so admission never interrupts other lanes;
- every dispatch shape is fixed (`slots` lanes, whatever is inactive
  rides as masked padding), so the WHOLE serving lifetime runs ONE
  compiled program per config.

Greedy and plain-temperature sampling run in the slot pool (sampling is
seeded per request: `fold_in(PRNGKey(seed), tokens_generated)`, so a
request's output does not depend on what shared its dispatches).
top-k/top-p/beam requests take the legacy whole-sequence path in
`ui/server.py` — their filters are static program variants, not per-slot
switches.

Resilience contract (ISSUE-4, mirrors `batcher.MicroBatcher`): bounded
admission (`max_queue_depth` -> `ServingOverloadError`), per-request
deadlines shed at the admitter before a prompt ever occupies a slot
(`DeadlineExceededError`), an abandoned request's slot is freed so a
timed-out client stops costing decode steps, an optional circuit
breaker fast-fails admission after consecutive step failures, and
`begin_drain()`/`drain()` implement the SIGTERM grace window.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    ServingUnavailableError,
    check_admission,
)


def validate_request(cfg, prompt_ids, max_new_tokens: int) -> List[int]:
    """THE serving-request contract, shared by the HTTP endpoint (as
    400s) and `ContinuousLMServer` (as ValueErrors): non-empty prompt of
    in-vocab tokens, positive budget, and prompt + new tokens within the
    model's fixed max_len cache.  A bad request must fail HERE, before
    it reaches a decode worker — an error raised mid-drain fails every
    co-travelling request in the slot pool."""
    ids = [int(t) for t in prompt_ids]
    if not ids:
        raise ValueError("prompt_ids must contain at least one token")
    bad = [t for t in ids if not 0 <= t < cfg.vocab_size]
    if bad:
        raise ValueError(f"prompt_ids outside vocab "
                         f"[0, {cfg.vocab_size}): {bad[:5]}")
    max_new = int(max_new_tokens)
    if max_new < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
    if len(ids) + max_new > cfg.max_len:
        raise ValueError(
            f"prompt ({len(ids)} tokens) + max_new_tokens ({max_new}) "
            f"exceeds max_len ({cfg.max_len}); shorten one of them")
    return ids


class _LMRequest:
    __slots__ = ("prompt", "max_new", "temperature", "seed", "event",
                 "result", "error", "enqueued", "deadline", "abandoned")

    def __init__(self, prompt: List[int], max_new: int, temperature: float,
                 seed: int, deadline: Optional[float] = None):
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.event = threading.Event()
        self.result: Optional[List[int]] = None
        self.error: Optional[BaseException] = None
        self.enqueued = time.perf_counter()
        self.deadline = deadline   # absolute perf_counter time, or None
        self.abandoned = False     # client gave up waiting


class _Slot:
    __slots__ = ("req", "pos", "fed", "generated")

    def __init__(self):
        self.req: Optional[_LMRequest] = None
        self.pos = 0          # next cache position to write
        self.fed = 0          # prompt tokens already fed (prefill cursor)
        self.generated: List[int] = []

    @property
    def active(self) -> bool:
        return self.req is not None


class ContinuousLMServer:
    """Slot-based continuous decode over one TransformerLM.

    `generate(prompt_ids, max_new_tokens)` is thread-safe and blocks
    until the request's sequence is complete; any number of requests
    share the device via the slot pool.
    """

    def __init__(self, cfg, params, slots: int = 4,
                 metrics: Optional[ServingMetrics] = None,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_queue_depth is not None and max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1 or None, got "
                             f"{max_queue_depth}")
        self.cfg = cfg
        self.params = params
        self.n_slots = int(slots)
        self.max_queue_depth = max_queue_depth
        self.default_deadline_s = default_deadline_s
        self.breaker = breaker
        self.metrics = metrics if metrics is not None else ServingMetrics()
        if breaker is not None:
            breaker.add_listener(self.metrics.set_breaker_state)
            self.metrics.set_breaker_state(breaker.state)
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._running = False
        self._accepting = True
        self._thread: Optional[threading.Thread] = None
        self._cache = None    # lazy: (k, v) device buffers
        self._step = None
        self._slots = [_Slot() for _ in range(self.n_slots)]
        self._steps = 0

    # ---- client side ------------------------------------------------------

    def validate(self, prompt_ids, max_new_tokens: int) -> List[int]:
        """`validate_request` against this server's config."""
        return validate_request(self.cfg, prompt_ids, max_new_tokens)

    def _retry_after_locked(self) -> float:
        lat = self.metrics.latency.summary()
        per_req = (lat.get("p50_ms", 100.0) or 100.0) / 1e3
        return max(0.1, per_req * (1 + len(self._queue) / self.n_slots))

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 timeout: Optional[float] = None,
                 deadline_s: Optional[float] = None) -> List[int]:
        """prompt ids -> full sequence (prompt + generated), blocking.

        `timeout` bounds the client's wait; `deadline_s` (default
        `default_deadline_s`) rides the queue item so the admitter sheds
        the request once it expires instead of spending decode steps on
        a client that already gave up."""
        ids = self.validate(prompt_ids, max_new_tokens)
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        # fold into int32 range (the device-side PRNGKey seed dtype) so a
        # huge client seed cannot overflow the worker's seed vector
        seed = int(seed) & 0x7FFFFFFF
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        req = _LMRequest(ids, int(max_new_tokens), temperature, seed)
        if deadline_s is not None:
            req.deadline = req.enqueued + float(deadline_s)
        with self._cond:
            check_admission(
                accepting=self._accepting, breaker=self.breaker,
                queue_depth=len(self._queue),
                max_queue_depth=self.max_queue_depth,
                metrics=self.metrics,
                retry_after_s=self._retry_after_locked, what="LM")
            if not self._running:
                self._start_locked()
            self._queue.append(req)
            self.metrics.set_queue_depth(len(self._queue))
            self._cond.notify_all()
        if not req.event.wait(timeout):
            # Cancel rather than abandon (mirror of MicroBatcher.submit):
            # a still-queued request is removed so retry-on-timeout
            # clients cannot fill the pool with zombie decodes; one
            # already in a slot is MARKED abandoned and the worker frees
            # the slot at its next admit round (slot state is written by
            # the worker thread ONLY — freeing it here would race the
            # lock-free step-input build in `_drain_step`).
            now = time.perf_counter()
            with self._cond:
                try:
                    self._queue.remove(req)
                    self.metrics.set_queue_depth(len(self._queue))
                    self.metrics.record_shed()
                except ValueError:
                    req.abandoned = True
                    # a request the worker already RESOLVED needs no shed
                    # here: a completed result was counted as a served
                    # request at fold time, and a worker-shed error was
                    # counted when it was shed; an in-slot request is
                    # shed by the admitter when it frees the slot
                resolved_with_error = (req.event.is_set()
                                       and req.error is not None)
            if (req.deadline is not None and now >= req.deadline
                    and not resolved_with_error):
                # count a deadline miss only when the server-side
                # deadline actually expired and the worker has not
                # already accounted it (mirror of MicroBatcher.submit)
                self.metrics.record_deadline_missed()
            raise DeadlineExceededError(
                f"LM request timed out after {timeout}s")
        if req.error is not None:
            raise req.error
        return req.result

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self.metrics.set_queue_depth(0)
        for req in leftovers:
            self.metrics.record_shed()
            req.error = ServingUnavailableError("LM server stopped")
            req.event.set()

    # ---- drain lifecycle --------------------------------------------------

    @property
    def accepting(self) -> bool:
        """False once draining — the /readyz signal."""
        with self._cond:
            return self._accepting

    def ready(self) -> bool:
        """Readiness for traffic: accepting admissions and the circuit
        breaker is not open (docs/robustness.md serving lifecycle)."""
        if not self.accepting:
            return False
        return self.breaker is None or self.breaker.state != "open"

    def begin_drain(self) -> None:
        """Stop admission: subsequent generates raise
        `ServingUnavailableError`; queued + in-slot work still decodes."""
        with self._cond:
            self._accepting = False
            self._cond.notify_all()

    def drain(self, grace_s: float = 5.0) -> bool:
        """Stop admission, wait up to `grace_s` for queued + in-slot
        requests to finish, then stop the worker.  Returns True when
        everything drained within the grace window."""
        self.begin_drain()
        deadline = time.perf_counter() + max(0.0, grace_s)
        while True:
            with self._cond:
                busy = bool(self._queue) or any(
                    s.active for s in self._slots)
            if not busy:
                break
            if time.perf_counter() >= deadline:
                break
            time.sleep(0.01)
        with self._cond:
            drained = not self._queue and not any(
                s.active for s in self._slots)
        self.stop()
        return drained

    def stats(self) -> Dict:
        out = self.metrics.snapshot()
        with self._cond:
            out["slots"] = self.n_slots
            out["active_slots"] = sum(s.active for s in self._slots)
            out["queue_depth"] = len(self._queue)
            out["decode_steps"] = self._steps
            out["accepting"] = self._accepting
        out["max_len"] = self.cfg.max_len
        out["compiled_programs"] = 1  # one slot program per config
        return out

    # ---- worker side ------------------------------------------------------

    def _reset_cache(self) -> None:
        """(Re)allocate the KV pool.  Needed after a FAILED dispatch
        too: the step donates the k/v buffers, so an exception mid-step
        leaves `self._cache` pointing at deleted buffers — without a
        rebuild the keep-serving path would fail every later request."""
        from deeplearning4j_tpu.parallel.generation import init_slot_cache

        cache = init_slot_cache(self.cfg, self.n_slots)
        self._cache = (cache["k"], cache["v"])

    def _start_locked(self) -> None:
        if self._step is None:
            from deeplearning4j_tpu.parallel.generation import (
                make_slot_step,
            )

            self._step = make_slot_step(self.cfg)
            self._reset_cache()
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="lm-decode")
        self._thread.start()

    def _admit_locked(self) -> None:
        """Queued prompts join free slots; the slot restarts at position
        0 — stale KV beyond a slot's position is masked, so no reset of
        the cache buffers is needed.  Doomed work is shed first: an
        abandoned request's slot is freed (its client gave up — further
        decode steps are wasted device time; slot state is worker-owned,
        so this is the one safe place to free it), and an expired or
        abandoned queue item must never occupy a slot.  The queue sweep
        is one rebuild pass — per-item `deque.remove` would be O(n^2)
        under exactly the overload storm it exists for."""
        for slot in self._slots:
            if slot.active and slot.req.abandoned:
                self.metrics.record_shed()
                slot.req = None
        now = time.perf_counter()
        kept, shed = collections.deque(), 0
        for req in self._queue:
            if req.abandoned:
                shed += 1
            elif req.deadline is not None and now >= req.deadline:
                shed += 1
                self.metrics.record_deadline_missed()
                req.error = DeadlineExceededError(
                    f"deadline exceeded after {now - req.enqueued:.3f}s "
                    f"in LM queue; shed before decode")
                req.event.set()
            else:
                kept.append(req)
        if shed:
            self._queue = kept
            self.metrics.record_shed(shed)
        for slot in self._slots:
            if not self._queue:
                break
            if slot.active:
                continue
            slot.req = self._queue.popleft()
            slot.pos = 0
            slot.fed = 0
            slot.generated = []
        self.metrics.set_queue_depth(len(self._queue))

    def _drain_step(self) -> bool:
        """One scheduling round: admit, build the step inputs, dispatch,
        fold the sampled tokens back into each lane.  Returns False when
        idle (nothing active, nothing queued)."""
        with self._cond:
            self._admit_locked()
            active = [s for s in self._slots if s.active]
            if not active:
                return False
        if self.breaker is not None and not self.breaker.allow_dispatch():
            # open breaker: fast-fail whatever is in flight rather than
            # burning decode steps on a failing device
            err = CircuitOpenError(
                "circuit breaker open: decode fast-failed",
                retry_after_s=self.breaker.retry_after_s())
            with self._cond:
                for s in self._slots:
                    if s.active:
                        self.metrics.record_shed()
                        s.req.error = err
                        s.req.event.set()
                        s.req = None
            return True
        if self._cache is None:
            # a failed step consumed its donated k/v buffers and set the
            # cache aside; rebuild INSIDE the protected loop so a failing
            # rebuild fails this round's requests instead of killing the
            # worker thread (slots restart at pos 0 — no state to keep)
            self._reset_cache()
        token = np.zeros((self.n_slots,), np.int32)
        pos = np.zeros((self.n_slots,), np.int32)
        temp = np.zeros((self.n_slots,), np.float32)
        seeds = np.zeros((self.n_slots,), np.int32)
        counts = np.zeros((self.n_slots,), np.int32)
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            req = slot.req
            if slot.fed < len(req.prompt):     # prefill: teacher-force
                token[i] = req.prompt[slot.fed]
            else:                              # decode: feed last sample
                token[i] = slot.generated[-1]
            pos[i] = slot.pos
            temp[i] = req.temperature
            seeds[i] = req.seed
            counts[i] = len(slot.generated)
        nxt, k, v = self._step(self.params, *self._cache, pos, token,
                               temp, seeds, counts)
        if self.breaker is not None:
            self.breaker.record_success()
        self._cache = (k, v)
        nxt = np.asarray(nxt)
        self._steps += 1
        emitted = 0
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            slot.pos += 1
            if slot.fed < len(slot.req.prompt):
                slot.fed += 1
                # the LAST prompt token's logits yield the first sample
                if slot.fed < len(slot.req.prompt):
                    continue
            slot.generated.append(int(nxt[i]))
            emitted += 1
            if len(slot.generated) >= slot.req.max_new:
                if slot.req.abandoned:
                    # the client timed out mid-decode and already got
                    # DeadlineExceededError: the finished sequence is
                    # discarded work, not a served request
                    self.metrics.record_shed()
                else:
                    slot.req.result = slot.req.prompt + slot.generated
                    self.metrics.record_request(
                        time.perf_counter() - slot.req.enqueued)
                    slot.req.event.set()
                slot.req = None
        self.metrics.record_dispatch(len(active), self.n_slots)
        if emitted:
            self.metrics.record_tokens(emitted)
        return True

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._running:
                    # abort in-flight + queued rather than leaving clients
                    # blocked on a dead worker
                    victims = [s.req for s in self._slots if s.active]
                    victims += list(self._queue)
                    for s in self._slots:
                        s.req = None
                    self._queue.clear()
                    for r in victims:
                        self.metrics.record_shed()
                        r.error = ServingUnavailableError(
                            "LM server stopped")
                        r.event.set()
                    return
            try:
                busy = self._drain_step()
            except BaseException as e:  # noqa: BLE001 — fail in-flight, keep serving
                if self.breaker is not None:
                    self.breaker.record_failure()
                with self._cond:
                    victims = [s for s in self._slots if s.active]
                    for s in victims:
                        s.req.error = e
                        s.req.event.set()
                        s.req = None
                # the failed step may have consumed its donated k/v
                # buffers; mark the cache dead so the next round rebuilds
                # it inside this same protected loop (a rebuild that
                # throws then fails THAT round's requests, not the worker)
                self._cache = None
                busy = True
            if not busy:
                with self._cond:
                    if not self._running:
                        return
                    if not self._queue:
                        self._cond.wait(0.05)
            else:
                time.sleep(0)  # yield: let submitters enqueue mid-decode
