"""Continuous batching for LM generation.

`generate()` decodes one request (or one fixed batch) to completion:
requests arriving mid-decode wait for the whole previous decode.  The
continuous server instead keeps a fixed pool of `slots` decode lanes
and advances every active lane each device step:

- a finished sequence frees its slot immediately;
- a queued prompt joins mid-flight — prefill rides the same per-token
  step (prefill-as-decode), so admission never interrupts other lanes;
- every dispatch shape is fixed (`slots` lanes, whatever is inactive
  rides as masked padding), so the WHOLE serving lifetime runs a fixed,
  pre-compilable program set per config.

KV state comes in two modes (ISSUE-7):

- `kv="dense"` — the original one `[L, slots, max_len, H, K]` cache:
  every lane provisions max_len positions whether it uses them or not
  (`parallel.generation.make_slot_step`).
- `kv="paged"` (default) — block-table paged KV: one fixed pool of
  `[pages, page_size, H, K]` pages per layer, per-slot page lists
  carried as a `[slots, max_pages]` int32 block table inside the jitted
  step (`parallel.generation.make_paged_step`).  Pages are allocated on
  admission and refcount-freed on completion (`serving/paged.py`), so
  device capacity is sum-of-actual-lengths instead of slots * max_len.
  On top of it:

  * **radix prefix reuse** — a host-side radix tree over prompt token
    prefixes maps to refcounted page runs; a request whose prompt
    shares a cached prefix skips prefill for those tokens entirely
    (copy-on-write at the divergence page), which is what the fleet's
    prefix-affinity router (ISSUE-6) was set up to feed;
  * **chunked prefill** — a long prompt feeds up to `prefill_chunk`
    tokens per dispatch instead of one, so admission latency shrinks
    by ~chunk× while active decode lanes keep advancing every step.

  The compile-count discipline holds: one program per
  (config, pages, page_size, chunk) — a decode-step (chunk 1), one
  prefill-chunk step when `prefill_chunk > 1`, and the copy-on-write
  page copy; `warmup()` compiles all of them before traffic (after it,
  no request can trigger an XLA compile), otherwise each compiles on
  its first dispatch like every other serving program.

Greedy and plain-temperature sampling run in the slot pool (sampling is
seeded per request: `fold_in(PRNGKey(seed), tokens_generated)`, so a
request's output does not depend on what shared its dispatches).
top-k/top-p/beam requests take the legacy whole-sequence path in
`ui/server.py` — their filters are static program variants, not per-slot
switches.

**Speculative multi-token decode** (ISSUE-13, `speculate="ngram"` or
`"model"`, paged KV only): a cheap drafter (`serving/draft.py`)
proposes up to `draft_len` continuation tokens per greedy decode lane
per round; the target model scores `[last_committed, d_1..d_k]` in ONE
wide dispatch through the SAME chunked-feed program ladder chunked
prefill rides, and the accept rule runs in-jit
(`parallel.generation.make_spec_step`): the longest draft prefix the
target's argmax agrees with is committed, plus the target's own bonus
token at the divergence point.  Greedy output is byte-identical to
1-token decode by construction.  Rollback is a pointer move on the
paged pool — rejected columns wrote k/v into the lane's own future
pages (or the null page), positions the causal mask hides, so the host
just advances `pos` by 1 + accepted; pages were allocated at admission
for the whole request and flow back through the normal `PagePool`
refcount discipline at completion, never per round.  SAMPLING lanes
(temperature > 0) are never drafted for — verifying a sampled draft
greedily would mis-sample — and fall back to 1-token decode per round
while riding the same dispatches; `speculate` with `kv="dense"` is a
typed ValueError at construction (the rollback story needs pages).
Accounting: accept-rate / tokens-per-round counters in
`ServingMetrics`, a `speculate` section in `stats()`, and
drafted/accepted attrs on each request's decode trace span.

**Disaggregated serving hooks** (ISSUE-14, `ship=True`, paged KV only):
the pool speaks the KV page-shipping wire plane (`serving/transfer.py`)
so a fleet can split worker roles — prefill workers chew long prompts
and ship the finished pages to decode workers:

- `prefill_export(...)` admits a request normally (radix reuse +
  chunked prefill included), but at prefill completion — after the
  first token is sampled and the prompt pages enter the radix tree —
  the lane's pages are gathered OUT of the pool in one fixed-shape
  dispatch (`parallel.generation.make_page_gather`) and the request
  resolves to a `PageExport` instead of decoding further.  The radix
  tree keeps the prefix, so repeated shared-prefix prefills stay
  nearly free on the prefill worker.
- `admit_with_pages(export)` allocates the lane's full page budget
  from the local pool, installs the shipped pages in ONE batched
  dispatch (`make_page_install`, the pending-install plane riding the
  same pre-feed window as pending CoW copies), registers the prompt's
  full pages in the local radix tree, and joins the lane mid-flight
  exactly like a chunked-prefill completion: pos/fed/committed state
  arrives with the shipment, decode continues through the normal step.
  KV at position t is a pure function of tokens[0..t] and the weights,
  so a shipped lane's output is byte-identical to a locally-prefilled
  one, greedy or seeded sampling.

**Token streaming + TTFT**: `generate_stream(...)` yields each
committed token as it lands (speculative rounds can commit several at
once — each is yielded individually), backing the SSE leg of
`/lm/generate`; a consumer that goes away mid-stream abandons the
request, freeing its slot and pages at the next admit round.  Every
request stamps time-to-first-token into the `ttft` histogram — the
latency the prefill/decode split exists to protect.  Per-request
`session_id`s feed sticky-session accounting (`session_affinity_hits`)
whether or not a fleet router is in front.

**Overload survival** (ISSUE-15, `serving/pressure.py`): every request
carries a `priority` (`interactive` > `batch` > `best_effort`, default
interactive) and the admission queue is kept ordered by
(priority, arrival) — one class degenerates to the historic FIFO.
With `preempt=True` (paged KV), a higher-priority request that would
otherwise wait on a dry `PagePool` PREEMPTS the lowest-priority active
lane: its pages are gathered in one fixed-shape dispatch, serialized
through the shipping wire frame (SHA-256 over the payload) into a
bounded host-side `SwapStore` (LRU, byte-capped), its slot and pages
freed, and the request requeued with its original arrival stamp.  On
re-admission the lane restores through the same pending-install plane
a shipped lane uses and resumes BYTE-IDENTICALLY — greedy and seeded
sampling alike, because the `fold_in(seed, count)` automaton sees
identical inputs — composing with speculation, radix prefix reuse and
chunked prefill.  A victim whose swap state was evicted (typed
`SwapEvictedError`) or corrupted (the SHA-256 check) recomputes from
its prompt: deterministic decode makes even that path byte-identical,
so the loss is visible only in the ledger and the trace.  With
`brownout` on, a pool-pressure automaton (`BrownoutLadder`:
pages-free + queue-depth signals, hysteresis both directions) degrades
gracefully before shedding — 1: speculation off, 2: prefill ride-along
width shrunk, 3: best_effort lanes preempted proactively, 4:
best_effort admissions shed with Retry-After — never touching
interactive until the ladder is exhausted; every transition is
counted, traced and exposed (docs/robustness.md "The degradation
ladder").

**Multi-tenant traffic shaping** (ISSUE-16, `serving/tenancy.py`):
with a `TenantRegistry` installed every request carries a `tenant`
(default: the built-in unmetered ``default`` tenant, so registry-less
deployments and pre-tenancy clients keep exact behavior).  Admission
charges the request's token cost against the tenant's token bucket
BEFORE the shared gate — an over-quota tenant gets a typed 429 whose
Retry-After derives from its own bucket refill, and its refusals never
consume the queue bound other tenants share.  The queue order becomes
(priority rank, WFQ virtual finish time, arrival): priority still
dominates absolutely; weighted-fair queuing only interleaves tenants
WITHIN a class, and one tenant degenerates to the historic FIFO.  The
brownout ladder's L3 preemption and L4 shed become tenant-aware: while
any tenant is over quota or burning SLO budget, victims are taken from
the worst offender first and a compliant tenant is never touched;
without an offender the rungs keep their PR-15 global behavior.
Per-tenant ledgers (tokens in/out, throttles, SLO burn rate) ride
``/serving/stats`` under ``tenancy`` and Prometheus under the
``serving_lm_tenant_*`` families (docs/robustness.md "Tenancy &
SLOs").

Resilience contract (ISSUE-4, mirrors `batcher.MicroBatcher`): bounded
admission (`max_queue_depth` -> `ServingOverloadError`), per-request
deadlines shed at the admitter before a prompt ever occupies a slot
(`DeadlineExceededError`), an abandoned request's slot (and its pages)
is freed so a timed-out client stops costing decode steps, an optional
circuit breaker fast-fails admission after consecutive step failures,
and `begin_drain()`/`drain()` implement the SIGTERM grace window.  A
failed dispatch consumed its donated KV buffers AND invalidated the
page contents, so the recovery path rebuilds the device pool and resets
the allocator + radix tree together — a stale tree entry pointing into
a zeroed pool would serve silent garbage.
"""

from __future__ import annotations

import collections
import queue as _queue
import threading
import time
from typing import Dict, Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.obs.compilewatch import (
    compile_scope,
    compile_watcher,
)
from deeplearning4j_tpu.obs.registry import MetricsRegistry
from deeplearning4j_tpu.obs.trace import (
    TraceRecorder,
    new_request_id,
    span,
    trace,
)
from deeplearning4j_tpu.serving.hibernate import (
    TieredStateStore,
    prefix_key,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.paged import PagePool, RadixPrefixCache
from deeplearning4j_tpu.serving.pressure import (
    BrownoutLadder,
    PRIORITY_RANK,
    PressureConfig,
    RANK_BEST_EFFORT,
    SwapEvictedError,
    normalize_priority,
)
from deeplearning4j_tpu.serving.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    ServingError,
    ServingOverloadError,
    ServingUnavailableError,
    check_admission,
)
from deeplearning4j_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    TenantQuotaError,
    TenantRegistry,
)
from deeplearning4j_tpu.serving.transfer import (
    PageExport,
    PageShipError,
    check_compatible,
    deserialize_export,
    model_signature,
    quantize_export,
    serialize_export,
)


def validate_request(cfg, prompt_ids, max_new_tokens: int) -> List[int]:
    """THE serving-request contract, shared by the HTTP endpoint (as
    400s) and `ContinuousLMServer` (as ValueErrors): non-empty prompt of
    in-vocab tokens, positive budget, and prompt + new tokens within the
    model's fixed max_len cache.  A bad request must fail HERE, before
    it reaches a decode worker — an error raised mid-drain fails every
    co-travelling request in the slot pool."""
    ids = [int(t) for t in prompt_ids]
    if not ids:
        raise ValueError("prompt_ids must contain at least one token")
    bad = [t for t in ids if not 0 <= t < cfg.vocab_size]
    if bad:
        raise ValueError(f"prompt_ids outside vocab "
                         f"[0, {cfg.vocab_size}): {bad[:5]}")
    max_new = int(max_new_tokens)
    if max_new < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
    if len(ids) + max_new > cfg.max_len:
        raise ValueError(
            f"prompt ({len(ids)} tokens) + max_new_tokens ({max_new}) "
            f"exceeds max_len ({cfg.max_len}); shorten one of them")
    return ids


class _LMRequest:
    __slots__ = ("prompt", "max_new", "temperature", "seed", "event",
                 "result", "error", "enqueued", "deadline", "abandoned",
                 "request_id", "t_installed", "t_done", "prefix_matched",
                 "drafted", "accepted", "export", "export_result",
                 "import_pages", "stream", "session_id", "t_first",
                 "priority", "rank", "swap_key", "swap_restore",
                 "swap_error", "stream_pushed", "preempted",
                 "tenant", "vft", "cost")

    def __init__(self, prompt: List[int], max_new: int, temperature: float,
                 seed: int, deadline: Optional[float] = None,
                 request_id: Optional[str] = None):
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.event = threading.Event()
        self.result: Optional[List[int]] = None
        self.error: Optional[BaseException] = None
        self.enqueued = time.perf_counter()
        self.deadline = deadline   # absolute perf_counter time, or None
        self.abandoned = False     # client gave up waiting
        self.request_id = request_id       # X-Request-Id (ISSUE-8)
        self.t_installed: Optional[float] = None  # slot-install stamp
        self.t_done: Optional[float] = None       # decode-complete stamp
        self.prefix_matched = 0            # radix-cache tokens reused
        self.drafted = 0                   # speculative tokens proposed
        self.accepted = 0                  # speculative tokens accepted
        # disaggregated serving (ISSUE-14)
        self.export = False                # resolve at prefill completion
        self.export_result: Optional[PageExport] = None
        self.import_pages: Optional[PageExport] = None  # shipped-in lane
        self.stream = None                 # per-token queue (SSE leg)
        self.session_id: Optional[str] = None
        self.t_first: Optional[float] = None  # first-committed-token stamp
        # overload survival (ISSUE-15)
        self.priority = "interactive"      # admission class
        self.rank = 0                      # PRIORITY_RANK[priority]
        self.swap_key: Optional[str] = None   # SwapStore key while queued
        self.swap_restore = False          # import_pages came from swap
        self.swap_error: Optional[str] = None  # typed restore failure
        self.stream_pushed = 0             # tokens already streamed
        self.preempted = 0                 # times this lane was preempted
        # multi-tenant traffic shaping (ISSUE-16)
        self.tenant = DEFAULT_TENANT       # normalized tenant name
        self.vft = 0.0                     # WFQ virtual finish time
        self.cost = self.max_new + len(self.prompt)  # token cost charged


class _Slot:
    __slots__ = ("req", "pos", "fed", "generated",
                 "table", "owned", "shared", "inserted")

    def __init__(self):
        self.req: Optional[_LMRequest] = None
        self.pos = 0          # next cache position to write
        self.fed = 0          # prompt tokens already fed (prefill cursor)
        self.generated: List[int] = []
        # paged-KV bookkeeping (kv="paged" only)
        self.table: Optional[np.ndarray] = None   # [max_pages] int32 row
        self.owned: List[int] = []    # pages this lane allocated
        self.shared: List[int] = []   # prefix pages reused from the tree
        self.inserted = False         # prompt pages registered in the tree

    @property
    def active(self) -> bool:
        return self.req is not None


class ContinuousLMServer:
    """Slot-based continuous decode over one TransformerLM.

    `generate(prompt_ids, max_new_tokens)` is thread-safe and blocks
    until the request's sequence is complete; any number of requests
    share the device via the slot pool.  `kv="paged"` (default) serves
    from the block-table paged pool with radix prefix reuse and chunked
    prefill; `kv="dense"` keeps the original per-slot dense cache (the
    bench baseline).
    """

    def __init__(self, cfg, params, slots: int = 4,
                 metrics: Optional[ServingMetrics] = None,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 kv: str = "paged", page_size: int = 16,
                 pages: Optional[int] = None, prefill_chunk: int = 8,
                 speculate: str = "off", draft_len: int = 4,
                 drafter=None, draft_model=None, ship: bool = False,
                 preempt: bool = False, swap_bytes: int = 64 << 20,
                 brownout=None, tenants=None,
                 paged_kernel: Optional[bool] = None,
                 hibernate_idle_s: Optional[float] = None,
                 state_dir: Optional[str] = None,
                 state_disk_bytes: int = 1 << 30,
                 swap_quantize: bool = True,
                 tracer: Optional[TraceRecorder] = None,
                 registry: Optional[MetricsRegistry] = None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_queue_depth is not None and max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1 or None, got "
                             f"{max_queue_depth}")
        if kv not in ("paged", "dense"):
            raise ValueError(f"kv must be 'paged' or 'dense', got {kv!r}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        if speculate not in ("off", "ngram", "model"):
            raise ValueError(f"speculate must be 'off', 'ngram' or "
                             f"'model', got {speculate!r}")
        if drafter is not None and speculate == "off":
            speculate = "custom"           # injected Drafter instance
        if speculate != "off" and kv != "paged":
            # typed at ADMISSION of the config, not a crash at dispatch:
            # speculative rollback is a pointer move ONLY on the paged
            # pool (docs/performance.md "The speculative decode cost
            # model"); the dense cache has no cheap rewind story
            raise ValueError(
                f"speculate={speculate!r} requires kv='paged' "
                f"(got kv={kv!r}): rollback rides the page tables")
        if speculate != "off" and draft_len < 1:
            raise ValueError(f"draft_len must be >= 1, got {draft_len}")
        if ship and kv != "paged":
            # same typed-at-construction rule as speculate: shipping is
            # page lists over the wire — the dense cache has none
            raise ValueError(
                f"ship=True requires kv='paged' (got kv={kv!r}): page "
                f"shipping moves block-table pages")
        if preempt and kv != "paged":
            raise ValueError(
                f"preempt=True requires kv='paged' (got kv={kv!r}): "
                f"preemption swaps block-table pages out to the host")
        if brownout and kv != "paged":
            raise ValueError(
                f"brownout requires kv='paged' (got kv={kv!r}): the "
                f"ladder's signals are the paged pool's pressure")
        if paged_kernel and kv != "paged":
            raise ValueError(
                f"paged_kernel=True requires kv='paged' (got kv={kv!r}):"
                f" the fused kernel walks the block tables")
        if hibernate_idle_s is not None:
            if kv != "paged":
                raise ValueError(
                    f"hibernate_idle_s requires kv='paged' (got "
                    f"kv={kv!r}): hibernation parks block-table pages "
                    f"on the tiered state store")
            if float(hibernate_idle_s) < 0:
                raise ValueError(
                    f"hibernate_idle_s must be >= 0, got "
                    f"{hibernate_idle_s}")
        if state_dir is not None and not (preempt
                                          or hibernate_idle_s is not None):
            raise ValueError(
                "state_dir names a disk tier nothing would write: it "
                "requires preempt=True or hibernate_idle_s (serve with "
                "-lm-preempt or -lm-hibernate-idle-s)")
        self.cfg = cfg
        self.params = params
        self.n_slots = int(slots)
        self.max_queue_depth = max_queue_depth
        self.default_deadline_s = default_deadline_s
        self.breaker = breaker
        self.kv = kv
        self.page_size = int(page_size)
        from deeplearning4j_tpu.parallel.generation import pages_per_seq

        self.max_pages = pages_per_seq(cfg, self.page_size)
        # `pages` = usable KV pages in the pool (the reserved null page
        # is on top).  Default: full worst-case capacity — every slot
        # can hold max_len, and prefix sharing turns into extra
        # effective capacity rather than a correctness question.
        self.kv_pages = (int(pages) if pages is not None
                         else self.n_slots * self.max_pages)
        if self.kv_pages < 1:
            raise ValueError(f"pages must be >= 1, got {self.kv_pages}")
        self.prefill_chunk = int(prefill_chunk)
        # None = auto (fused block-table kernel on TPU, gather oracle
        # elsewhere); resolved ONCE here so the ladder keys, stats and
        # every make_*_step call agree for the server's lifetime
        from deeplearning4j_tpu.parallel.paged_kernel import (
            resolve_paged_kernel,
        )

        self.paged_kernel = (resolve_paged_kernel(paged_kernel)
                             if kv == "paged" else False)
        self.speculate = speculate
        self.draft_len = int(draft_len)
        self._drafter = drafter            # built in _start_locked if None
        self._draft_model = draft_model    # optional (cfg, params) pair
        # the ONE wide program width: chunked prefill and speculative
        # verify share it ([last, d_1..d_k] needs draft_len+1 columns)
        if speculate != "off":
            self.spec_width = max(self.prefill_chunk, self.draft_len + 1)
        else:
            self.spec_width = 0
        self.metrics = metrics if metrics is not None else ServingMetrics()
        # observability plane (ISSUE-8): publish the LM pool's cells on
        # the server registry, trace every request, and install the
        # compile watcher before any program compiles
        self.tracer = tracer
        if registry is not None:
            self.metrics.register_into(registry, plane="lm")
        self._compile_watch = compile_watcher()
        if breaker is not None:
            breaker.add_listener(self.metrics.set_breaker_state)
            self.metrics.set_breaker_state(breaker.state)
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._running = False
        self._accepting = True
        self._thread: Optional[threading.Thread] = None
        self._cache = None    # lazy: (k, v) device buffers
        self._step = None     # ONE dispatch entry point (tests stub it)
        self._decode_step = None
        self._chunk_step = None
        self._copy = None
        self._pool: Optional[PagePool] = None
        self._tree: Optional[RadixPrefixCache] = None
        self._pending_cow: List[Dict] = []
        # disaggregation plane (ISSUE-14): page export/import programs,
        # shipments awaiting their device install, and the sticky-session
        # LRU (session_id -> last-seen tick) behind session_affinity_hits
        self.ship = bool(ship)
        self._gather = None
        self._install = None
        self._pending_install: List[Dict] = []
        # overload-survival plane (ISSUE-15): priority preemption with
        # host swap-out, and the brownout degradation ladder.  All of
        # it is worker-thread state mutated under self._cond (the same
        # single-mutator discipline as the page pool).
        self.preempt = bool(preempt)
        # tiered state hierarchy (ISSUE-19): ONE store serves both the
        # preemption swap plane (process-local "swap-<n>" keys) and the
        # hibernation plane (content-addressed "hib-<digest>" keys).
        # With a state_dir the host LRU tier spills to a checksummed
        # disk tier, so idle-session capacity is bounded by disk.
        self.hibernate_idle_s = (float(hibernate_idle_s)
                                 if hibernate_idle_s is not None else None)
        self.hibernate = self.hibernate_idle_s is not None
        self.swap_quantize = bool(swap_quantize)
        self.state_dir = str(state_dir) if state_dir is not None else None
        if self.preempt or self.hibernate:
            self._swap = TieredStateStore(
                int(swap_bytes), disk_dir=self.state_dir,
                disk_bytes=int(state_disk_bytes))
            if self.state_dir is not None:
                # a crashed predecessor's process-local swap keys can
                # never restore in THIS process — GC them (counted);
                # hibernated prefixes are content-addressed and stay
                # valid across restarts, so they survive untouched
                self._swap.gc("swap-")
        else:
            self._swap = None
        self._swap_seq = 0
        # idle-session tracking for hibernation: session_id -> the full
        # committed token sequence + last-activity stamp, LRU-bounded.
        # Worker-thread state like the slots (finish-fold writes it,
        # the admit-round sweep drains it).
        self._hib_sessions: "collections.OrderedDict[str, Dict]" = (
            collections.OrderedDict())
        if brownout is None or brownout is False:
            self._pressure = None
        elif isinstance(brownout, PressureConfig):
            self._pressure = BrownoutLadder(brownout)
        else:
            self._pressure = BrownoutLadder()
        # multi-tenant traffic shaping (ISSUE-16): None = tenancy off
        # (zero behavioral change); a registry/dict/JSON turns on the
        # quota meter, WFQ queue ordering and SLO-aware victim
        # selection.  Meter charges and WFQ stamps happen under
        # self._cond like every other admission mutation.
        self.tenants = TenantRegistry.coerce(tenants)
        # observed cadence of pressure-ladder updates (EWMA seconds):
        # the Retry-After base for the L4 shed and the quota 429 —
        # down_dwell calm updates at this cadence is the ladder's real
        # exit timescale (ISSUE-16 satellite fix)
        self._pressure_tick_s = 0.05
        self._pressure_stamp: Optional[float] = None
        self._sessions: "collections.OrderedDict[str, int]" = (
            collections.OrderedDict())
        self._session_capacity = 1024
        self._warm_req: Optional[threading.Event] = None
        self._slots = [_Slot() for _ in range(self.n_slots)]
        self._steps = 0

    # ---- client side ------------------------------------------------------

    def _required_pages(self, plen: int, max_new: int) -> int:
        """Pages one lane needs: positions written = plen + max_new - 1
        (the final sampled token is returned, never fed)."""
        return -(-(plen + max_new - 1) // self.page_size)

    def validate(self, prompt_ids, max_new_tokens: int) -> List[int]:
        """`validate_request` against this server's config, plus the
        paged pool's hard capacity: a request that could never fit the
        whole pool is the client's error, not an overload."""
        ids = validate_request(self.cfg, prompt_ids, max_new_tokens)
        if self.kv == "paged":
            need = self._required_pages(len(ids), int(max_new_tokens))
            if need > self.kv_pages:
                raise ValueError(
                    f"request needs {need} KV pages "
                    f"({len(ids)} prompt + {int(max_new_tokens)} new, "
                    f"page_size {self.page_size}) but the pool holds "
                    f"{self.kv_pages}; raise -lm-pages or shorten it")
        return ids

    def _retry_after_locked(self) -> float:
        lat = self.metrics.latency.summary()
        per_req = (lat.get("p50_ms", 100.0) or 100.0) / 1e3
        return max(0.1, per_req * (1 + len(self._queue) / self.n_slots))

    def _ladder_retry_after_locked(self) -> float:
        """Retry-After for pressure-driven refusals (the L4 shed, and
        the floor under a quota 429 while the ladder is up).  ISSUE-16
        satellite fix: derived from the ladder's REAL exit timescale —
        `down_dwell` consecutive calm updates at the observed update
        cadence (EWMA, stamped by `_update_pressure_locked`) — instead
        of the backlog constant, so clients back off proportionally to
        how long the ladder actually needs to step down.  Falls back to
        the backlog estimate when no ladder is installed."""
        if self._pressure is None:
            return self._retry_after_locked()
        dwell = self._pressure.config.down_dwell * self._pressure_tick_s
        return max(0.1, dwell)

    def _build_request(self, prompt_ids, max_new_tokens: int,
                       temperature: float, seed: int,
                       deadline_s: Optional[float],
                       request_id: Optional[str],
                       session_id: Optional[str] = None,
                       export: bool = False,
                       priority: Optional[str] = None,
                       tenant: Optional[str] = None) -> _LMRequest:
        """Validate + construct one queue item — THE shared front half of
        `generate`/`generate_stream`/`prefill_export`/`admit_with_pages`.
        Export lanes are budgeted for their prefill pages only (they
        never decode here); everything else pays the full page budget
        via the ONE shared `validate()` contract."""
        if export:
            ids = validate_request(self.cfg, prompt_ids, max_new_tokens)
            if (self.kv == "paged"
                    and -(-len(ids) // self.page_size) > self.kv_pages):
                raise ValueError(
                    f"prompt needs {-(-len(ids) // self.page_size)} "
                    f"prefill pages (page_size {self.page_size}) but "
                    f"the pool holds {self.kv_pages}; raise -lm-pages "
                    f"or shorten it")
        else:
            ids = self.validate(prompt_ids, max_new_tokens)
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        # fold into int32 range (the device-side PRNGKey seed dtype) so a
        # huge client seed cannot overflow the worker's seed vector
        seed = int(seed) & 0x7FFFFFFF
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        if request_id is None and self.tracer is not None:
            request_id = new_request_id()
        req = _LMRequest(ids, int(max_new_tokens), float(temperature),
                         seed, request_id=request_id)
        if deadline_s is not None:
            req.deadline = req.enqueued + float(deadline_s)
        req.session_id = (str(session_id) if session_id is not None
                          else None)
        req.export = bool(export)
        req.priority = normalize_priority(priority)
        req.rank = PRIORITY_RANK[req.priority]
        # tenant validation mirrors the priority gate: None -> the
        # built-in default tenant, unknown -> ValueError (the front's
        # 400).  Without a registry any explicit non-default tenant is
        # unknown by definition.
        if self.tenants is not None:
            req.tenant = self.tenants.normalize(tenant)
        elif tenant is not None and str(tenant) != DEFAULT_TENANT:
            raise ValueError(
                f"unknown tenant {str(tenant)!r}: no tenant registry "
                f"is installed (serve -tenants, or "
                f"ContinuousLMServer(tenants=...))")
        return req

    def _enqueue(self, req: _LMRequest) -> None:
        """Admission under the pool lock: the shared gate, worker start,
        priority-ordered queue insert, and sticky-session accounting.
        The brownout ladder's last rung fires here: at level 4 a
        best_effort admission is refused with 503 + Retry-After BEFORE
        the shared gate's queue bound, so interactive (and batch)
        traffic keeps the whole queue bound to itself while the pool
        recovers.  A draining/stopped server is NOT accepting at all —
        that outranks the shed, so clients get the typed
        draining/unavailable error and fail over instead of retrying a
        pool that will never admit again.

        The tenant quota gate (ISSUE-16) fires FIRST among the
        accepting-state refusals: an over-quota tenant's 429s are the
        CLIENT's budget, evaluated before the server-capacity shed and
        the shared gate, so a flooding tenant's refusals never consume
        the queue bound (and never dodge the meter by arriving while
        the ladder is shedding)."""
        with self._cond:
            if self._accepting and self.tenants is not None:
                try:
                    self.tenants.meter.charge(req.tenant, req.cost)
                except TenantQuotaError as e:
                    self.metrics.record_rejected()
                    self.metrics.record_class("rejected", req.priority)
                    self.metrics.record_tenant("rejected", req.tenant)
                    self.metrics.record_tenant("throttled", req.tenant)
                    # while the ladder is up, the bucket-refill retry is
                    # floored at the ladder's exit timescale: tokens
                    # refilling sooner than the pool recovers would
                    # invite the flood straight back (satellite fix)
                    if (self._pressure is not None
                            and self._pressure.level > 0):
                        e.retry_after_s = max(
                            e.retry_after_s,
                            self._ladder_retry_after_locked())
                    raise
            if (self._accepting
                    and self._pressure is not None
                    and self._pressure.level >= 4
                    and req.rank >= RANK_BEST_EFFORT
                    and not (self.tenants is not None
                             and self.tenants.compliant(req.tenant)
                             and self.tenants.any_offender())):
                # tenant-aware shed (ISSUE-16): while a non-compliant
                # tenant exists, a COMPLIANT tenant's best_effort still
                # admits — the rung takes from the offender, never from
                # a tenant inside its quota and SLO.  Without tenancy
                # (or without an offender) the PR-15 global shed holds.
                self.metrics.record_rejected()
                self.metrics.record_class("rejected", req.priority)
                if self.tenants is not None:
                    self.metrics.record_tenant("rejected", req.tenant)
                self.metrics.record_brownout_shed()
                raise ServingOverloadError(
                    "brownout level 4: best_effort admission shed "
                    "while the KV pool recovers",
                    retry_after_s=self._ladder_retry_after_locked())
            try:
                check_admission(
                    accepting=self._accepting, breaker=self.breaker,
                    queue_depth=len(self._queue),
                    max_queue_depth=self.max_queue_depth,
                    metrics=self.metrics,
                    retry_after_s=self._retry_after_locked, what="LM")
            except ServingError:
                # the shared gate already counted the rejection; the
                # per-class ledger rides along (ISSUE-15)
                self.metrics.record_class("rejected", req.priority)
                if self.tenants is not None:
                    self.metrics.record_tenant("rejected", req.tenant)
                raise
            if not self._running:
                self._start_locked()
            if req.session_id is not None:
                self._note_session_locked(req.session_id)
            if self.tenants is not None:
                # WFQ stamp at admission: virtual finish time within
                # the tenant's weighted share (ISSUE-16).  Stamped once
                # — a preempted request re-inserts with its ORIGINAL
                # vft, the WFQ analog of keeping the enqueue stamp.
                req.vft = self.tenants.wfq.stamp(req.tenant, req.cost)
            self._queue_insert_locked(req)
            self.metrics.set_queue_depth(len(self._queue))
            self._cond.notify_all()

    def _queue_insert_locked(self, req: _LMRequest) -> None:
        """Priority-ordered insert: the queue is kept sorted by
        (rank, vft, enqueued) so `popleft` always yields the most
        important request, weighted-fairly across tenants within a
        class (ISSUE-16), oldest-first as the tie-break.  Without a
        tenant registry every vft is 0.0 and the key degenerates to the
        PR-15 (rank, enqueued) sort; with ONE tenant the WFQ virtual
        finish times are strictly increasing in arrival order, so one
        class × one tenant is exactly the historic FIFO (pinned by
        test).  A preempted request re-inserts with its ORIGINAL
        enqueue stamp AND original vft, so it lands ahead of later
        arrivals of its own class/tenant instead of restarting at the
        back.  O(queue) insert; the queue is bounded by
        `max_queue_depth`."""
        key = (req.rank, req.vft, req.enqueued)
        i = len(self._queue)
        while i > 0:
            prev = self._queue[i - 1]
            if (prev.rank, prev.vft, prev.enqueued) <= key:
                break
            i -= 1
        if i == len(self._queue):
            self._queue.append(req)
        else:
            self._queue.insert(i, req)

    def _note_session_locked(self, session_id: str) -> None:
        """Sticky-session accounting (ISSUE-14 satellite): a session_id
        this pool has served before is an affinity HIT — the router's
        session rendezvous (or a client pinning one replica) landed the
        conversation back on the pool holding its radix pages.  Bounded
        LRU; works identically behind a fleet front or a bare `serve`
        so clients write one payload shape against both."""
        hit = session_id in self._sessions
        if hit:
            self._sessions.move_to_end(session_id)
        else:
            self._sessions[session_id] = 1
            while len(self._sessions) > self._session_capacity:
                self._sessions.popitem(last=False)
        self.metrics.record_session(hit)

    def _cancel_request(self, req: _LMRequest, status: str) -> None:
        """Give up on an unresolved request (client timeout or stream
        disconnect).  Cancel rather than abandon (mirror of
        MicroBatcher.submit): a still-queued request is removed so
        retry-on-timeout clients cannot fill the pool with zombie
        decodes; one already in a slot is MARKED abandoned and the
        worker frees the slot at its next admit round (slot state is
        written by the worker thread ONLY — freeing it here would race
        the lock-free step-input build in `_drain_step`)."""
        now = time.perf_counter()
        with self._cond:
            try:
                self._queue.remove(req)
                self.metrics.set_queue_depth(len(self._queue))
                self.metrics.record_shed()
                self.metrics.record_class("shed", req.priority)
                if self.tenants is not None:
                    self.metrics.record_tenant("shed", req.tenant)
                self._drop_swap_locked(req)
            except ValueError:
                req.abandoned = True
                # a request the worker already RESOLVED needs no shed
                # here: a completed result was counted as a served
                # request at fold time, and a worker-shed error was
                # counted when it was shed; an in-slot request is
                # shed by the admitter when it frees the slot
            resolved_with_error = (req.event.is_set()
                                   and req.error is not None)
        if (req.deadline is not None and now >= req.deadline
                and not resolved_with_error):
            # count a deadline miss only when the server-side
            # deadline actually expired and the worker has not
            # already accounted it (mirror of MicroBatcher.submit)
            self.metrics.record_deadline_missed()
            self.metrics.record_class("deadline_missed", req.priority)
            if self.tenants is not None:
                self.metrics.record_tenant("deadline_missed", req.tenant)
        self._trace_request(req, time.perf_counter(), status)

    def _wait(self, req: _LMRequest,
              timeout: Optional[float]) -> List[int]:
        """Block until the request resolves; raises its error or the
        timeout as typed failures.  Returns `req.result`."""
        if not req.event.wait(timeout):
            self._cancel_request(req, "timeout")
            raise DeadlineExceededError(
                f"LM request timed out after {timeout}s")
        done = time.perf_counter()
        if req.error is not None:
            self._trace_request(req, done, "error")
            raise req.error
        self._trace_request(req, done, "ok")
        return req.result

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 timeout: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 request_id: Optional[str] = None,
                 session_id: Optional[str] = None,
                 priority: Optional[str] = None,
                 tenant: Optional[str] = None) -> List[int]:
        """prompt ids -> full sequence (prompt + generated), blocking.

        `timeout` bounds the client's wait; `deadline_s` (default
        `default_deadline_s`) rides the queue item so the admitter sheds
        the request once it expires instead of spending decode steps on
        a client that already gave up.  `request_id` names the request's
        trace (``X-Request-Id``); `session_id` feeds sticky-session
        affinity accounting.  `priority` (interactive/batch/best_effort,
        default interactive) orders admission and marks the lane's
        preemption class (docs/robustness.md "The degradation
        ladder").  `tenant` (default "default") names the registered
        tenant charged for the request — quota 429s, WFQ ordering, and
        SLO burn accounting key on it (ISSUE-16)."""
        req = self._build_request(prompt_ids, max_new_tokens, temperature,
                                  seed, deadline_s, request_id,
                                  session_id=session_id,
                                  priority=priority, tenant=tenant)
        self._enqueue(req)
        return self._wait(req, timeout)

    def generate_stream(self, prompt_ids, max_new_tokens: int,
                        temperature: float = 0.0, seed: int = 0,
                        timeout: Optional[float] = None,
                        deadline_s: Optional[float] = None,
                        request_id: Optional[str] = None,
                        session_id: Optional[str] = None,
                        priority: Optional[str] = None,
                        tenant: Optional[str] = None
                        ) -> Iterator[int]:
        """Streaming `generate`: admission happens HERE (typed errors
        raise before a single byte of response is committed), then the
        returned iterator yields each committed token as the worker
        folds it — a speculative round's multi-token commit is yielded
        token by token.  Closing the iterator mid-stream (the SSE
        client disconnected) abandons the request so its slot and pages
        free at the worker's next admit round instead of decoding for
        nobody.  The full sequence is `prompt + every yielded token`."""
        req = self._build_request(prompt_ids, max_new_tokens, temperature,
                                  seed, deadline_s, request_id,
                                  session_id=session_id,
                                  priority=priority, tenant=tenant)
        req.stream = _queue.SimpleQueue()
        self._enqueue(req)
        return self._stream_tokens(req, timeout)

    def _stream_tokens(self, req: _LMRequest,
                       timeout: Optional[float]) -> Iterator[int]:
        deadline = (None if timeout is None
                    else time.perf_counter() + float(timeout))
        cancelled = False
        try:
            while True:
                wait = 0.05
                if deadline is not None:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        cancelled = True
                        self._cancel_request(req, "timeout")
                        raise DeadlineExceededError(
                            f"LM stream timed out after {timeout}s")
                    wait = min(wait, remaining)
                try:
                    yield int(req.stream.get(timeout=wait))
                    continue
                except _queue.Empty:
                    pass
                if req.event.is_set():
                    # the worker resolved the request; tokens are pushed
                    # BEFORE the event is set (same thread), so one final
                    # drain empties the queue in order
                    while True:
                        try:
                            yield int(req.stream.get_nowait())
                        except _queue.Empty:
                            break
                    if req.error is not None:
                        self._trace_request(req, time.perf_counter(),
                                            "error")
                        raise req.error
                    self._trace_request(req, time.perf_counter(), "ok")
                    return
        finally:
            if not cancelled and not req.event.is_set():
                # consumer went away mid-stream (GeneratorExit from the
                # SSE handler, or an error in the client loop): abandon
                # so the slot and its pages stop decoding for nobody.
                # The timeout branch above already cancelled — a second
                # cancel would double-count the deadline miss and
                # record two traces for one request.
                self._cancel_request(req, "disconnect")

    # ---- disaggregation: KV page export / import (ISSUE-14) ---------------

    def _require_ship(self, what: str) -> None:
        if self.kv != "paged":
            raise ValueError(
                f"page {what} requires kv='paged': shipping moves "
                f"block-table pages (got kv={self.kv!r})")
        if not self.ship:
            raise ValueError(
                f"page {what} requested but the pool was started with "
                f"ship=False (serve with -lm-ship, or "
                f"ContinuousLMServer(ship=True))")

    def prefill_export(self, prompt_ids, max_new_tokens: int,
                       temperature: float = 0.0, seed: int = 0,
                       timeout: Optional[float] = None,
                       deadline_s: Optional[float] = None,
                       request_id: Optional[str] = None,
                       session_id: Optional[str] = None,
                       priority: Optional[str] = None,
                       tenant: Optional[str] = None) -> PageExport:
        """Prefill-worker half of disaggregation: run the prompt through
        normal admission (radix reuse, chunked prefill, CoW) but resolve
        at prefill completion with the lane's shippable state — prompt
        pages, block-table metadata, and the FIRST committed token (the
        last prompt token's logits produce it, so shipping without it
        would cost the decode worker a redundant dispatch).  The request
        contract (max_new within max_len etc.) is validated here so a
        doomed request fails on the prefill worker, before any bytes
        move."""
        self._require_ship("export")
        req = self._build_request(prompt_ids, max_new_tokens, temperature,
                                  seed, deadline_s, request_id,
                                  session_id=session_id, export=True,
                                  priority=priority, tenant=tenant)
        self._enqueue(req)
        self._wait(req, timeout)
        return req.export_result

    def admit_with_pages(self, export: PageExport,
                         timeout: Optional[float] = None,
                         deadline_s: Optional[float] = None,
                         request_id: Optional[str] = None) -> List[int]:
        """Decode-worker half: verify the shipment's geometry against
        this pool (`PageShipError` on any mismatch — the caller's
        recompute ladder), allocate the lane's full page budget, install
        the shipped pages in one batched dispatch, and join mid-flight
        exactly like a chunked-prefill completion.  Returns the full
        sequence, byte-identical to a locally-prefilled lane."""
        self._require_ship("import")
        check_compatible(export, self.cfg, self.page_size)
        if export.quantized and not self.swap_quantize:
            raise PageShipError(
                "shipment is int8-quantized but this pool runs "
                "swap_quantize=off: refusing a lossy install on an "
                "exact-bytes pool (recompute locally instead)")
        if len(export.committed) >= export.max_new:
            # the prefill worker's first sample already filled the whole
            # budget (max_new == 1): nothing to decode — answer without
            # occupying a slot or installing a page.  Still a served
            # request in EVERY ledger (plane, class, tenant) — the
            # fleet reconciliation asserts they agree (ISSUE-16)
            priority = normalize_priority(export.priority)
            tenant = (self.tenants.normalize(export.tenant)
                      if self.tenants is not None else DEFAULT_TENANT)
            with self._cond:
                if export.session_id is not None:
                    self._note_session_locked(export.session_id)
            self.metrics.record_request(0.0)
            self.metrics.record_first_token(0.0)
            self.metrics.record_class("requests", priority)
            if self.tenants is not None:
                self.metrics.record_tenant("requests", tenant)
            return (list(export.prompt)
                    + list(export.committed[:export.max_new]))
        req = self._build_request(export.prompt, export.max_new,
                                  export.temperature, export.seed,
                                  deadline_s, request_id,
                                  session_id=export.session_id,
                                  priority=export.priority,
                                  tenant=export.tenant)
        req.import_pages = export
        self._enqueue(req)
        return self._wait(req, timeout)

    def _trace_request(self, req: _LMRequest, done: float,
                       status: str) -> None:
        """The LM request's lifecycle trace: queue_wait (admission to
        slot install) then decode (install to completion), plus any XLA
        compiles that landed inside the decode window."""
        if self.tracer is None:
            return
        spans = []
        t_in = req.t_installed if req.t_installed is not None else done
        spans.append(span("queue_wait", req.enqueued, t_in))
        if req.t_installed is not None:
            t_done = req.t_done if req.t_done is not None else done
            spans.append(span(
                "decode", req.t_installed, t_done,
                prompt_tokens=len(req.prompt),
                generated=(len(req.result) - len(req.prompt)
                           if req.result else 0),
                prefix_matched=req.prefix_matched or None,
                drafted=req.drafted or None,
                accepted=(req.accepted if req.drafted else None),
                preempted=req.preempted or None,
                swap_error=req.swap_error))
            if self._compile_watch.any_since(req.t_installed):
                for c_end, c_dur, key in (self._compile_watch
                                          .events_between(req.t_installed,
                                                          t_done)):
                    spans.append(span("xla_compile", c_end - c_dur,
                                      c_end, program_key=key))
        self.tracer.record(trace(
            req.request_id or new_request_id(), "lm", spans,
            status=status, prompt_tokens=len(req.prompt),
            error=(str(req.error) if req.error is not None else None)))

    def warmup(self, timeout: Optional[float] = 600.0) -> int:
        """Start the worker and pre-compile every device program before
        traffic; returns the compiled-program count.  Without warmup
        each program compiles on its first dispatch (the decode step on
        the first request, the prefill-chunk step on the first
        full-chunk prompt, the CoW copy on the first mid-page prefix
        split) — the same lazy-until-warmup contract as
        `ServingEngine.warmup()`: after warmup, NO request can trigger
        an XLA compile, which is what the zero-recompile storm tests
        pin via jax.monitoring.

        The warm dispatches run on the WORKER's live cache (inactive
        lanes write only the reserved null page), not a throwaway copy:
        a pool sized to fill device memory must not transiently double
        during startup or a rolling swap."""
        with self._cond:
            if not self._running:
                self._start_locked()
            ev = self._warm_req
            if ev is None:
                ev = self._warm_req = threading.Event()
            self._cond.notify_all()
        if not ev.wait(timeout):
            # the warm never ran (dense mode never went idle, or the
            # device is wedged): report 0, not a count the zero-compile
            # contract would falsely promise
            return 0
        return self.compiled_programs()

    def _warm_programs(self) -> None:
        """Worker-side warm: one dispatch per program against the live
        cache.  Only called while every lane is idle — the paged step
        with n_feed=0 writes nothing but the null page, and the idle
        dense step's pos-0 write lands in lanes that restart at pos 0
        on admission anyway — so cache contents stay serviceable and no
        second pool is ever allocated."""
        if self._cache is None:
            self._reset_cache()
        zi = np.zeros((self.n_slots,), np.int32)
        zf = np.zeros((self.n_slots,), np.float32)
        if self.kv == "dense":
            with compile_scope("lm:dense"):
                _, k, v = self._step(self.params, *self._cache, zi, zi,
                                     zf, zi, zi)
            self._cache = (k, v)
            return
        table = np.zeros((self.n_slots, self.max_pages), np.int32)
        if self.speculate != "off":
            widths = [1, self.spec_width]
            for w in widths:
                tok = np.zeros((self.n_slots, w), np.int32)
                with compile_scope(f"lm:paged[w{w}]"):
                    out = self._step(self.params, *self._cache, table,
                                     zi, zi, zi, tok, zf, zi, zi)
                self._cache = (out[-2], out[-1])
            if hasattr(self._drafter, "warmup"):
                self._drafter.warmup()
        else:
            widths = [1] + ([self.prefill_chunk]
                            if self.prefill_chunk > 1 else [])
            for w in widths:
                tok = np.zeros((self.n_slots, w), np.int32)
                with compile_scope(f"lm:paged[w{w}]"):
                    _, k, v = self._step(self.params, *self._cache,
                                         table, zi, zi, tok, zf, zi, zi)
                self._cache = (k, v)
        with compile_scope("lm:page_copy"):
            k, v = self._copy(*self._cache, np.int32(0), np.int32(0))
        self._cache = (k, v)
        if self.ship or self.preempt or self.hibernate:
            # the shipping/swap/hibernate pair: a gather out of the live
            # pool (not donated — the row of nulls reads only the null
            # page) and an n=0 install whose every row lands on the
            # null page
            zrow = np.zeros((self.max_pages,), np.int32)
            with compile_scope("lm:page_gather"):
                self._gather(*self._cache, zrow)
            shape = (self.cfg.n_layers, self.max_pages, self.page_size,
                     self.cfg.n_heads, self.cfg.head_dim)
            zp = np.zeros(shape, np.dtype(self.cfg.dtype))
            with compile_scope("lm:page_install"):
                k, v = self._install(*self._cache, zp, zp, zrow,
                                     np.int32(0))
            self._cache = (k, v)

    def compiled_programs(self) -> int:
        if self.kv == "dense":
            return 1
        # page gather + batched install serve the shipping wire plane,
        # preemption swap-out/restore AND hibernate/resume — one
        # compiled pair for all three
        ship = 2 if (self.ship or self.preempt or self.hibernate) else 0
        if self.speculate != "off":
            # 1-wide decode + the shared prefill/verify wide program +
            # page copy, plus whatever the drafter runs on device
            drafter = (self._drafter.compiled_programs()
                       if self._drafter is not None
                       and hasattr(self._drafter, "compiled_programs")
                       else 0)
            return 3 + drafter + ship
        return 2 + (1 if self.prefill_chunk > 1 else 0) + ship

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self.metrics.set_queue_depth(0)
        for req in leftovers:
            self.metrics.record_shed()
            self.metrics.record_class("shed", req.priority)
            if self.tenants is not None:
                self.metrics.record_tenant("shed", req.tenant)
            req.error = ServingUnavailableError("LM server stopped")
            req.event.set()

    # ---- drain lifecycle --------------------------------------------------

    @property
    def accepting(self) -> bool:
        """False once draining — the /readyz signal."""
        with self._cond:
            return self._accepting

    def ready(self) -> bool:
        """Readiness for traffic: accepting admissions and the circuit
        breaker is not open (docs/robustness.md serving lifecycle)."""
        if not self.accepting:
            return False
        return self.breaker is None or self.breaker.state != "open"

    def begin_drain(self) -> None:
        """Stop admission: subsequent generates raise
        `ServingUnavailableError`; queued + in-slot work still decodes."""
        with self._cond:
            self._accepting = False
            self._cond.notify_all()

    def drain(self, grace_s: float = 5.0) -> bool:
        """Stop admission, wait up to `grace_s` for queued + in-slot
        requests to finish, then stop the worker.  Returns True when
        everything drained within the grace window."""
        self.begin_drain()
        deadline = time.perf_counter() + max(0.0, grace_s)
        while True:
            with self._cond:
                busy = bool(self._queue) or any(
                    s.active for s in self._slots)
            if not busy:
                break
            if time.perf_counter() >= deadline:
                break
            time.sleep(0.01)
        with self._cond:
            drained = not self._queue and not any(
                s.active for s in self._slots)
        self.stop()
        return drained

    def _kv_bytes(self) -> Dict:
        """Actual vs provisioned KV bytes — the honest memory column for
        the bench (a dense pool's provisioned bytes are paid whether or
        not any lane fills them; the paged pool's actual bytes follow
        the refcounted pages, radix-shared prefixes counted once)."""
        cfg = self.cfg
        per_tok = (2 * cfg.n_layers * cfg.n_heads * cfg.head_dim
                   * np.dtype(cfg.dtype).itemsize)
        if self.kv == "dense":
            provisioned = self.n_slots * cfg.max_len * per_tok
            active = per_tok * sum(s.pos for s in self._slots if s.active)
        else:
            provisioned = self.kv_pages * self.page_size * per_tok
            in_use = self._pool.in_use if self._pool is not None else 0
            active = in_use * self.page_size * per_tok
        return {"provisioned": int(provisioned), "active": int(active),
                "per_token": int(per_tok)}

    def stats(self) -> Dict:
        out = self.metrics.snapshot()
        with self._cond:
            out["slots"] = self.n_slots
            out["active_slots"] = sum(s.active for s in self._slots)
            out["queue_depth"] = len(self._queue)
            out["decode_steps"] = self._steps
            out["accepting"] = self._accepting
            out["kv_bytes"] = self._kv_bytes()
            kv = {"mode": self.kv}
            if self.kv == "paged":
                kv.update({
                    "page_size": self.page_size,
                    "pages": self.kv_pages,
                    "max_pages_per_seq": self.max_pages,
                    "prefill_chunk": self.prefill_chunk,
                    "pages_in_use": (self._pool.in_use
                                     if self._pool is not None else 0),
                    "pages_free": (self._pool.free
                                   if self._pool is not None
                                   else self.kv_pages),
                    "radix_nodes": (self._tree.nodes
                                    if self._tree is not None else 0),
                    "ship": self.ship,
                    "paged_kernel": self.paged_kernel})
            if self._sessions:
                out["sessions_tracked"] = len(self._sessions)
            out["kv"] = kv
            if self.preempt or self._pressure is not None:
                pres: Dict = {"preempt": self.preempt}
                if self._swap is not None:
                    pres["swap"] = self._swap.stats()
                if self._pressure is not None:
                    pres["brownout"] = self._pressure.stats()
                out["pressure"] = pres
            if self.hibernate and self._swap is not None:
                out["hibernation"] = {
                    "idle_s": self.hibernate_idle_s,
                    "quantize": self.swap_quantize,
                    "disk": self.state_dir,
                    "tracked_sessions": len(self._hib_sessions),
                    "store": self._swap.stats()}
            if self.tenants is not None:
                out["tenancy"] = self.tenants.stats()
            if self.speculate != "off":
                spec = {"mode": self.speculate,
                        "draft_len": self.draft_len,
                        "verify_width": self.spec_width}
                drafted = out.get("spec_drafted", 0)
                if drafted:
                    spec.update({
                        "drafted": drafted,
                        "accepted": out.get("spec_accepted", 0),
                        "accept_rate": out.get("spec_accept_rate", 0.0)})
                if out.get("decode_rounds"):
                    spec["tokens_per_decode_round"] = out.get(
                        "tokens_per_decode_round", 0.0)
                out["speculate"] = spec
        out["max_len"] = self.cfg.max_len
        out["compiled_programs"] = self.compiled_programs()
        # first-class compile accounting (ISSUE-8): XLA compiles the
        # watcher attributed to the LM pool's dispatch scopes
        out["compiles_total"] = compile_watcher().total(prefix="lm:")
        return out

    # ---- worker side ------------------------------------------------------

    def _reset_cache(self) -> None:
        """(Re)allocate the device KV buffers.  Needed after a FAILED
        dispatch too: the step donates the k/v buffers, so an exception
        mid-step leaves `self._cache` pointing at deleted buffers —
        without a rebuild the keep-serving path would fail every later
        request.  Host-side page state is reset separately
        (`_reset_pool_locked`) because it must happen BEFORE the next admit
        round, while the device rebuild may be deferred to dispatch."""
        if self.kv == "dense":
            from deeplearning4j_tpu.parallel.generation import (
                init_slot_cache,
            )

            cache = init_slot_cache(self.cfg, self.n_slots)
        else:
            from deeplearning4j_tpu.parallel.generation import (
                init_paged_cache,
            )

            cache = init_paged_cache(self.cfg, self.kv_pages + 1,
                                     self.page_size)
        self._cache = (cache["k"], cache["v"])

    def _reset_pool_locked(self) -> None:
        """Fresh allocator + radix tree + slot page bookkeeping.  Called
        at start and whenever the device pool's CONTENTS died (failed
        dispatch, worker stop): a radix entry pointing into a rebuilt
        pool would serve zeros as a cached prefix.  Caller holds
        ``self._cond`` (the ``*_locked`` contract — admission reads the
        pool/tree/CoW list under the same lock)."""
        if self.kv != "paged":
            return
        self._pool = PagePool(self.kv_pages + 1, self.page_size)
        self._tree = RadixPrefixCache(self._pool)
        self._pending_cow = []
        # shipments awaiting device install referenced pages (and
        # content) that died with the pool — their lanes restart or fail
        # with it, so the pending plane resets wholesale too
        self._pending_install = []
        for s in self._slots:
            s.table = None
            s.owned = []
            s.shared = []
            s.inserted = False
        if self._drafter is not None:
            # the drafter's lane state tracked lanes that no longer
            # exist; its own cache self-heals via the common-prefix
            # rewind, but the bookkeeping must not outlive the pool
            self._drafter.reset()
        if self._swap is not None:
            # swapped blobs are self-contained host copies and would
            # stay VALID across a device pool rebuild, but the reset
            # paths either fail every request that could restore them
            # (stop) or want one coherent story (failed dispatch):
            # clear, and let any surviving queued victim take the
            # recompute-from-prompt path — byte-identical either way.
            # HIBERNATED entries ("hib-") survive the reset: they are
            # content-addressed by prompt tokens and the KV they carry
            # is deterministic from those tokens, so they stay valid no
            # matter what happened to the device pool.
            self._swap.clear("swap-")
        self._hib_sessions.clear()
        self.metrics.set_pages(0, self.kv_pages, self.kv_pages)

    def _start_locked(self) -> None:
        if self._step is None:
            if self.kv == "dense":
                from deeplearning4j_tpu.parallel.generation import (
                    make_slot_step,
                )

                self._step = make_slot_step(self.cfg)
            else:
                from deeplearning4j_tpu.parallel.generation import (
                    make_page_copy,
                    make_paged_step,
                    make_spec_step,
                )

                total = self.kv_pages + 1
                self._decode_step = make_paged_step(
                    self.cfg, total, self.page_size, 1,
                    paged_kernel=self.paged_kernel)
                if self.speculate != "off":
                    # ONE wide program serves chunked prefill AND the
                    # speculative verify — the same chunked-feed ladder,
                    # widened to fit [last, d_1..d_draft_len]
                    self._chunk_step = make_spec_step(
                        self.cfg, total, self.page_size, self.spec_width,
                        paged_kernel=self.paged_kernel)
                else:
                    self._chunk_step = (make_paged_step(
                        self.cfg, total, self.page_size,
                        self.prefill_chunk,
                        paged_kernel=self.paged_kernel)
                        if self.prefill_chunk > 1 else None)
                self._copy = make_page_copy(self.cfg, total,
                                            self.page_size)
                if self.ship or self.preempt or self.hibernate:
                    from deeplearning4j_tpu.parallel.generation import (
                        make_page_gather,
                        make_page_install,
                    )

                    self._gather = make_page_gather(self.cfg, total,
                                                    self.page_size)
                    self._install = make_page_install(self.cfg, total,
                                                      self.page_size)
                if self.speculate != "off" and self._drafter is None:
                    from deeplearning4j_tpu.serving.draft import (
                        make_drafter,
                    )

                    self._drafter = make_drafter(
                        self.speculate, self.cfg, self.params,
                        self.n_slots, draft_model=self._draft_model)

                if self.speculate != "off":
                    def dispatch(params, k, v, table, pos, n_feed,
                                 n_draft, tokens, temperature, seeds,
                                 counts):
                        # speculative signature: every dispatch carries
                        # n_draft and returns per-lane accepted counts
                        # (zeros on the 1-wide plain-decode program)
                        if tokens.shape[1] == 1:
                            nxt, k, v = self._decode_step(
                                params, k, v, table, pos, n_feed,
                                tokens, temperature, seeds, counts)
                            return nxt, np.zeros(
                                (self.n_slots,), np.int32), k, v
                        return self._chunk_step(
                            params, k, v, table, pos, n_feed, n_draft,
                            tokens, temperature, seeds, counts)
                else:
                    def dispatch(params, k, v, table, pos, n_feed,
                                 tokens, temperature, seeds, counts):
                        # ONE entry point for every paged dispatch
                        # (decode and prefill-chunk widths) so
                        # fault-injection tests that stub `self._step`
                        # intercept them all
                        fn = (self._decode_step if tokens.shape[1] == 1
                              else self._chunk_step)
                        return fn(params, k, v, table, pos, n_feed,
                                  tokens, temperature, seeds, counts)

                self._step = dispatch
            self._reset_pool_locked()
            self._reset_cache()
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="lm-decode")
        self._thread.start()

    # ---- paged admission --------------------------------------------------

    def _free_slot_pages(self, slot: _Slot) -> None:
        """Refcount-release everything a lane held: its own pages drop
        to 0 and return to the free list unless the radix tree kept
        them; shared prefix pages drop back to their other holders."""
        if self.kv != "paged" or self._pool is None:
            return
        if slot.owned:
            self._pool.release(slot.owned)
        if slot.shared:
            self._pool.release(slot.shared)
        slot.owned = []
        slot.shared = []
        slot.table = None
        slot.inserted = False

    def _resolve_swap_locked(self, req: _LMRequest) -> None:
        """Turn a requeued victim's swap key into an installable
        shipment.  A key whose blob was evicted (`SwapEvictedError`) or
        fails the wire frame's SHA-256/geometry checks (`PageShipError`)
        is the typed swap-loss path: the loss is counted, stamped on
        the victim request's trace, and the lane falls back to
        recomputing from its prompt — deterministic decode makes the
        recomputed tokens byte-identical, so the CLIENT never sees the
        error, only the accounting and the trace do."""
        key, req.swap_key = req.swap_key, None
        try:
            blob = self._swap.take(key)
        except SwapEvictedError as e:
            self.metrics.record_swap_lost("evicted")
            req.swap_error = f"{type(e).__name__}: {e}"
            return
        try:
            ex = deserialize_export(blob)
            check_compatible(ex, self.cfg, self.page_size,
                             mid_decode=True)
            if ex.quantized and not self.swap_quantize:
                raise PageShipError(
                    "swapped frame is int8-quantized but this pool "
                    "runs swap_quantize=off: refusing a lossy restore "
                    "on an exact-bytes pool")
        except PageShipError as e:
            self.metrics.record_swap_lost("corrupt")
            req.swap_error = f"{type(e).__name__}: {e}"
            return
        req.import_pages = ex
        req.swap_restore = True

    def _plan_admission_paged(self, req: _LMRequest):
        """Radix-match + allocate for one queued request.  Returns the
        install plan, or None when the pool (after eviction) cannot
        supply the fresh pages — the request stays queued, FIFO.  Every
        page the plan references is already retained."""
        plen = len(req.prompt)
        if req.swap_key is not None and self._swap is not None:
            # a preempted lane coming back: resolve its host swap into
            # the same install plane a shipped lane uses (or fall back
            # to recompute-from-prompt when the state is gone/corrupt)
            self._resolve_swap_locked(req)
        if req.import_pages is not None:
            # shipped-in lane (ISSUE-14): FULL prefix pages this pool's
            # radix tree already holds are reused instead of installing
            # duplicate shipped copies — a sticky session's next turn
            # re-ships its growing prompt, and without this the decode
            # pool would pay O(turns x prompt) duplicate pages for a
            # prefix it already caches.  No plen-1 cap (unlike normal
            # admission): the first token arrived committed, nothing
            # re-feeds.  Partial (CoW) matches are skipped — the
            # shipped copy of a mid-page divergence is cheaper than a
            # device copy + overwrite.
            total_pages = self._required_pages(plen, req.max_new)
            full, partial = self._tree.match(req.prompt)
            if partial is not None:
                self._pool.release([partial[0]])
            need = total_pages - len(full)
            if self._pool.free < need:
                if self._pool.free + self._tree.evictable() >= need:
                    self._tree.evict(need)
            fresh = self._pool.alloc(need)
            if fresh is None:
                if full:
                    self._pool.release(full)
                return None
            return {"full": full, "partial": None, "fresh": fresh,
                    "matched": len(full) * self.page_size,
                    "total_pages": total_pages}
        # export lanes (prefill-only) budget just their prompt pages —
        # decode happens on whatever pool the shipment lands in
        total_pages = (-(-plen // self.page_size) if req.export
                       else self._required_pages(plen, req.max_new))
        # cap reuse at plen-1: the LAST prompt token is always re-fed —
        # its logits are what the first sampled token comes from
        full, partial = self._tree.match(req.prompt[:plen - 1])
        if len(full) > total_pages:     # cannot happen (cap above), but
            raise AssertionError("radix match exceeded the page budget")
        resume = None
        if self.hibernate and self._swap is not None:
            # a hibernated session's prompt prefix may cover MORE pages
            # than the tree still holds: probe the tiered store for the
            # longest stored whole-page prefix beyond the radix match
            resume = self._probe_hibernated_locked(req, len(full))
        if resume is not None and partial is not None:
            # the resumed frame extends past the divergence page: the
            # CoW copy would duplicate content the frame carries exactly
            self._pool.release([partial[0]])
            partial = None
        need = total_pages - len(full)
        if self._pool.free < need:
            # evict ONLY when eviction can actually cover the shortfall:
            # wiping cached prefixes while still admitting nothing would
            # destroy the hit rate for zero capacity gained (the pages
            # this plan already retained are pinned, so they never count
            # as evictable against themselves)
            if self._pool.free + self._tree.evictable() >= need:
                self._tree.evict(need)
        fresh = self._pool.alloc(need)
        if fresh is None:
            if full:
                self._pool.release(full)
            if partial is not None:
                self._pool.release([partial[0]])
            if resume is not None:
                # un-consume the blob: the session's state must survive
                # until the pool can actually seat the lane
                self._swap.put(resume["key"], resume["blob"])
            return None
        if resume is not None:
            return {"full": full, "partial": None, "fresh": fresh,
                    "matched": int(resume["n_hib"]) * self.page_size,
                    "total_pages": total_pages, "resume": resume}
        matched = len(full) * self.page_size + (partial[1]
                                                if partial else 0)
        return {"full": full, "partial": partial, "fresh": fresh,
                "matched": matched, "total_pages": total_pages}

    def _probe_hibernated_locked(self, req: _LMRequest,
                                 have: int) -> Optional[Dict]:
        """Longest hibernated whole-page prompt prefix beyond the
        `have` pages the radix tree already matched -> an exact
        (dequantized) `PageExport` ready for the pending-install plane,
        or None.  Probes deepest-first by content digest, so the cost
        on a miss is one digest per candidate depth, no I/O.  A stored
        blob that is gone or fails its integrity/geometry/quantization
        checks is the typed resume-loss path: counted on the hibernate
        ledger, stamped on THIS request's trace, and the probe keeps
        descending — shallower prefixes may still be intact."""
        plen = len(req.prompt)
        for k in range((plen - 1) // self.page_size, have, -1):
            covered = [int(t) for t in req.prompt[:k * self.page_size]]
            key = prefix_key(covered)
            if key not in self._swap:
                continue
            try:
                blob = self._swap.take(key)
            except SwapEvictedError as e:
                self.metrics.record_hibernate_lost("evicted")
                req.swap_error = f"{type(e).__name__}: {e}"
                continue
            except PageShipError as e:
                self.metrics.record_hibernate_lost("corrupt")
                req.swap_error = f"{type(e).__name__}: {e}"
                continue
            try:
                ex = deserialize_export(blob)
                check_compatible(ex, self.cfg, self.page_size,
                                 prefix=True)
                if ex.quantized and not self.swap_quantize:
                    raise PageShipError(
                        "hibernated frame is int8-quantized but this "
                        "pool runs swap_quantize=off: refusing a lossy "
                        "resume on an exact-bytes pool")
                if ex.prompt != covered:
                    raise PageShipError(
                        "hibernated frame's tokens diverge from its "
                        "digest key: refusing to install foreign KV")
            except PageShipError as e:
                self.metrics.record_hibernate_lost("corrupt")
                req.swap_error = f"{type(e).__name__}: {e}"
                continue
            nbytes = ex.nbytes()
            exact = ex.exact_nbytes()
            return {"ex": ex.dequantized(), "n_hib": k, "nbytes": nbytes,
                    "exact_nbytes": exact, "key": key, "blob": blob}
        return None

    def _install_paged_locked(self, slot: _Slot, req: _LMRequest,
                              plan) -> None:
        """Bind one admitted request to a lane.  Caller holds
        ``self._cond`` (the ``*_locked`` contract): the pending-CoW
        append below races the worker's swap in `_drain_step`
        otherwise."""
        slot.req = req
        req.t_installed = time.perf_counter()
        req.prefix_matched = plan["matched"]
        slot.generated = []
        slot.fed = plan["matched"]
        slot.pos = plan["matched"]
        slot.shared = list(plan["full"])
        slot.owned = list(plan["fresh"])
        slot.inserted = False
        row = np.zeros((self.max_pages,), np.int32)
        n_full = len(plan["full"])
        row[:n_full] = plan["full"]
        row[n_full:plan["total_pages"]] = plan["fresh"]
        slot.table = row
        if req.import_pages is not None:
            # shipped-in lane: arrive mid-flight exactly where the
            # prefill worker left it — prompt fully fed, first token(s)
            # committed, next write lands at pos (possibly mid-page,
            # overwriting shipped garbage past the divergence).  The
            # device install rides the pending plane below, executed
            # BEFORE any feed of this round; the prompt's full pages
            # enter the local radix tree now so the next shared-prefix
            # admission (this session's next turn) reuses them.
            ex = req.import_pages
            # at-rest/wire bytes BEFORE dequantizing — the ledger must
            # read what actually moved through the store or the wire
            wire_nbytes = ex.nbytes()
            ex = ex.dequantized()   # identity on exact frames
            slot.fed = len(req.prompt)
            slot.pos = int(ex.pos)
            slot.generated = list(ex.committed)
            n_ship = ex.n_pages
            mp = self.max_pages
            shape = (self.cfg.n_layers, mp, self.page_size,
                     self.cfg.n_heads, self.cfg.head_dim)
            pk = np.zeros(shape, np.dtype(self.cfg.dtype))
            pv = np.zeros(shape, np.dtype(self.cfg.dtype))
            pk[:, :n_ship] = ex.pages_k
            pv[:, :n_ship] = ex.pages_v
            # radix-matched prefix pages are NOT re-installed: their
            # rows in the install target the null page, so the shared
            # pages (other lanes may be reading them) are never
            # rewritten — shipped content for them is byte-identical
            # by the radix invariant anyway
            irow = row.copy()
            irow[:len(plan["full"])] = 0
            self._pending_install.append(
                {"pk": pk, "pv": pv, "row": irow, "n": n_ship,
                 "nbytes": wire_nbytes, "swap": req.swap_restore})
            self.metrics.record_prefix_query(plan["matched"])
            n_full_prompt = len(req.prompt) // self.page_size
            if n_full_prompt:
                slot.inserted = True
                self._tree.insert(
                    req.prompt[:n_full_prompt * self.page_size],
                    [int(p) for p in row[:n_full_prompt]])
            # the shipment's committed tokens ARE this lane's first
            # tokens: stamp TTFT at install (the prefill worker already
            # paid the first-token latency; this pool's number says how
            # long the shipment sat in its queue).  A PREEMPTED lane
            # restoring from swap already stamped its true first token
            # before the preemption — never re-stamp it.
            if req.t_first is None:
                req.t_first = req.t_installed
                self.metrics.record_first_token(
                    req.t_first - req.enqueued)
            return
        res = plan.get("resume")
        if res is not None:
            # hibernated-session resume (ISSUE-19): the store held KV
            # for a longer prompt prefix than the radix tree — install
            # the resumed pages through the same pending plane a
            # shipment uses, then register them in the tree so the
            # session's NEXT turn (or a concurrent shared-prefix
            # admission) reuses them without touching disk.  Rows the
            # tree already served stay zeroed (null page): shared pages
            # other lanes may be reading are never rewritten.
            ex = res["ex"]
            n_hib = int(res["n_hib"])
            mp = self.max_pages
            shape = (self.cfg.n_layers, mp, self.page_size,
                     self.cfg.n_heads, self.cfg.head_dim)
            pk = np.zeros(shape, np.dtype(self.cfg.dtype))
            pv = np.zeros(shape, np.dtype(self.cfg.dtype))
            pk[:, :n_hib] = ex.pages_k
            pv[:, :n_hib] = ex.pages_v
            irow = row.copy()
            irow[:n_full] = 0
            irow[n_hib:] = 0
            self._pending_install.append(
                {"pk": pk, "pv": pv, "row": irow, "n": n_hib,
                 "nbytes": res["nbytes"],
                 "exact_nbytes": res["exact_nbytes"],
                 "pages": n_hib, "hibernate": True})
            self._tree.insert(req.prompt[:n_hib * self.page_size],
                              [int(p) for p in row[:n_hib]])
        elif plan["partial"] is not None:
            # copy-on-write: the divergence page's matched tokens are
            # valid KV; copy it into this lane's first fresh page and
            # overwrite from the divergence offset.  The source stays
            # retained until the device copy lands (eviction must not
            # recycle it first); _drain_step executes and releases.
            src, _ = plan["partial"]
            self._pending_cow.append({"src": int(src),
                                      "dst": int(plan["fresh"][0])})
        self.metrics.record_prefix_query(plan["matched"])

    def _admit_locked(self) -> None:
        """Queued prompts join free slots.  Doomed work is shed first:
        an abandoned request's slot (and pages) is freed, and an expired
        or abandoned queue item must never occupy a slot.  The queue
        sweep is one rebuild pass — per-item `deque.remove` would be
        O(n^2) under exactly the overload storm it exists for.

        Paged admission is priority-then-FIFO (ISSUE-15): the queue is
        kept sorted by (rank, enqueued), so the head is the most
        important oldest request.  When the head's pages cannot be
        supplied even after eviction, admission PREEMPTS the
        lowest-priority active lane (strictly outranked by the head;
        its state swaps out to the host store) before giving up and
        waiting — so a latency class never starves behind a long
        low-value lane.  With preemption off (or no outranked victim)
        the historic head-of-line wait is unchanged: admission stops
        rather than letting smaller later requests starve the head."""
        for slot in self._slots:
            if slot.active and slot.req.abandoned:
                self.metrics.record_shed()
                self.metrics.record_class("shed", slot.req.priority)
                if self.tenants is not None:
                    self.metrics.record_tenant("shed", slot.req.tenant)
                self._free_slot_pages(slot)
                slot.req = None
        now = time.perf_counter()
        kept, shed = collections.deque(), 0
        for req in self._queue:
            if req.abandoned:
                shed += 1
                self.metrics.record_class("shed", req.priority)
                if self.tenants is not None:
                    self.metrics.record_tenant("shed", req.tenant)
                self._drop_swap_locked(req)
            elif req.deadline is not None and now >= req.deadline:
                shed += 1
                self.metrics.record_deadline_missed()
                self.metrics.record_class("shed", req.priority)
                self.metrics.record_class("deadline_missed",
                                          req.priority)
                if self.tenants is not None:
                    self.metrics.record_tenant("shed", req.tenant)
                    self.metrics.record_tenant("deadline_missed",
                                               req.tenant)
                self._drop_swap_locked(req)
                req.error = DeadlineExceededError(
                    f"deadline exceeded after {now - req.enqueued:.3f}s "
                    f"in LM queue; shed before decode")
                req.event.set()
            else:
                kept.append(req)
        if shed:
            self._queue = kept
            self.metrics.record_shed(shed)
        self._update_pressure_locked()
        self._hibernate_idle_locked(now)
        for slot in self._slots:
            if not self._queue:
                break
            if slot.active:
                continue
            if self.kv == "paged":
                head = self._queue[0]
                plan = self._plan_admission_paged(head)
                while plan is None and self._preempt_one_locked(head):
                    plan = self._plan_admission_paged(head)
                if plan is None:
                    break              # head-of-line waits for pages
                req = self._queue.popleft()
                if self.tenants is not None:
                    self.tenants.wfq.advance(req.vft)
                self._install_paged_locked(slot, req, plan)
            else:
                slot.req = self._queue.popleft()
                if self.tenants is not None:
                    self.tenants.wfq.advance(slot.req.vft)
                slot.req.t_installed = time.perf_counter()
                slot.pos = 0
                slot.fed = 0
                slot.generated = []
        self.metrics.set_queue_depth(len(self._queue))
        if self.kv == "paged" and self._pool is not None:
            self.metrics.set_pages(self._pool.in_use, self._pool.free,
                                   self.kv_pages)

    def _hibernate_idle_locked(self, now: float) -> None:
        """Park idle sticky sessions' cached pages on the tiered state
        store (ISSUE-19).  A session is idle once `hibernate_idle_s`
        has passed since its last completion; its radix-cached chain is
        gathered in one fixed-shape dispatch, (optionally) quantized,
        serialized through the integrity-checked wire frame, stored
        under its content digest, and the tree's hold on the pages is
        dropped — device capacity frees while the session's KV rests
        on host or disk, resumable hours later byte-identically (the
        store outlives pool resets AND, with a state_dir, the
        process)."""
        if (not self.hibernate or self._swap is None
                or self._gather is None or self._cache is None
                or self._tree is None or not self._hib_sessions):
            return
        idle = [sid for sid, meta in self._hib_sessions.items()
                if now - meta["t"] >= self.hibernate_idle_s]
        for sid in idle:
            meta = self._hib_sessions.pop(sid)
            tokens = meta["tokens"]
            # only positions BEFORE the final sampled token have KV
            # (the last sample is returned, never fed) — park exactly
            # the fully-written pages
            n_full = (len(tokens) - 1) // self.page_size
            if n_full == 0:
                continue
            covered = [int(t) for t in tokens[:n_full * self.page_size]]
            full, partial = self._tree.match(covered)
            if partial is not None:
                self._pool.release([partial[0]])
            if len(full) != n_full:
                # the tree already evicted part of the chain under
                # pressure: nothing complete to park — whatever prefix
                # remains keeps serving radix hits
                if full:
                    self._pool.release(full)
                continue
            row = np.zeros((self.max_pages,), np.int32)
            row[:n_full] = full
            with compile_scope("lm:page_gather"):
                pk, pv = self._gather(*self._cache, row)
            pk = np.asarray(pk)[:, :n_full]
            pv = np.asarray(pv)[:, :n_full]
            ex = PageExport(
                prompt=covered, max_new=1, temperature=0.0, seed=0,
                committed=[], pos=n_full * self.page_size,
                page_size=self.page_size, pages_k=pk, pages_v=pv,
                model=model_signature(self.cfg, self.page_size),
                session_id=sid)
            exact = ex.exact_nbytes()
            if self.swap_quantize:
                ex = quantize_export(ex)
            blob = serialize_export(ex)
            stored = self._swap.put(prefix_key(covered), blob)
            self._pool.release(full)
            if stored is None:
                # the blob alone exceeds the host cap: nothing was
                # parked and nothing was lost — the pages stay in the
                # radix tree and keep serving hits from device
                continue
            for lost in stored:
                # hibernated prefixes pushed off the capped tiers are
                # counted NOW — a resume probe treats a missing key as
                # a plain miss, so eviction time is the only chance
                # (swap-keyed victims stay counted at restore, as ever)
                if lost.startswith("hib-"):
                    self.metrics.record_hibernate_lost("evicted")
            self.metrics.record_hibernate("out", n_full, ex.nbytes(),
                                          exact)
            self._tree.forget(covered)

    def _drop_swap_locked(self, req: _LMRequest) -> None:
        """A shed/abandoned queue item releases its host swap bytes."""
        if req.swap_key is not None and self._swap is not None:
            self._swap.discard(req.swap_key)
            req.swap_key = None

    def _update_pressure_locked(self) -> None:
        """One brownout-ladder reading per admission round: pool
        pages-free + queue depth in, level out; every transition is
        counted and published (ISSUE-15).  Ladder level 3 additionally
        preempts best_effort lanes PROACTIVELY — before the pool is
        fully dry — whenever strictly higher-class work is waiting;
        with a tenant registry installed the rung takes lanes from
        non-compliant (over-quota / SLO-burning) tenants FIRST and
        leaves a compliant tenant's lanes alone whenever an offender
        holds one (ISSUE-16)."""
        if self._pressure is None or self._pool is None:
            return
        # observed update cadence (EWMA), the real timescale behind
        # `down_dwell` exits — feeds `_ladder_retry_after_locked` so
        # Retry-After tracks how fast this pool ACTUALLY re-evaluates
        # pressure, not a constant (ISSUE-16 satellite fix)
        now = time.perf_counter()
        if self._pressure_stamp is not None:
            dt = now - self._pressure_stamp
            if 0.0 < dt < 5.0:
                self._pressure_tick_s = (0.8 * self._pressure_tick_s
                                         + 0.2 * dt)
        self._pressure_stamp = now
        # pages-free counts evictable radix-cached pages too: a warm
        # prefix cache is reclaimable capacity, not pressure — without
        # this an idle pool with a full cache would sit degraded forever
        cfg = self._pressure.config
        avail = self._pool.free
        if (self._tree is not None
                and avail / max(1, self.kv_pages)
                <= cfg.enter_free_frac[0] + cfg.exit_free_margin):
            # evictable() is an O(cache) tree walk under the pool lock,
            # once per admission round: skip it when free pages alone
            # clear the shallowest enter threshold plus the exit margin
            # — adding reclaimable capacity on top cannot change the
            # ladder's reading there (every enter_free_frac[k] and
            # every calm bound is <= this line)
            avail += self._tree.evictable()
        moves = self._pressure.update(avail, self.kv_pages,
                                      len(self._queue), self.n_slots)
        self.metrics.record_brownout(self._pressure.level, len(moves))
        if (self._pressure.level >= 3 and self.preempt and self._queue
                and self._queue[0].rank < RANK_BEST_EFFORT):
            head_rank = self._queue[0].rank
            victims = [s for s in self._slots
                       if (s.active and not s.req.abandoned
                           and s.req.rank >= RANK_BEST_EFFORT
                           and s.req.rank > head_rank)]
            if (self.tenants is not None
                    and any(not self.tenants.compliant(s.req.tenant)
                            for s in victims)):
                # offender-first rung (ISSUE-16): while a non-compliant
                # tenant holds a candidate lane, preempt ONLY its lanes
                # — a compliant tenant's best_effort survives L3
                victims = [s for s in victims
                           if not self.tenants.compliant(s.req.tenant)]
            for slot in victims:
                self._preempt_slot_locked(slot)

    def _preempt_one_locked(self, head: _LMRequest) -> bool:
        """Pick and preempt ONE victim so `head` can admit: the active
        lane with the worst (highest) rank strictly above the head's,
        ties broken newest-first so older work of the same class keeps
        its progress.  With a tenant registry the WORST-BEHAVED tenant
        pays first: victims sort by (over-quota, SLO burn rate) ahead
        of the PR-15 (rank, enqueued) key, so an offender's lane swaps
        out before a compliant tenant's ever does (ISSUE-16).  Returns
        False when preemption is off, no program pair exists yet, or
        nothing outranked is running."""
        if not self.preempt or self._gather is None or self._cache is None:
            return False
        victims = [s for s in self._slots
                   if s.active and not s.req.abandoned
                   and s.req.rank > head.rank]
        if not victims:
            return False
        if self.tenants is not None:
            victim = max(victims,
                         key=lambda s: (self.tenants.badness(s.req.tenant),
                                        s.req.rank, s.req.enqueued))
        else:
            victim = max(victims,
                         key=lambda s: (s.req.rank, s.req.enqueued))
        self._preempt_slot_locked(victim)
        return True

    def _preempt_slot_locked(self, slot: _Slot) -> None:
        """Evict one active lane in favor of higher-priority work.

        A lane mid-decode swaps its KV state out to the host: one
        fixed-shape gather dispatch, then the same serialized wire
        frame the shipping plane uses (SHA-256 over the payload), into
        the byte-capped LRU `SwapStore`.  On re-admission it restores
        through the pending-install plane and resumes byte-identically
        — decode is deterministic (greedy and `fold_in(seed, count)`
        sampling), so even a lane whose swap is later lost recomputes
        the SAME tokens from its prompt.  A lane still mid-prefill (or
        an export lane) has nothing worth shipping: it just requeues
        and re-prefills (radix-cached pages make that cheap).  Either
        way the request keeps its original enqueue stamp, so it
        re-enters AHEAD of later arrivals of its own class."""
        req = slot.req
        mid_decode = (slot.fed >= len(req.prompt) and slot.generated
                      and not req.export)
        if (mid_decode and self._swap is not None
                and self._gather is not None and self._cache is not None):
            n = -(-slot.pos // self.page_size)
            with compile_scope("lm:page_gather"):
                pk, pv = self._gather(*self._cache, slot.table)
            pk = np.asarray(pk)[:, :n]
            pv = np.asarray(pv)[:, :n]
            ex = PageExport(
                prompt=list(req.prompt), max_new=req.max_new,
                temperature=req.temperature, seed=req.seed,
                committed=list(slot.generated), pos=int(slot.pos),
                page_size=self.page_size, pages_k=pk, pages_v=pv,
                model=model_signature(self.cfg, self.page_size),
                session_id=req.session_id, priority=req.priority,
                tenant=req.tenant)
            if self.swap_quantize:
                # per-page int8 in transit and at rest (ISSUE-19):
                # ~4x fewer bytes through the tiers; the deterministic
                # resume-parity tests pin that dequantized restore
                # still reproduces the exact token stream
                ex = quantize_export(ex)
            blob = serialize_export(ex)
            key = f"swap-{self._swap_seq}"
            self._swap_seq += 1
            evicted = self._swap.put(key, blob)
            if evicted is None:
                # the blob alone exceeds the cap: recompute-from-prompt
                # on re-admission instead of wiping every other victim
                self.metrics.record_swap_lost("evicted")
            else:
                req.swap_key = key
                # raw array bytes, matching the swap-in site and the
                # ship ledger — a lossless round trip reads out == in
                self.metrics.record_swap("out", n, ex.nbytes())
                # LRU victims whose state just got dropped recompute
                # from their prompts at restore time — where the loss
                # is counted (once), by _resolve_swap_locked
        req.preempted += 1
        self.metrics.record_preemption(req.priority)
        if self.tenants is not None:
            self.metrics.record_tenant("preempted", req.tenant)
        self._free_slot_pages(slot)
        slot.req = None
        slot.generated = []
        self._queue_insert_locked(req)
        self.metrics.set_queue_depth(len(self._queue))

    def _finish_slot(self, slot: _Slot) -> None:
        """Completion fold: resolve the client, free the lane + pages."""
        if slot.req.abandoned:
            # the client timed out mid-decode and already got
            # DeadlineExceededError: the finished sequence is
            # discarded work, not a served request
            self.metrics.record_shed()
            self.metrics.record_class("shed", slot.req.priority)
            if self.tenants is not None:
                self.metrics.record_tenant("shed", slot.req.tenant)
        else:
            self.metrics.record_class("requests", slot.req.priority)
            slot.req.result = slot.req.prompt + slot.generated
            now = time.perf_counter()
            slot.req.t_done = now
            t_in = slot.req.t_installed or now
            # queue-wait vs decode-compute split (ISSUE-8 satellite)
            self.metrics.record_request(
                now - slot.req.enqueued,
                queue_wait_s=t_in - slot.req.enqueued,
                compute_s=now - t_in)
            if self.tenants is not None:
                # the tenant's completion ledger: served count, tokens
                # actually generated (tokens_out), and the SLO window
                # sample that drives the burn-rate gauge (ISSUE-16)
                tn = slot.req.tenant
                self.metrics.record_tenant("requests", tn)
                self.tenants.meter.record_out(tn, len(slot.generated))
                self.tenants.slo.record(tn, now - slot.req.enqueued)
                self.metrics.set_tenant_burn(
                    tn, self.tenants.slo.burn_rate(tn))
            if (self.hibernate and slot.req.session_id is not None
                    and self._tree is not None
                    and slot.table is not None):
                # sticky-session hibernation tracking (ISSUE-19): the
                # FULL committed sequence's whole pages enter the radix
                # tree (prompt pages alone would forget the generated
                # turn), and the session is stamped for the idle sweep.
                # Only fully-WRITTEN pages insert — the final sampled
                # token is returned, never fed, so its position has no
                # KV yet.
                seq = slot.req.result
                n_full = (len(seq) - 1) // self.page_size
                if n_full:
                    self._tree.insert(
                        seq[:n_full * self.page_size],
                        [int(p) for p in slot.table[:n_full]])
                sid = slot.req.session_id
                self._hib_sessions[sid] = {"tokens": list(seq),
                                           "t": now}
                self._hib_sessions.move_to_end(sid)
                while len(self._hib_sessions) > self._session_capacity:
                    self._hib_sessions.popitem(last=False)
            slot.req.event.set()
        self._free_slot_pages(slot)
        slot.req = None

    def _insert_prompt_pages(self, slot: _Slot) -> None:
        """Prefill just completed: register this prompt's FULL pages in
        the radix tree so the next shared-prefix request skips them.
        Page-granular — a prompt shorter than one page caches nothing."""
        if self.kv != "paged" or slot.inserted:
            return
        slot.inserted = True
        plen = len(slot.req.prompt)
        n_full = plen // self.page_size
        if n_full:
            self._tree.insert(slot.req.prompt[:n_full * self.page_size],
                              [int(p) for p in slot.table[:n_full]])

    def _drain_step(self) -> bool:
        """One scheduling round: admit, build the step inputs, dispatch,
        fold the sampled tokens back into each lane.  Returns False when
        idle (nothing active, nothing queued)."""
        with self._cond:
            # a pending warmup runs on the worker's own cache, inside
            # this protected loop (a failing warm dispatch rides the
            # same fault path as a failing decode).  The paged step
            # with n_feed=0 touches only the null page, so it is safe
            # even alongside live lanes; the dense warm waits for idle
            # (its unconditional pos-0 write would clobber active rows)
            warm = self._warm_req
            idle = not any(s.active for s in self._slots)
            if warm is not None and (idle or self.kv == "paged"):
                self._warm_req = None
            else:
                warm = None
        if warm is not None:
            try:
                self._warm_programs()
            finally:
                warm.set()
            return True
        with self._cond:
            self._admit_locked()
            active = [s for s in self._slots if s.active]
            if not active:
                return False
            cow, self._pending_cow = self._pending_cow, []
            installs, self._pending_install = self._pending_install, []
            # the brownout level this round dispatches under — read
            # once with the lock held; the ladder only moves inside
            # _admit_locked, so the level cannot change mid-dispatch
            level = (self._pressure.level if self._pressure is not None
                     else 0)
        if self.breaker is not None and not self.breaker.allow_dispatch():
            # open breaker: fast-fail whatever is in flight rather than
            # burning decode steps on a failing device
            err = CircuitOpenError(
                "circuit breaker open: decode fast-failed",
                retry_after_s=self.breaker.retry_after_s())
            with self._cond:
                for item in cow:
                    # un-executed CoW copies hold a retention on their
                    # source page; the lane that wanted them is failing
                    self._pool.release([item["src"]])
                for s in self._slots:
                    if s.active:
                        self.metrics.record_shed()
                        self.metrics.record_class("shed",
                                                  s.req.priority)
                        if self.tenants is not None:
                            self.metrics.record_tenant("shed",
                                                       s.req.tenant)
                        s.req.error = err
                        s.req.event.set()
                        self._free_slot_pages(s)
                        s.req = None
            return True
        if self._cache is None:
            # a failed step consumed its donated k/v buffers and set the
            # cache aside; rebuild INSIDE the protected loop so a failing
            # rebuild fails this round's requests instead of killing the
            # worker thread (page/radix state was already reset by the
            # fault handler — slots restart at pos 0, nothing to keep)
            self._reset_cache()
        if self.kv == "paged":
            return self._dispatch_paged(active, cow, installs, level)
        return self._dispatch_dense(active)

    def _dispatch_dense(self, active) -> bool:
        token = np.zeros((self.n_slots,), np.int32)
        pos = np.zeros((self.n_slots,), np.int32)
        temp = np.zeros((self.n_slots,), np.float32)
        seeds = np.zeros((self.n_slots,), np.int32)
        counts = np.zeros((self.n_slots,), np.int32)
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            req = slot.req
            if slot.fed < len(req.prompt):     # prefill: teacher-force
                token[i] = req.prompt[slot.fed]
            else:                              # decode: feed last sample
                token[i] = slot.generated[-1]
            pos[i] = slot.pos
            temp[i] = req.temperature
            seeds[i] = req.seed
            counts[i] = len(slot.generated)
        with compile_scope("lm:dense"):
            nxt, k, v = self._step(self.params, *self._cache, pos, token,
                                   temp, seeds, counts)
        if self.breaker is not None:
            self.breaker.record_success()
        self._cache = (k, v)
        nxt = np.asarray(nxt)
        self._steps += 1
        emitted = 0
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            slot.pos += 1
            if slot.fed < len(slot.req.prompt):
                slot.fed += 1
                # the LAST prompt token's logits yield the first sample
                if slot.fed < len(slot.req.prompt):
                    continue
            self._commit_tokens(slot, [int(nxt[i])])
            emitted += 1
            if len(slot.generated) >= slot.req.max_new:
                self._finish_slot(slot)
        self.metrics.record_dispatch(len(active), self.n_slots)
        if emitted:
            self.metrics.record_tokens(emitted)
        return True

    def _draft_proposals(self) -> Dict[int, List[int]]:
        """One drafting round: collect per-lane proposals for GREEDY
        decode-phase lanes with budget left.  Sampling lanes
        (temperature > 0) are never drafted for — a greedy accept rule
        over a sampled lane would mis-sample — and ride the round as
        plain 1-token decode; so do lanes mid-prefill and lanes within
        one token of their budget.  Out-of-vocab draft tokens (a
        misbehaving custom Drafter) are truncated at the first offender
        so the verify feed stays a valid token chunk."""
        histories: List[Optional[List[int]]] = [None] * self.n_slots
        budgets = [0] * self.n_slots
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            req = slot.req
            remaining = req.max_new - len(slot.generated)
            if (slot.fed >= len(req.prompt) and req.temperature == 0
                    and remaining >= 2 and slot.generated):
                histories[i] = req.prompt + slot.generated
                budgets[i] = min(self.draft_len, remaining - 1)
        if not any(budgets):
            return {}
        proposals = self._drafter.propose(histories, budgets)
        out: Dict[int, List[int]] = {}
        for i, prop in enumerate(proposals):
            if not budgets[i] or not prop:
                continue
            clean: List[int] = []
            for t in prop[:budgets[i]]:
                t = int(t)
                if not 0 <= t < self.cfg.vocab_size:
                    break
                clean.append(t)
            if clean:
                out[i] = clean
        return out

    def _commit_tokens(self, slot: _Slot, toks: List[int]) -> None:
        """Fold newly committed tokens into a lane: first-token TTFT
        stamp, the lane's generated list, and the request's stream (one
        push per token — a speculative round's multi-token commit
        streams as individual events).  The stream cursor is the
        COUNT of tokens already pushed, not "everything new": a
        preempted lane whose swap state was lost recomputes its early
        tokens from the prompt, and those regenerated (byte-identical)
        tokens must not stream twice."""
        req = slot.req
        if req.t_first is None:
            req.t_first = time.perf_counter()
            self.metrics.record_first_token(req.t_first - req.enqueued)
        slot.generated.extend(toks)
        if req.stream is not None and not req.abandoned:
            # monotonic cursor: a recompute rebuilding the early tokens
            # stays BELOW the cursor until it passes where the stream
            # left off — never rewind it, or the rebuilt (identical)
            # tokens would stream again
            for t in slot.generated[req.stream_pushed:]:
                req.stream.put(int(t))
            req.stream_pushed = max(req.stream_pushed,
                                    len(slot.generated))

    def _export_slot(self, slot: _Slot) -> None:
        """Prefill just completed on an export lane: gather its pages
        out of the pool (one fixed-shape dispatch + one host sync),
        resolve the request with the shipment, and free the lane.  Runs
        BEFORE the lane's pages are released — the radix tree keeps the
        prompt pages for the next shared-prefix prefill, and page
        content is only ever recycled through the allocator."""
        req = slot.req
        t0 = time.perf_counter()
        with compile_scope("lm:page_gather"):
            pk, pv = self._gather(*self._cache, slot.table)
        n = -(-slot.pos // self.page_size)
        pk = np.asarray(pk)[:, :n]
        pv = np.asarray(pv)[:, :n]
        ex = PageExport(
            prompt=list(req.prompt), max_new=req.max_new,
            temperature=req.temperature, seed=req.seed,
            committed=list(slot.generated), pos=int(slot.pos),
            page_size=self.page_size, pages_k=pk, pages_v=pv,
            model=model_signature(self.cfg, self.page_size),
            session_id=req.session_id, priority=req.priority,
            tenant=req.tenant)
        self.metrics.record_ship("out", n, ex.nbytes(),
                                 time.perf_counter() - t0)
        req.export_result = ex
        self._finish_slot(slot)

    def _dispatch_paged(self, active, cow, installs,
                        level: int = 0) -> bool:
        # land shipped-in pages first (their lane's committed state is
        # already live — its next feed reads them), then pending
        # copy-on-write pages: a CoW admitted in the same round may
        # diverge FROM a page the shipment just installed
        for item in installs:
            t0 = time.perf_counter()
            with compile_scope("lm:page_install"):
                k, v = self._install(*self._cache, item["pk"],
                                     item["pv"], item["row"],
                                     np.int32(item["n"]))
            self._cache = (k, v)
            if item.get("swap"):
                # a preempted lane restoring from the host store — the
                # swap ledger, not the wire-shipping one
                self.metrics.record_swap("in", item["n"],
                                         item["nbytes"])
            elif item.get("hibernate"):
                # a hibernated session resuming from the tiered store —
                # the hibernation ledger (at-rest vs exact bytes feed
                # the compression ratio the bench gates on)
                self.metrics.record_hibernate("in", item["pages"],
                                              item["nbytes"],
                                              item["exact_nbytes"])
            else:
                self.metrics.record_ship("in", item["n"],
                                         item["nbytes"],
                                         time.perf_counter() - t0)
        for item in cow:
            with compile_scope("lm:page_copy"):
                k, v = self._copy(*self._cache, np.int32(item["src"]),
                                  np.int32(item["dst"]))
            self._cache = (k, v)
            self._pool.release([item["src"]])
        # brownout ladder effects (ISSUE-15, docs/robustness.md "The
        # degradation ladder"): level 1 turns speculation off (drafts
        # buy throughput with wide-dispatch compute — under pressure
        # that compute belongs to survival); level 2 additionally
        # shrinks the prefill ride-along width so active decode lanes
        # commit more often while admission throughput pays.
        drafts = (self._draft_proposals()
                  if self._drafter is not None and level < 1 else {})
        chunk_eff = (max(1, self.prefill_chunk // 2) if level >= 2
                     else self.prefill_chunk)
        # chunk width: the wide program dispatches only while some lane
        # has a FULL chunk of prompt left to feed — sub-chunk tails and
        # pure-decode rounds ride the 1-wide program — or, with
        # speculation on, while some lane has drafts to verify (and
        # then prompt tails hitch a ride on the already-paid wide
        # dispatch).  Short-prompt non-speculative traffic therefore
        # never compiles (or pays for) the wide program at all; a long
        # prompt costs ceil(P/chunk) wide dispatches plus its tail.
        width = 1
        full_chunk = any(len(s.req.prompt) - s.fed >= chunk_eff
                         for s in active)
        if self.speculate != "off":
            if drafts or (full_chunk and self.prefill_chunk > 1):
                width = self.spec_width
        elif self._chunk_step is not None and full_chunk:
            width = self.prefill_chunk
        tokens = np.zeros((self.n_slots, width), np.int32)
        pos = np.zeros((self.n_slots,), np.int32)
        n_feed = np.zeros((self.n_slots,), np.int32)
        n_draft = np.zeros((self.n_slots,), np.int32)
        temp = np.zeros((self.n_slots,), np.float32)
        seeds = np.zeros((self.n_slots,), np.int32)
        counts = np.zeros((self.n_slots,), np.int32)
        table = np.zeros((self.n_slots, self.max_pages), np.int32)
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            req = slot.req
            remaining = len(req.prompt) - slot.fed
            if remaining > 0:                  # chunked prefill
                f = min(remaining, width, chunk_eff)
                tokens[i, :f] = req.prompt[slot.fed:slot.fed + f]
                n_feed[i] = f
            elif width > 1 and i in drafts:    # speculative verify
                prop = drafts[i]
                tokens[i, 0] = slot.generated[-1]
                tokens[i, 1:1 + len(prop)] = prop
                n_feed[i] = 1 + len(prop)
                n_draft[i] = len(prop)
            else:                              # decode: feed last sample
                tokens[i, 0] = slot.generated[-1]
                n_feed[i] = 1
            pos[i] = slot.pos
            temp[i] = req.temperature
            seeds[i] = req.seed
            counts[i] = len(slot.generated)
            table[i] = slot.table
        with compile_scope(f"lm:paged[w{width}]"):
            if self.speculate != "off":
                nxt, acc, k, v = self._step(
                    self.params, *self._cache, table, pos, n_feed,
                    n_draft, tokens, temp, seeds, counts)
            else:
                nxt, k, v = self._step(self.params, *self._cache, table,
                                       pos, n_feed, tokens, temp, seeds,
                                       counts)
                acc = None
        if self.breaker is not None:
            self.breaker.record_success()
        self._cache = (k, v)
        # ONE host sync per round: the bonus tokens and the per-lane
        # accepted counts arrive together, never per token
        nxt = np.asarray(nxt)
        acc = np.asarray(acc) if acc is not None else None
        self._steps += 1
        emitted = 0
        for i, slot in enumerate(self._slots):
            if not slot.active or n_feed[i] == 0:
                continue
            if slot.fed < len(slot.req.prompt):
                slot.pos += int(n_feed[i])
                slot.fed += int(n_feed[i])
                if slot.fed < len(slot.req.prompt):
                    continue
                # prefill complete: its full pages become reusable, and
                # the last prompt token's logits yield the first sample
                self._insert_prompt_pages(slot)
                self._commit_tokens(slot, [int(nxt[i])])
                emitted += 1
                if slot.req.export:
                    # export lane: this pool's job ends at prefill —
                    # gather the pages, resolve with the shipment
                    self._export_slot(slot)
                    continue
            else:
                # decode fold with in-jit accept/rollback: commit the
                # accepted draft prefix plus the bonus token; rewind is
                # a pointer move — pos advances past ONLY the committed
                # feeds, so rejected columns' k/v (written into the
                # lane's own future pages) stay masked until real
                # writes land over them.  No pages move: the lane's
                # pages were granted at admission and flow back through
                # `_free_slot_pages` refcounts at completion.
                a = int(acc[i]) if acc is not None else 0
                k_drafted = int(n_draft[i])
                slot.pos += 1 + a
                if k_drafted:
                    slot.req.drafted += k_drafted
                    slot.req.accepted += a
                    self._commit_tokens(
                        slot, [int(t) for t in drafts[i][:a]]
                        + [int(nxt[i])])
                else:
                    self._commit_tokens(slot, [int(nxt[i])])
                emitted += 1 + a
                self.metrics.record_decode_round(
                    1 + a, drafted=k_drafted, accepted=a)
            if len(slot.generated) >= slot.req.max_new:
                self._finish_slot(slot)
        self.metrics.record_dispatch(len(active), self.n_slots)
        if emitted:
            self.metrics.record_tokens(emitted)
        self.metrics.set_pages(self._pool.in_use, self._pool.free,
                               self.kv_pages)
        return True

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._running:
                    # abort in-flight + queued rather than leaving clients
                    # blocked on a dead worker
                    victims = [s.req for s in self._slots if s.active]
                    victims += list(self._queue)
                    for s in self._slots:
                        s.req = None
                    self._queue.clear()
                    # page contents survive a stop only as long as the
                    # buffers do — release everything in one sweep
                    self._reset_pool_locked()
                    if (self.hibernate and self._swap is not None
                            and self.state_dir is not None):
                        # a clean stop makes hibernation durable: demote
                        # host-tier entries (only hib- remain — the
                        # reset above dropped the swap- lane state) so a
                        # restarted server over the same state_dir
                        # resumes them instead of recomputing
                        self._swap.flush_to_disk()
                    if self._warm_req is not None:
                        # a warmup() waiting on a stopped server must
                        # unblock, not sit out its timeout
                        self._warm_req.set()
                        self._warm_req = None
                    for r in victims:
                        self.metrics.record_shed()
                        self.metrics.record_class("shed", r.priority)
                        if self.tenants is not None:
                            self.metrics.record_tenant("shed", r.tenant)
                        r.error = ServingUnavailableError(
                            "LM server stopped")
                        r.event.set()
                    return
            try:
                busy = self._drain_step()
            except BaseException as e:  # noqa: BLE001 — fail in-flight, keep serving
                if self.breaker is not None:
                    self.breaker.record_failure()
                with self._cond:
                    victims = [s for s in self._slots if s.active]
                    for s in victims:
                        s.req.error = e
                        s.req.event.set()
                        s.req = None
                    # the failed step consumed its donated k/v buffers
                    # AND whatever pages the radix tree pointed into:
                    # reset the host page state NOW (pure Python, cannot
                    # fail) so the next admit round allocates against a
                    # coherent pool, and mark the device cache dead so
                    # the next round rebuilds it inside this same
                    # protected loop (a rebuild that throws then fails
                    # THAT round's requests, not the worker)
                    self._reset_pool_locked()
                    self._cache = None
                busy = True
            if not busy:
                with self._cond:
                    if not self._running:
                        return
                    if not self._queue:
                        self._cond.wait(0.05)
            else:
                time.sleep(0)  # yield: let submitters enqueue mid-decode
