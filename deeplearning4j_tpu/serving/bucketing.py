"""Shape bucketing for the serving path.

XLA compiles one program per distinct input shape, so a serving process
that dispatches whatever batch/length arrives compiles an unbounded
program set — each new shape paying a full compile (seconds) on the
request path.  The fix is the classic fixed-shape serving discipline
(the O(1)-cache / compiler-first serving papers in PAPERS.md): pad every
dispatch UP to a small fixed ladder of shapes so any traffic pattern
executes a bounded, pre-warmable program set.

`BucketLadder` owns the ladder: a short ascending list of batch buckets
(default 1/8/32/128) and, for sequence models, a pow2 ladder of length
buckets.  Padding never changes results: batch-dim padding rows are
computed and sliced away (rows are independent in inference — no batch
statistics), and length-dim padding is masked per example via the
network's `[batch, time]` mask.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

DEFAULT_BATCH_BUCKETS = (1, 8, 32, 128)


def pow2_length_buckets(max_len: int, min_len: int = 16) -> Tuple[int, ...]:
    """Powers of two from min_len up to (and including) max_len."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    out = []
    b = max(1, min_len)
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


class BucketLadder:
    """A fixed ladder of (batch[, length]) buckets.

    `batch_bucket(n)` / `length_bucket(t)` return the smallest bucket
    that fits; oversize requests raise (the caller — the micro-batcher —
    enforces its own `max_batch` below the top bucket).  `program_bound`
    is the worst-case number of distinct dispatch shapes the ladder can
    produce — the serving engine's compile-count guard pins actual
    compiles to it.
    """

    def __init__(self,
                 batch_buckets: Sequence[int] = DEFAULT_BATCH_BUCKETS,
                 length_buckets: Optional[Sequence[int]] = None):
        self.batch_buckets = tuple(sorted(set(int(b) for b in batch_buckets)))
        if not self.batch_buckets or self.batch_buckets[0] < 1:
            raise ValueError(f"batch buckets must be positive ints, got "
                             f"{batch_buckets}")
        self.length_buckets = (None if length_buckets is None else
                               tuple(sorted(set(int(b)
                                                for b in length_buckets))))
        if self.length_buckets is not None and (
                not self.length_buckets or self.length_buckets[0] < 1):
            raise ValueError(f"length buckets must be positive ints, got "
                             f"{length_buckets}")

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    @property
    def program_bound(self) -> int:
        """Worst-case distinct dispatch shapes: |batch| x |length|."""
        return len(self.batch_buckets) * (len(self.length_buckets)
                                          if self.length_buckets else 1)

    def batch_bucket(self, n: int) -> int:
        """Smallest batch bucket >= n."""
        if n < 1:
            raise ValueError(f"batch must be >= 1, got {n}")
        for b in self.batch_buckets:
            if n <= b:
                return b
        raise ValueError(f"batch {n} exceeds the largest bucket "
                         f"{self.batch_buckets[-1]}; split the request or "
                         f"extend the ladder")

    def length_bucket(self, t: int) -> int:
        """Smallest length bucket >= t (requires a length ladder)."""
        if self.length_buckets is None:
            raise ValueError("this ladder has no length buckets")
        if t < 1:
            raise ValueError(f"length must be >= 1, got {t}")
        for b in self.length_buckets:
            if t <= b:
                return b
        raise ValueError(f"length {t} exceeds the largest bucket "
                         f"{self.length_buckets[-1]}")

    def pad_rows(self, x: np.ndarray) -> Tuple[np.ndarray, int]:
        """Zero-pad axis 0 up to the batch bucket: (padded, n_real)."""
        n = int(x.shape[0])
        b = self.batch_bucket(n)
        if b == n:
            return x, n
        pad = np.zeros((b - n,) + x.shape[1:], x.dtype)
        return np.concatenate([x, pad], axis=0), n

    def pad_length(self, x: np.ndarray,
                   mask: Optional[np.ndarray] = None
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """Zero-pad axis 1 (time) of a [n, T, ...] batch up to the length
        bucket and return (padded_x, mask) where mask is [n, T_bucket]
        with 1.0 over real steps — padded steps contribute nothing to a
        masked forward."""
        t = int(x.shape[1])
        tb = self.length_bucket(t)
        if mask is None:
            mask = np.ones(x.shape[:2], np.float32)
        if tb == t:
            return x, mask
        pad_x = np.zeros((x.shape[0], tb - t) + x.shape[2:], x.dtype)
        pad_m = np.zeros((x.shape[0], tb - t), np.float32)
        return (np.concatenate([x, pad_x], axis=1),
                np.concatenate([mask, pad_m], axis=1))
