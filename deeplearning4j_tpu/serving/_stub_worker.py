"""Stdlib-only stub serving worker — the process-supervision test body.

`FleetSupervisor` (serving/procfleet.py) supervises real OS processes:
it polls exit status, sends real SIGTERM/SIGKILL, and watches a real
port.  Exercising that in tier-1 against full `dl4j serve` workers would
cost a jax import (~4s) plus model warmup per spawn, so this module is
the minimal honest stand-in: a real process that binds a real port and
speaks the replica endpoint surface the router and supervisor dispatch
against (`/readyz`, `/healthz`, `/serving/stats`, `/model/predict`) —
kill -9 it, SIGSTOP it, flake its boot, and the supervisor sees exactly
what a dead/wedged/flaking `dl4j serve` worker looks like, in ~100ms of
boot instead of seconds.

Run it BY FILE PATH (``python .../serving/_stub_worker.py --port N``),
never ``-m``: executing by path skips the ``deeplearning4j_tpu``
package parents entirely, which is where the jax import lives.  This
module must therefore stay importable with the stdlib alone.
`serving.procfleet.stub_worker_command()` builds the command line.

Faults on tap (all deterministic, flag-driven):
- ``--ready-delay-s S``: /readyz answers 503 for the first S seconds
  (a worker that binds fast but warms slowly);
- ``--never-ready``: /readyz stays 503 forever (the ready-timeout path
  — the supervisor must attach the log tail to its report);
- ``--boot-exit-code N``: print a line and exit N immediately (the
  boot-flake path that drives crash-loop quarantine).

SIGTERM exits 0 after a clean shutdown — the supervisor classifies that
death ``clean``, same as a drained `dl4j serve` worker.
"""

import argparse
import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _StubServer(ThreadingHTTPServer):
    # same restart-after-drain semantics as serving/resilience.py's
    # ServingHTTPServer (not imported: this file must stay stdlib-only)
    allow_reuse_address = True
    daemon_threads = True


class _StubHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # silence stderr per request
        pass

    def _json(self, code: int, payload) -> None:
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802
        srv = self.server
        if self.path == "/healthz":
            self._json(200, {"ok": True})
        elif self.path == "/readyz":
            ready = (not srv.never_ready
                     and time.monotonic() - srv.t0 >= srv.ready_delay_s)
            if ready:
                self._json(200, {"ready": True})
            else:
                self._json(503, {"ready": False, "reasons": ["warming"]})
        elif self.path == "/serving/stats":
            with srv.lock:
                n = srv.requests
            # the classifier-plane ledger shape fleet_stats folds
            self._json(200, {
                "classifier": {"requests": n, "rejected": 0, "shed": 0,
                               "deadline_missed": 0, "poison_isolated": 0},
                "uptime_s": time.monotonic() - srv.t0,
                "replicas": srv.replicas,
                "stub_worker": True})
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802
        srv = self.server
        if self.path != "/model/predict":
            self._json(404, {"error": f"unknown path {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length)) if length else {}
            feats = body.get("features") or []
            n = len(feats)
        except (ValueError, json.JSONDecodeError) as e:
            self._json(400, {"error": str(e)})
            return
        with srv.lock:
            srv.requests += n if n else 1
        self._json(200, {"predictions": [0] * n,
                         "outputs": [[1.0, 0.0, 0.0]] * n})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--ready-delay-s", type=float, default=0.0)
    ap.add_argument("--never-ready", action="store_true")
    ap.add_argument("--boot-exit-code", type=int, default=None)
    # elastic-restart probe: the replica count this incarnation was
    # launched with (a real elastic training worker would size its mesh
    # by it); echoed in /serving/stats so supervision tests can assert a
    # resurrection came back with the REWRITTEN count
    ap.add_argument("-replicas", "--replicas", type=int, default=None)
    args = ap.parse_args(argv)

    if args.boot_exit_code is not None:
        print(f"stub-worker: boot flake — exiting "
              f"{args.boot_exit_code}", flush=True)
        return int(args.boot_exit_code)
    try:
        server = _StubServer((args.host, args.port), _StubHandler)
    except OSError as e:
        # EADDRINUSE etc: the log line is what collision diagnostics read
        print(f"stub-worker: bind failed on {args.host}:{args.port}: "
              f"{e}", flush=True)
        return 98
    server.t0 = time.monotonic()
    server.ready_delay_s = float(args.ready_delay_s)
    server.never_ready = bool(args.never_ready)
    server.requests = 0
    server.replicas = args.replicas
    server.lock = threading.Lock()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    threading.Thread(target=server.serve_forever, daemon=True).start()
    print(f"stub-worker: listening on {args.host}:{args.port} "
          f"(pid {os.getpid()})", flush=True)
    while not stop.wait(0.1):
        pass
    server.shutdown()
    server.server_close()
    print("stub-worker: SIGTERM — clean exit", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
