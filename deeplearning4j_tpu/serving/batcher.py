"""Dynamic micro-batching: coalesce concurrent requests into one dispatch.

The single-request serving path pays one XLA dispatch (plus host->device
transfer) PER request; for small models the dispatch overhead IS the
request (docs/performance.md dispatch-overhead model).  The micro-batcher
is the inference-side analog of the fused training driver: a request
queue whose worker thread coalesces everything that arrives within a
short window (`max_wait_ms`, up to `max_batch` rows) into ONE padded
device dispatch, then slices the row-aligned results back per request.

Correctness contract: the model's inference forward is row-independent
(no batch statistics), so a request's rows produce bitwise-identical
outputs whether dispatched alone or inside a coalesced padded batch —
tests/test_serving.py pins this byte-for-byte under concurrency.

Requests with different trailing shapes (e.g. different padded sequence
buckets) never share a dispatch: the worker groups the queue head with
same-shape followers and leaves the rest queued for the next cycle.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu.serving.metrics import ServingMetrics


class _Pending:
    __slots__ = ("x", "mask", "event", "result", "error", "enqueued")

    def __init__(self, x: np.ndarray, mask: Optional[np.ndarray]):
        self.x = x
        self.mask = mask
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.enqueued = time.perf_counter()

    @property
    def key(self):
        """Dispatch-compatibility key: trailing shape + mask presence."""
        return (self.x.shape[1:], self.x.dtype.str,
                None if self.mask is None else self.mask.shape[1:])


class MicroBatcher:
    """Request queue + coalescing worker in front of a dispatch function.

    `dispatch(x, mask, n_real)` receives the stacked real rows (the
    callee pads to its bucket) and must return row-aligned outputs as a
    numpy array of at least `n_real` rows.  `submit()` blocks the
    calling thread until its slice of the result is ready and is safe to
    call from any number of threads.
    """

    def __init__(self, dispatch: Callable, max_batch: int = 32,
                 max_wait_ms: float = 2.0,
                 metrics: Optional[ServingMetrics] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self._dispatch = dispatch
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # ---- client side ------------------------------------------------------

    def submit(self, x: np.ndarray, mask: Optional[np.ndarray] = None,
               timeout: Optional[float] = None) -> np.ndarray:
        """Enqueue a [n, ...] request and block for its [n, ...] outputs."""
        x = np.asarray(x)
        if x.ndim < 2 or x.shape[0] < 1:
            raise ValueError(f"request must be [n, ...] with n >= 1, got "
                             f"shape {x.shape}")
        if x.shape[0] > self.max_batch:
            raise ValueError(f"request rows ({x.shape[0]}) exceed max_batch "
                             f"({self.max_batch}); split the request")
        item = _Pending(x, None if mask is None else np.asarray(mask))
        with self._cond:
            if not self._running:
                self._start_locked()
            self._queue.append(item)
            self.metrics.set_queue_depth(len(self._queue))
            self._cond.notify_all()
        if not item.event.wait(timeout):
            # Cancel rather than abandon: a still-queued request is
            # removed (otherwise retry-on-timeout clients fill the queue
            # with zombie work the device still executes); one the worker
            # already took is in flight and cannot be recalled.
            with self._cond:
                try:
                    self._queue.remove(item)
                    self.metrics.set_queue_depth(len(self._queue))
                except ValueError:
                    pass  # worker took it: the dispatch is in flight
            raise TimeoutError(f"serving request timed out after {timeout}s")
        self.metrics.record_request(time.perf_counter() - item.enqueued)
        if item.error is not None:
            raise item.error
        return item.result

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # fail anything still queued rather than leaving clients hung
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
        for item in leftovers:
            item.error = RuntimeError("batcher stopped")
            item.event.set()

    # ---- worker side ------------------------------------------------------

    def _start_locked(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="micro-batcher")
        self._thread.start()

    def _collect(self):
        """Take the queue head plus same-shape followers.

        Two regimes, which is what makes the batcher both low-latency
        and high-occupancy:

        - worker BUSY (queue non-empty when it frees up): dispatch
          immediately — the previous dispatch's duration already served
          as the coalescing window, so waiting again only adds latency
          (and on hosts with coarse timers, any timed wait costs ~1ms);
        - worker IDLE (had to block for the head): hold the head open up
          to `max_wait_ms` from its arrival so a burst's co-travellers
          can join its dispatch.
        """
        with self._cond:
            was_idle = not self._queue
            while self._running and not self._queue:
                self._cond.wait(0.1)
            if not self._running:
                return []
            head = self._queue[0]
            if was_idle and self.max_wait_s > 0:
                deadline = head.enqueued + self.max_wait_s
                while self._running:
                    rows = sum(i.x.shape[0] for i in self._queue
                               if i.key == head.key)
                    remaining = deadline - time.perf_counter()
                    if rows >= self.max_batch or remaining <= 0:
                        break
                    self._cond.wait(remaining)  # submits notify early
            group, rows, rest = [], 0, collections.deque()
            while self._queue:
                item = self._queue.popleft()
                if (item.key == head.key
                        and rows + item.x.shape[0] <= self.max_batch):
                    group.append(item)
                    rows += item.x.shape[0]
                else:
                    rest.append(item)
            self._queue.extend(rest)
            self.metrics.set_queue_depth(len(self._queue))
            return group

    def _run(self) -> None:
        while True:
            group = self._collect()
            if not group:
                with self._cond:
                    if not self._running:
                        return
                continue
            try:
                x = (group[0].x if len(group) == 1
                     else np.concatenate([g.x for g in group], axis=0))
                mask = None
                if group[0].mask is not None:
                    mask = (group[0].mask if len(group) == 1
                            else np.concatenate([g.mask for g in group],
                                                axis=0))
                out = np.asarray(self._dispatch(x, mask, x.shape[0]))
                off = 0
                for g in group:
                    n = g.x.shape[0]
                    g.result = out[off:off + n]
                    off += n
            except BaseException as e:  # noqa: BLE001 — fail the GROUP, keep serving
                for g in group:
                    g.error = e
            finally:
                for g in group:
                    g.event.set()
