"""Dynamic micro-batching: coalesce concurrent requests into one dispatch.

The single-request serving path pays one XLA dispatch (plus host->device
transfer) PER request; for small models the dispatch overhead IS the
request (docs/performance.md dispatch-overhead model).  The micro-batcher
is the inference-side analog of the fused training driver: a request
queue whose worker thread coalesces everything that arrives within a
short window (`max_wait_ms`, up to `max_batch` rows) into ONE padded
device dispatch, then slices the row-aligned results back per request.

Correctness contract: the model's inference forward is row-independent
(no batch statistics), so a request's rows produce bitwise-identical
outputs whether dispatched alone or inside a coalesced padded batch —
tests/test_serving.py pins this byte-for-byte under concurrency.

Requests with different trailing shapes (e.g. different padded sequence
buckets) never share a dispatch: the worker groups the queue head with
same-shape followers and leaves the rest queued for the next cycle.

Resilience contract (ISSUE-4, docs/robustness.md "serving plane"):

- admission is bounded — past `max_queue_depth` queued requests,
  `submit` raises `ServingOverloadError` (HTTP 503 + Retry-After)
  instead of queueing forever;
- deadlines are carried on queue items and already-expired work is shed
  *before* dispatch (`DeadlineExceededError`), so a timed-out client
  stops costing device time;
- a failed group dispatch is bisected (bounded depth, retry.py backoff
  between sub-dispatches) so exactly the poison request(s) fail and
  their co-batched neighbours still get byte-identical results;
- an optional `CircuitBreaker` fast-fails admission after N consecutive
  whole-dispatch failures and probes half-open after a cooldown;
- `begin_drain()`/`drain()` stop admission and let in-flight work finish
  within a grace window (the SIGTERM path of `dl4j serve`).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu.obs.compilewatch import compile_watcher
from deeplearning4j_tpu.obs.trace import (
    TraceRecorder,
    new_request_id,
    span,
    trace,
)
from deeplearning4j_tpu.resilience.retry import RetryPolicy, backoff_delays
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    ServingError,
    ServingUnavailableError,
    check_admission,
)
from deeplearning4j_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    TenantQuotaError,
    TenantRegistry,
)

# Backoff between bisection sub-dispatches: short — the worker thread is
# the serving plane, so these sleeps are paid by every co-batched
# request still waiting on its slice.
_BISECT_POLICY = RetryPolicy(max_attempts=8, base_delay=0.002,
                             multiplier=2.0, max_delay=0.05, jitter=0.0,
                             retryable=(Exception,))


def _build_serving_trace(raw):
    """Materialize one batcher trace from its hot-path tuple (the
    `TraceRecorder.record_lazy` builder — runs at /trace/recent read
    time, never on the request path)."""
    (rid, enqueued, t_start, t_end, done, status, rows, err,
     compiles, wall) = raw
    spans = [span("queue_wait", enqueued,
                  t_start if t_start is not None else done)]
    if t_start is not None:
        te = t_end if t_end is not None else done
        spans.append(span("dispatch", t_start, te, rows=rows))
        spans.append(span("respond", te, done))
        for c_end, c_dur, key in compiles or ():
            spans.append(span("xla_compile", c_end - c_dur, c_end,
                              program_key=key))
    out = trace(rid, "serving", spans, status=status, rows=rows,
                error=err)
    out["wall_time"] = wall
    return out


class _Pending:
    __slots__ = ("x", "mask", "event", "result", "error", "enqueued",
                 "deadline", "abandoned", "request_id", "t_start", "t_end",
                 "tenant")

    def __init__(self, x: np.ndarray, mask: Optional[np.ndarray],
                 deadline: Optional[float] = None,
                 request_id: Optional[str] = None):
        self.x = x
        self.mask = mask
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.enqueued = time.perf_counter()
        self.deadline = deadline   # absolute perf_counter time, or None
        self.abandoned = False     # client gave up waiting (timeout race)
        self.request_id = request_id   # X-Request-Id (tracing, ISSUE-8)
        self.t_start: Optional[float] = None  # dispatch start (worker)
        self.t_end: Optional[float] = None    # dispatch end (worker)
        self.tenant = DEFAULT_TENANT   # billing identity (ISSUE-16)

    @property
    def key(self):
        """Dispatch-compatibility key: trailing shape + mask presence."""
        return (self.x.shape[1:], self.x.dtype.str,
                None if self.mask is None else self.mask.shape[1:])


class MicroBatcher:
    """Request queue + coalescing worker in front of a dispatch function.

    `dispatch(x, mask, n_real)` receives the stacked real rows (the
    callee pads to its bucket) and must return row-aligned outputs as a
    numpy array of at least `n_real` rows.  `submit()` blocks the
    calling thread until its slice of the result is ready and is safe to
    call from any number of threads.

    `max_queue_depth` bounds admission (None = unbounded, the pre-ISSUE-4
    behavior); `default_deadline_s` applies a per-request deadline when
    the caller does not pass one; `breaker` (a `CircuitBreaker`) guards
    the dispatch path; `max_bisect_depth` bounds poison-isolation
    recursion (0 disables bisection).
    """

    def __init__(self, dispatch: Callable, max_batch: int = 32,
                 max_wait_ms: float = 2.0,
                 metrics: Optional[ServingMetrics] = None,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 max_bisect_depth: int = 6,
                 bisect_policy: RetryPolicy = _BISECT_POLICY,
                 tracer: Optional[TraceRecorder] = None,
                 tenants=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if max_queue_depth is not None and max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1 or None, got "
                             f"{max_queue_depth}")
        self._dispatch = dispatch
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue_depth = max_queue_depth
        self.default_deadline_s = default_deadline_s
        self.breaker = breaker
        self.max_bisect_depth = int(max_bisect_depth)
        self.bisect_policy = bisect_policy
        # request tracing (ISSUE-8): None = tracing off.  The recorder
        # is bounded, and span assembly is a handful of dict builds per
        # request — the bench `obs` row gates the overhead.  The compile
        # watcher is resolved ONCE: per-request global lookups (and the
        # ensure-installed probe) are off the hot path.
        self.tracer = tracer
        self._compile_watch = compile_watcher() if tracer is not None \
            else None
        # multi-tenant admission gate (ISSUE-16): None = unmetered (the
        # historic single-tenant behavior, bit for bit)
        self.tenants = TenantRegistry.coerce(tenants)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        if breaker is not None:
            breaker.add_listener(self.metrics.set_breaker_state)
            self.metrics.set_breaker_state(breaker.state)
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._running = False
        self._accepting = True
        self._in_flight = 0
        self._thread: Optional[threading.Thread] = None

    # ---- client side ------------------------------------------------------

    def _retry_after_locked(self) -> float:
        """Retry-After hint for an admission rejection: roughly the time
        for the current backlog to clear (p50 latency per queued item,
        floored at the coalescing window)."""
        lat = self.metrics.latency.summary()
        per_item = (lat.get("p50_ms", 50.0) or 50.0) / 1e3
        return max(0.1, self.max_wait_s + per_item * len(self._queue))

    def submit(self, x: np.ndarray, mask: Optional[np.ndarray] = None,
               timeout: Optional[float] = None,
               deadline_s: Optional[float] = None,
               request_id: Optional[str] = None,
               tenant: Optional[str] = None) -> np.ndarray:
        """Enqueue a [n, ...] request and block for its [n, ...] outputs.

        `timeout` bounds the *client's* wait; `deadline_s` (default
        `default_deadline_s`) is carried on the queue item so the worker
        sheds the request before dispatch once it expires — a client that
        has already given up must not cost device time.  `request_id`
        names the request's trace when a tracer is attached (one is
        minted otherwise).  `tenant` (default "default") is the billing
        identity: with a `TenantRegistry` installed the request is
        charged one quota token per row BEFORE the shared admission
        gate, so an over-quota tenant's refusal (`TenantQuotaError`,
        HTTP 429) never consumes the queue bound (ISSUE-16)."""
        x = np.asarray(x)
        if x.ndim < 2 or x.shape[0] < 1:
            raise ValueError(f"request must be [n, ...] with n >= 1, got "
                             f"shape {x.shape}")
        if x.shape[0] > self.max_batch:
            raise ValueError(f"request rows ({x.shape[0]}) exceed max_batch "
                             f"({self.max_batch}); split the request")
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        if request_id is None and self.tracer is not None:
            request_id = new_request_id()
        item = _Pending(x, None if mask is None else np.asarray(mask),
                        request_id=request_id)
        if self.tenants is not None:
            item.tenant = self.tenants.normalize(tenant)
        elif tenant is not None and str(tenant) != DEFAULT_TENANT:
            raise ValueError(
                f"unknown tenant {str(tenant)!r}: no tenant registry "
                f"is installed (serve -tenants, or "
                f"MicroBatcher(tenants=...))")
        if deadline_s is not None:
            item.deadline = item.enqueued + float(deadline_s)
        with self._cond:
            if self._accepting and self.tenants is not None:
                try:
                    # one quota token per example row, charged before
                    # the shared gate (the 429 is the CLIENT's budget,
                    # not the server's capacity)
                    self.tenants.meter.charge(item.tenant,
                                              int(x.shape[0]))
                except TenantQuotaError:
                    self.metrics.record_rejected()
                    self.metrics.record_tenant("rejected", item.tenant)
                    self.metrics.record_tenant("throttled", item.tenant)
                    raise
            try:
                check_admission(
                    accepting=self._accepting, breaker=self.breaker,
                    queue_depth=len(self._queue),
                    max_queue_depth=self.max_queue_depth,
                    metrics=self.metrics,
                    retry_after_s=self._retry_after_locked, what="serving")
            except ServingError:
                # the shared gate counted the rejection; the per-tenant
                # ledger rides along so the fleet reconciliation
                # (submitted == Σ tenants) keeps holding (ISSUE-16)
                if self.tenants is not None:
                    self.metrics.record_tenant("rejected", item.tenant)
                raise
            if not self._running:
                self._start_locked()
            self._queue.append(item)
            self.metrics.set_queue_depth(len(self._queue))
            self._cond.notify_all()
        if not item.event.wait(timeout):
            # Cancel rather than abandon: a still-queued request is
            # removed (otherwise retry-on-timeout clients fill the queue
            # with zombie work the device still executes); one the worker
            # already took is MARKED abandoned — its rows are excluded
            # from the dispatch group if it has not formed yet (the
            # pop-vs-timeout race), and a dispatch already in flight has
            # its result discarded and counted as shed.
            now = time.perf_counter()
            with self._cond:
                try:
                    self._queue.remove(item)
                    self.metrics.set_queue_depth(len(self._queue))
                    self.metrics.record_shed()
                    if self.tenants is not None:
                        self.metrics.record_tenant("shed", item.tenant)
                except ValueError:
                    item.abandoned = True  # worker holds it: discard rows
                    # exactly-once shed accounting for the race: a result
                    # delivered before we marked is discarded and shed
                    # HERE; an error means the worker already resolved
                    # (and, for its own deadline sheds, already counted);
                    # an unset event means the worker's finally counts it
                    if item.event.is_set() and item.error is None:
                        self.metrics.record_shed()
                        if self.tenants is not None:
                            self.metrics.record_tenant("shed",
                                                       item.tenant)
                resolved_with_error = (item.event.is_set()
                                       and item.error is not None)
            if (item.deadline is not None and now >= item.deadline
                    and not resolved_with_error):
                # count a deadline miss only when the server-side
                # deadline actually EXPIRED and the worker did not
                # already resolve (and account) the item — a bare
                # client-wait timeout is client impatience, not shedding
                self.metrics.record_deadline_missed()
                if self.tenants is not None:
                    self.metrics.record_tenant("deadline_missed",
                                               item.tenant)
            self._trace_item(item, time.perf_counter(), "timeout")
            raise DeadlineExceededError(
                f"serving request timed out after {timeout}s")
        done = time.perf_counter()
        if item.error is not None:
            self._trace_item(item, done, "error")
            raise item.error
        qw = comp = None
        if item.t_start is not None:
            # the split the stats endpoint reports: time spent waiting
            # for a dispatch slot vs time inside the dispatch itself
            qw = item.t_start - item.enqueued
            comp = (item.t_end if item.t_end is not None
                    else done) - item.t_start
        self.metrics.record_request(done - item.enqueued,
                                    queue_wait_s=qw, compute_s=comp)
        if self.tenants is not None:
            # tenant completion ledger (ISSUE-16): served count, rows
            # out, and the SLO window sample behind the burn gauge
            self.metrics.record_tenant("requests", item.tenant)
            self.tenants.meter.record_out(item.tenant, int(x.shape[0]))
            self.tenants.slo.record(item.tenant, done - item.enqueued)
            self.metrics.set_tenant_burn(
                item.tenant, self.tenants.slo.burn_rate(item.tenant))
        self._trace_item(item, done, "ok")
        return item.result

    def _trace_item(self, item: _Pending, done: float,
                    status: str) -> None:
        """Record the request's lifecycle trace (queue_wait -> dispatch
        -> respond, plus any overlapping xla_compile spans — the
        off-ladder-recompile-in-THIS-request signal).  The hot path
        captures one raw tuple; the span dicts materialize only when
        /trace/recent is read (`record_lazy`)."""
        if self.tracer is None:
            return
        compiles = None
        if (item.t_start is not None
                and self._compile_watch.any_since(item.t_start)):
            compiles = self._compile_watch.events_between(
                item.t_start, item.t_end if item.t_end is not None
                else done)
        self.tracer.record_lazy(_build_serving_trace, (
            item.request_id or new_request_id(), item.enqueued,
            item.t_start, item.t_end, done, status,
            int(item.x.shape[0]),
            str(item.error) if item.error is not None else None,
            compiles, time.time()))

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # fail anything still queued rather than leaving clients hung
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self.metrics.set_queue_depth(0)
        for item in leftovers:
            self.metrics.record_shed()
            if self.tenants is not None:
                self.metrics.record_tenant("shed", item.tenant)
            item.error = ServingUnavailableError("batcher stopped")
            item.event.set()

    # ---- drain lifecycle --------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admission: subsequent submits raise
        `ServingUnavailableError`; queued + in-flight work still runs."""
        with self._cond:
            self._accepting = False
            self._cond.notify_all()

    def drain(self, grace_s: float = 5.0) -> bool:
        """Stop admission, wait up to `grace_s` for queued + in-flight
        work to finish, then stop the worker (anything still queued at
        that point fails with `ServingUnavailableError`).  Returns True
        when the queue fully drained within the grace window."""
        self.begin_drain()
        deadline = time.perf_counter() + max(0.0, grace_s)
        with self._cond:
            while self._queue or self._in_flight:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(min(0.05, remaining))
            drained = not self._queue and not self._in_flight
        self.stop()
        return drained

    # ---- worker side ------------------------------------------------------

    def _start_locked(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="micro-batcher")
        self._thread.start()

    def _shed_doomed_locked(self) -> None:
        """Drop already-expired items from the queue — doomed work must
        not reach the device; they fail with `DeadlineExceededError`.
        Abandoned items are swept defensively too, though in normal
        operation they cannot appear here (a still-queued item's client
        removes it itself; `abandoned` marks only popped items).  One
        rebuild pass: under an overload storm most of the queue can
        expire at once, and per-item `deque.remove` would be O(n^2)
        inside the lock every submit is waiting on."""
        now = time.perf_counter()
        kept, shed = collections.deque(), 0
        for item in self._queue:
            if item.abandoned:
                shed += 1
                if self.tenants is not None:
                    self.metrics.record_tenant("shed", item.tenant)
                item.event.set()
            elif item.deadline is not None and now >= item.deadline:
                shed += 1
                self.metrics.record_deadline_missed()
                if self.tenants is not None:
                    self.metrics.record_tenant("shed", item.tenant)
                    self.metrics.record_tenant("deadline_missed",
                                               item.tenant)
                item.error = DeadlineExceededError(
                    f"deadline exceeded after "
                    f"{now - item.enqueued:.3f}s in queue; shed before "
                    f"dispatch")
                item.event.set()
            else:
                kept.append(item)
        if shed:
            self._queue = kept
            self.metrics.record_shed(shed)
            self.metrics.set_queue_depth(len(kept))

    def _collect(self):
        """Take the queue head plus same-shape followers.

        Two regimes, which is what makes the batcher both low-latency
        and high-occupancy:

        - worker BUSY (queue non-empty when it frees up): dispatch
          immediately — the previous dispatch's duration already served
          as the coalescing window, so waiting again only adds latency
          (and on hosts with coarse timers, any timed wait costs ~1ms);
        - worker IDLE (had to block for the head): hold the head open up
          to `max_wait_ms` from its arrival so a burst's co-travellers
          can join its dispatch.
        """
        with self._cond:
            self._shed_doomed_locked()
            was_idle = not self._queue
            while self._running and not self._queue:
                self._cond.wait(0.1)
                self._shed_doomed_locked()
            if not self._running:
                return []
            head = self._queue[0]
            if was_idle and self.max_wait_s > 0:
                deadline = head.enqueued + self.max_wait_s
                while self._running:
                    rows = sum(i.x.shape[0] for i in self._queue
                               if i.key == head.key)
                    remaining = deadline - time.perf_counter()
                    if rows >= self.max_batch or remaining <= 0:
                        break
                    self._cond.wait(remaining)  # submits notify early
            # deadlines may have passed during the coalescing window —
            # shed BEFORE the dispatch group forms, then regroup from
            # whatever head remains
            self._shed_doomed_locked()
            if not self._queue:
                return []
            head = self._queue[0]
            group, rows, rest = [], 0, collections.deque()
            while self._queue:
                item = self._queue.popleft()
                if (item.key == head.key
                        and rows + item.x.shape[0] <= self.max_batch):
                    group.append(item)
                    rows += item.x.shape[0]
                else:
                    rest.append(item)
            self._queue.extend(rest)
            self._in_flight = len(group)
            self.metrics.set_queue_depth(len(self._queue))
            return group

    def _execute(self, group, depth, delays):
        """Dispatch `group` as one concatenated batch; on failure bisect
        (bounded depth, backoff between sub-dispatches) so exactly the
        poison item(s) fail and the rest still get their byte-identical
        row slices.  Returns (n_ok, n_failed) items.  The try covers
        concat AND result scatter, not just the dispatch: a MemoryError
        building the batch or a malformed dispatch return must become
        per-request errors, never escape to kill the worker."""
        # dispatch window stamps: feed the queue-wait/compute latency
        # split and the per-request trace spans
        t0 = time.perf_counter()
        for g in group:
            g.t_start = t0
        try:
            x = (group[0].x if len(group) == 1
                 else np.concatenate([g.x for g in group], axis=0))
            mask = None
            if group[0].mask is not None:
                mask = (group[0].mask if len(group) == 1
                        else np.concatenate([g.mask for g in group],
                                            axis=0))
            out = np.asarray(self._dispatch(x, mask, x.shape[0]))
            for g in group:
                g.t_end = time.perf_counter()
            off = 0
            results = []
            for g in group:
                n = g.x.shape[0]
                results.append(out[off:off + n])
                if results[-1].shape[:1] != (n,):
                    raise ValueError(
                        f"dispatch returned {out.shape} rows; cannot "
                        f"slice {n} rows at offset {off}")
                off += n
        except BaseException as e:  # noqa: BLE001 — fail/bisect the GROUP, keep serving
            if len(group) == 1 or depth >= self.max_bisect_depth:
                for g in group:
                    g.error = e
                return 0, len(group)
            time.sleep(max(0.0, next(delays, self.bisect_policy.max_delay)))
            mid = len(group) // 2
            ok_lo, bad_lo = self._execute(group[:mid], depth + 1, delays)
            ok_hi, bad_hi = self._execute(group[mid:], depth + 1, delays)
            return ok_lo + ok_hi, bad_lo + bad_hi
        for g, res in zip(group, results):
            g.result = res
        return len(group), 0

    def _run(self) -> None:
        while True:
            group = self._collect()
            if not group:
                with self._cond:
                    if not self._running:
                        return
                continue
            try:
                # final abandoned check under the lock: a client timing
                # out concurrently with the pop marked its item, and its
                # rows must not ride the dispatch
                with self._cond:
                    live = []
                    for g in group:
                        if g.abandoned:
                            self.metrics.record_shed()
                            if self.tenants is not None:
                                self.metrics.record_tenant("shed",
                                                           g.tenant)
                            g.event.set()
                        else:
                            live.append(g)
                    group = live
                    self._in_flight = len(group)
                if not group:
                    continue
                if (self.breaker is not None
                        and not self.breaker.allow_dispatch()):
                    err = CircuitOpenError(
                        "circuit breaker open: dispatch fast-failed",
                        retry_after_s=self.breaker.retry_after_s())
                    for g in group:
                        self.metrics.record_shed()
                        if self.tenants is not None:
                            self.metrics.record_tenant("shed", g.tenant)
                        g.error = err
                    continue
                try:
                    n_ok, n_bad = self._execute(
                        group, 0, backoff_delays(self.bisect_policy))
                except Exception as e:  # noqa: BLE001 — the worker survives ANY group failure
                    # belt-and-braces: _execute's own handler should have
                    # absorbed everything, but the worker thread dying
                    # would hang every future submit, so convert strays
                    # into per-request errors here
                    n_ok, n_bad = 0, len(group)
                    for g in group:
                        if g.error is None and g.result is None:
                            g.error = e
                if self.breaker is not None:
                    # a whole-dispatch failure is one where bisection
                    # salvaged nothing; isolated poison leaves the
                    # serving plane healthy.  Deliberate tradeoff: a
                    # POISON request dispatched alone (no coalescing
                    # partner) is indistinguishable from a failing
                    # device, so a client retrying one poison payload
                    # `failure_threshold` times on an otherwise-idle
                    # server does trip the breaker — the alternative
                    # (ignoring singleton failures) would keep a truly
                    # dead device from ever opening it.
                    if n_ok:
                        self.breaker.record_success()
                    else:
                        self.breaker.record_failure()
                if n_ok and n_bad:
                    self.metrics.record_poison_isolated(n_bad)
            finally:
                with self._cond:
                    for g in group:
                        # a client that abandoned mid-dispatch is gone:
                        # its delivered result/error is discarded — count
                        # the shed on whichever side observes the race
                        # second (see submit's timeout path)
                        if g.abandoned and not g.event.is_set():
                            self.metrics.record_shed()
                            if self.tenants is not None:
                                self.metrics.record_tenant("shed",
                                                           g.tenant)
                        # never resolve a client with silent None: if
                        # neither result nor error was assigned, the
                        # cycle aborted — fail typed
                        if g.error is None and g.result is None:
                            g.error = ServingUnavailableError(
                                "dispatch cycle aborted")
                        g.event.set()
                    self._in_flight = 0
                    self._cond.notify_all()
