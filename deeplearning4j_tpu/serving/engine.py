"""ServingEngine: a MultiLayerNetwork behind the micro-batcher + ladder.

The request path every endpoint shares:

    client threads -> MicroBatcher (coalesce within max_wait_ms)
                   -> BucketLadder (pad batch/length up the ladder)
                   -> MultiLayerNetwork.output_bucketed (cached jitted
                      forward, one program per ladder shape)
                   -> slice rows back per request

plus an explicit `warmup()` that pre-compiles every ladder shape before
traffic, and a compile-count guard: dispatching a shape outside the
ladder's bound raises instead of silently compiling program #N+1 on the
request path.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.obs.compilewatch import (
    compile_scope,
    compile_watcher,
)
from deeplearning4j_tpu.obs.registry import MetricsRegistry
from deeplearning4j_tpu.obs.trace import TraceRecorder
from deeplearning4j_tpu.serving.batcher import MicroBatcher
from deeplearning4j_tpu.serving.bucketing import BucketLadder
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.resilience import (
    CircuitBreaker,
    UnservableShapeError,
)


class ServingEngine:
    """Thread-safe batched inference over one model.

    `predict_proba(x)` / `predict(x)` accept a [n, ...] request (n up to
    `max_batch`) from any thread; rows ride whatever dispatch the
    batcher forms.  Sequence inputs ([n, T, ...]) are padded up the
    length ladder with per-example masks, so padding never changes
    results.
    """

    def __init__(self, net, ladder: Optional[BucketLadder] = None,
                 max_batch: Optional[int] = None, max_wait_ms: float = 2.0,
                 metrics: Optional[ServingMetrics] = None,
                 max_programs: Optional[int] = None,
                 input_dtype=np.float32,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 breaker_threshold: Optional[int] = 5,
                 breaker_cooldown_s: float = 1.0,
                 quantize: Optional[str] = None,
                 tracer: Optional[TraceRecorder] = None,
                 registry: Optional[MetricsRegistry] = None,
                 tenants=None):
        self.net = net
        self.ladder = ladder if ladder is not None else BucketLadder()
        # Precision plane (ISSUE-5): `quantize="int8"` serves per-channel
        # symmetric int8 weights (~4x smaller resident params,
        # dequantize-in-kernel matmuls).  The quantized view is built
        # once — at warmup() normally, or lazily before the first
        # dispatch — so every request ever served sees the SAME weights.
        if quantize not in (None, "int8"):
            raise ValueError(f"unsupported quantize={quantize!r} "
                             f"(None or 'int8')")
        self.quantize = quantize
        self._qnet = None
        self._qlock = threading.Lock()
        # every request is cast to ONE dtype (the one warmup() compiles)
        # so client-side dtype drift (float64 lists, int features) can
        # never mint extra programs or trip the guard; pass
        # input_dtype=None for models whose inputs must stay integral
        # (embedding front ends) — the guard then keys each dtype seen
        self.input_dtype = (None if input_dtype is None
                            else np.dtype(input_dtype))
        self.metrics = metrics if metrics is not None else ServingMetrics()
        # observability plane (ISSUE-8): publish this engine's metric
        # cells on the server's registry and trace every request; the
        # process-wide compile watcher attributes XLA compiles to the
        # dispatch shape that triggered them (compiles_total)
        self.tracer = tracer
        if registry is not None:
            self.metrics.register_into(registry, plane="classifier")
        # install the process-wide compile listener BEFORE any warmup
        # compile fires, or the first programs go uncounted
        compile_watcher()
        self.max_programs = (max_programs if max_programs is not None
                             else self.ladder.program_bound)
        self._shape_lock = threading.Lock()
        self._seen_shapes = {}   # dtype str -> set of dispatch shapes
        # serving-plane resilience (ISSUE-4): circuit breaker on the
        # dispatch path, bounded admission + deadlines on the queue
        self.breaker = (CircuitBreaker(
            failure_threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
            on_transition=self.metrics.set_breaker_state)
            if breaker_threshold else None)
        self.batcher = MicroBatcher(
            self._dispatch,
            max_batch=(max_batch if max_batch is not None
                       else self.ladder.max_batch),
            max_wait_ms=max_wait_ms, metrics=self.metrics,
            max_queue_depth=max_queue_depth,
            default_deadline_s=default_deadline_s,
            breaker=self.breaker, tracer=tracer, tenants=tenants)
        if self.batcher.max_batch > self.ladder.max_batch:
            raise ValueError(
                f"max_batch ({self.batcher.max_batch}) exceeds the "
                f"ladder's top bucket ({self.ladder.max_batch})")

    # ---- dispatch side ----------------------------------------------------

    def _model(self):
        """The dispatch target: the quantized view when quantize is set
        (built on first use, under a lock so concurrent first requests
        cannot quantize twice), else the float net."""
        if self.quantize is None:
            return self.net
        # double-checked fast path: after the first request this is one
        # unlocked read per dispatch; the slow path re-checks under the
        # lock, and the build happens exactly once
        if self._qnet is None:  # noqa: LCK101 — DCL fast path, locked recheck below
            from deeplearning4j_tpu.precision import QuantizedNet

            with self._qlock:
                if self._qnet is None:
                    self._qnet = QuantizedNet(self.net, dtype=self.quantize)
        return self._qnet  # noqa: LCK101 — set-once under _qlock, never cleared

    def _guard_shape(self, shape, dtype: str) -> None:
        """Compile-count guard: a dispatch shape beyond the ladder bound
        means bucketing failed — refuse to compile program #N+1.  The
        bound is PER dtype: with the default `input_dtype` coercion only
        one dtype ever occurs, and with `input_dtype=None` each client
        dtype legitimately owns its own ladder-sized program set."""
        with self._shape_lock:
            seen = self._seen_shapes.setdefault(dtype, set())
            if shape in seen:
                return
            if len(seen) >= self.max_programs:
                # the guard's evidence now includes the first-class
                # compile counter (ISSUE-8): how many XLA compiles this
                # engine's dispatch scopes actually observed
                observed = compile_watcher().total(prefix="classifier:")
                raise UnservableShapeError(
                    f"compile-count guard: dispatch shape {shape} "
                    f"({dtype}) would exceed the {self.max_programs}-"
                    f"program bound (seen: {sorted(seen)}; "
                    f"compiles_total observed: {observed}); the bucket "
                    f"ladder is not covering the traffic")
            seen.add(shape)

    def _dispatch(self, x: np.ndarray, mask: Optional[np.ndarray],
                  n_real: int) -> np.ndarray:
        bucket = self.ladder.batch_bucket(n_real)
        shape = (bucket,) + tuple(x.shape[1:])
        self._guard_shape(shape, x.dtype.str)
        # attribute any XLA compile this dispatch triggers to its ladder
        # shape: compiles_total{program_key="classifier:..."} — on the
        # warmed path this scope observes nothing
        with compile_scope(f"classifier:{shape}"):
            out = self._model().output_bucketed(x, mask=mask,
                                                ladder=self.ladder)
        self.metrics.record_dispatch(n_real, bucket)
        return np.asarray(out)

    # ---- client side ------------------------------------------------------

    def _prepare(self, x):
        """Normalize dtype (every request serves as `input_dtype` — the
        dtype warmup() compiled), then length-bucket sequence inputs
        (mask the padding).  Returns (x, mask, original_T)."""
        x = np.asarray(x)
        if self.input_dtype is not None and x.dtype != self.input_dtype:
            x = x.astype(self.input_dtype)
        if x.ndim >= 3 and self.ladder.length_buckets is not None:
            t = int(x.shape[1])
            x, mask = self.ladder.pad_length(x)
            return x, mask, t
        return x, None, None

    def predict_proba(self, x, timeout: Optional[float] = None,
                      deadline_s: Optional[float] = None,
                      request_id: Optional[str] = None,
                      tenant: Optional[str] = None) -> np.ndarray:
        """[n, ...] features -> [n, classes] output activations (or
        [n, T, classes] for sequence-tagging outputs, sliced back to the
        request's own T).  `deadline_s` rides the queue item so expired
        work is shed before dispatch (docs/robustness.md); `request_id`
        names the request's trace (``X-Request-Id``); `tenant` is the
        billing identity the batcher's quota gate charges (ISSUE-16)."""
        x, mask, t = self._prepare(x)
        out = self.batcher.submit(x, mask, timeout=timeout,
                                  deadline_s=deadline_s,
                                  request_id=request_id, tenant=tenant)
        if t is not None and out.ndim == 3 and out.shape[1] != t:
            out = out[:, :t]       # drop the length-bucket padding steps
        return out

    def predict(self, x, timeout: Optional[float] = None,
                deadline_s: Optional[float] = None,
                request_id: Optional[str] = None,
                tenant: Optional[str] = None) -> np.ndarray:
        """[n, ...] features -> [n] argmax class indices."""
        return np.argmax(self.predict_proba(x, timeout=timeout,
                                            deadline_s=deadline_s,
                                            request_id=request_id,
                                            tenant=tenant),
                         axis=-1)

    # ---- lifecycle --------------------------------------------------------

    def warmup(self, example: np.ndarray) -> int:
        """Pre-compile every ladder shape from one example row's shape
        (`example` is [...] or [1, ...]); returns the number of shapes
        warmed.  Run this before traffic: afterwards NO request can
        trigger an XLA compile (the guard enforces it).  With
        `quantize` set, the weights are quantized HERE — before any
        compile — so the warmed programs are the int8 programs."""
        model = self._model()
        example = np.asarray(example)
        row = (example[0] if example.ndim > 1 and example.shape[0] == 1
               else example)
        lengths = ([None] if row.ndim < 2 or self.ladder.length_buckets
                   is None else list(self.ladder.length_buckets))
        warmed = 0
        dt = self.input_dtype if self.input_dtype is not None else np.float32
        for b in self.ladder.batch_buckets:
            for t in lengths:
                shape = (b,) + ((t,) + row.shape[1:] if t is not None
                                else row.shape)
                x = np.zeros(shape, dt)
                mask = (np.ones((b, t), np.float32) if t is not None
                        else None)
                # straight to the model — warmup is not traffic, so it
                # registers shapes with the guard but not the metrics
                wshape = (b,) + tuple(x.shape[1:])
                self._guard_shape(wshape, x.dtype.str)
                with compile_scope(f"classifier:{wshape}"):
                    model.output_bucketed(x, mask=mask, ladder=self.ladder)
                warmed += 1
        return warmed

    def stats(self) -> Dict:
        out = self.metrics.snapshot()
        out["bucket_ladder"] = {
            "batch": list(self.ladder.batch_buckets),
            "length": (list(self.ladder.length_buckets)
                       if self.ladder.length_buckets else None)}
        with self._shape_lock:
            out["compiled_programs"] = sum(
                len(s) for s in self._seen_shapes.values())
        out["program_bound"] = self.max_programs
        # first-class compile accounting (ISSUE-8): XLA compiles the
        # watcher attributed to this engine's dispatch/warmup scopes
        out["compiles_total"] = compile_watcher().total(
            prefix="classifier:")
        out["accepting"] = self.accepting
        out["quantize"] = self.quantize
        # snapshot the reference WITHOUT _qlock: _model() holds that
        # lock for the entire first QuantizedNet build (compile-scale),
        # and a stats scrape must not stall behind it.  The unlocked
        # read is safe — the reference is assigned exactly once, under
        # the lock, after the view is fully built (GIL-atomic publish)
        qnet = self._qnet  # noqa: LCK101 — set-once publish; locking would stall scrapes on the first build
        if qnet is not None:
            out["quantization"] = qnet.quantization_report()
        return out

    @property
    def accepting(self) -> bool:
        """False once draining/stopped — the /readyz signal."""
        return self.batcher._accepting

    def ready(self) -> bool:
        """Readiness for traffic: accepting admissions and the circuit
        breaker is not open (docs/robustness.md serving lifecycle)."""
        if not self.accepting:
            return False
        return self.breaker is None or self.breaker.state != "open"

    def begin_drain(self) -> None:
        """Stop admission; queued + in-flight requests still complete."""
        self.batcher.begin_drain()

    def drain(self, grace_s: float = 5.0) -> bool:
        """Graceful shutdown: stop admission, let in-flight work finish
        within `grace_s`, then stop the worker.  Returns True when the
        queue fully drained."""
        return self.batcher.drain(grace_s)

    def stop(self) -> None:
        self.batcher.stop()
