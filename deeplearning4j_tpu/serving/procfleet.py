"""Process-per-replica fleet supervision (ISSUE-10, ROADMAP item 5).

The reference DL4J pushed scale-out to external runners (Spark /
ParameterServer) and trusted the CLUSTER to resurrect dead workers; our
fleet router (serving/fleet.py) ejects a dead replica and fails traffic
over, but nothing ever restarted it — a `kill -9` on a real `dl4j
serve` worker left a corpse forever.  `FleetSupervisor` is the layer
that owns worker PROCESSES end-to-end:

- **Crash detection** — every poll tick checks `Popen` exit status AND
  the worker's `/readyz` together, classifying deaths into a closed
  vocabulary:

  * ``clean``  — exit 0 or SIGTERM (a requested stop / graceful drain);
  * ``crash``  — any other exit (kill -9, a boot flake's nonzero exit,
    a segfault) or a worker that never went ready within
    `ready_timeout_s` (killed, with its log tail in the report);
  * ``wedged`` — the process is ALIVE but `/readyz` has failed
    `wedge_threshold` consecutive probes (SIGSTOP, a deadlocked
    worker): the supervisor hard-kills it and treats it as a death,
    because a wedged port is worse than a dead one — connections hang
    instead of failing fast.

- **Backoff restart** — a crashed worker respawns after an exponential,
  jittered delay (`RestartPolicy.backoff_s`); the resurrected worker
  re-enters rotation through the existing warm-then-attach discipline:
  it is attached to the router only once its `/readyz` goes green, so
  in-flight traffic NEVER routes to a cold port.  Each incarnation's
  replica is named ``{worker}#{k}`` — failover exclusion keys on the
  name, so a request that excluded the corpse never skips the
  resurrection.

- **Crash-loop quarantine** — `crash_loop_threshold` deaths inside
  `crash_loop_window_s` quarantines the worker behind a typed
  `CrashLoopError` surfaced in `/fleet/stats` (`supervision` section)
  and the `fleet_process_quarantines_total` counter; the poll loop
  skips it (no restart storm, no stalled health sweeps) until
  `release()`.

- **Cross-host attach** — a `WorkerSpec` with no ``command`` is a
  worker this supervisor did NOT spawn (another host's, another
  orchestrator's): liveness is probes only, restart authority is
  delegated to the pluggable `RestartPolicy.restart()` hook, and a
  worker that comes back (same URL) is re-attached through the same
  warm-then-attach gate.

Per-worker stdout/stderr are captured to size-rotated log files
(`runtime.launcher.spawn_logged`); crash and ready-timeout reports
attach the last ~20 lines.  Supervision events publish through the
PR-8 obs registry as ``fleet_process_*`` counters
(`collector_samples`), and `FleetRouter.fleet_stats()` inlines
`stats()` whenever a supervisor is installed.  Deterministic process
chaos — kill -9 at dispatch K, SIGSTOP wedge, boot-flake exits — lives
in `resilience.chaos.ProcessChaosConfig` / `chaos_procfleet`;
docs/robustness.md "Process supervision" has the state diagram and the
death-classification table.
"""

from __future__ import annotations

import collections
import http.client
import pathlib
import random
import sys
import tempfile
import threading
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.serving.resilience import ServingError


class CrashLoopError(ServingError):
    """A worker died `crash_loop_threshold` times inside
    `crash_loop_window_s` and was quarantined: restarting it again
    would just burn the backoff schedule on a deterministic failure
    (bad binary, bad port, bad model dir).  Surfaced — not raised into
    the poll loop — via `FleetSupervisor.stats()` / `/fleet/stats` so
    the health plane keeps running while a human (or `release()`)
    decides."""


# Death classifications (the closed vocabulary stats and tests use):
DEATH_CLEAN = "clean"
DEATH_CRASH = "crash"
DEATH_WEDGED = "wedged"

# Worker lifecycle states:
WORKER_STARTING = "starting"        # spawned/probing, not yet in rotation
WORKER_READY = "ready"              # attached, serving
WORKER_BACKOFF = "backoff"          # died; waiting out the restart delay
WORKER_QUARANTINED = "quarantined"  # crash-looped; needs release()
WORKER_STOPPED = "stopped"          # clean stop requested and done
WORKER_DOWN = "down"                # URL-attached worker unreachable


_STUB_WORKER = pathlib.Path(__file__).with_name("_stub_worker.py")


def stub_worker_command(port: int, host: str = "127.0.0.1", *,
                        ready_delay_s: float = 0.0,
                        never_ready: bool = False,
                        boot_exit_code: Optional[int] = None) -> List[str]:
    """Command line for one stdlib stub worker (`_stub_worker.py`) —
    run BY FILE PATH so the child skips the package's jax import and
    boots in ~100ms.  The supervision test/bench body."""
    cmd = [sys.executable, str(_STUB_WORKER), "--port", str(int(port)),
           "--host", host]
    if ready_delay_s:
        cmd += ["--ready-delay-s", str(float(ready_delay_s))]
    if never_ready:
        cmd.append("--never-ready")
    if boot_exit_code is not None:
        cmd += ["--boot-exit-code", str(int(boot_exit_code))]
    return cmd


class RestartPolicy:
    """Restart scheduling + crash-loop bookkeeping, pluggable per
    supervisor.

    - `backoff_s(k)`: the delay before respawn number `k` (0-based
      count of consecutive crashes) — exponential
      ``initial * factor**k`` capped at `backoff_max_s`, +/- `jitter`
      fraction uniform (same shape as `resilience.retry.RetryPolicy`,
      so a fleet of workers killed together does not thundering-herd
      the same restart instant).
    - `quarantine_due(death_times, now)`: True when
      `crash_loop_threshold` deaths landed inside
      `crash_loop_window_s`.
    - `restart(worker)`: the delegation hook for workers the
      supervisor did NOT spawn (cross-host URL attach) — the base
      policy has no authority there and returns False (probes only);
      subclass it to call a remote orchestrator.  Returning True counts
      a `restart_delegations` event; either way the supervisor keeps
      probing and re-attaches when the endpoint comes back.
    - `respawn_command(worker, command)`: rewrite the command a
      respawn runs — the ELASTIC restart seam: an elastic training
      worker that crashed on N replicas can resurrect on a shrunken
      host by having its `-replicas N` rewritten (see
      `rewrite_replicas` / `ElasticRestartPolicy`).  The base policy
      returns the command unchanged.
    """

    def __init__(self, backoff_initial_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 backoff_factor: float = 2.0, jitter: float = 0.25,
                 crash_loop_threshold: int = 3,
                 crash_loop_window_s: float = 60.0,
                 rng: Optional[random.Random] = None):
        if crash_loop_threshold < 1:
            raise ValueError(f"crash_loop_threshold must be >= 1, got "
                             f"{crash_loop_threshold}")
        self.backoff_initial_s = float(backoff_initial_s)
        self.backoff_max_s = float(backoff_max_s)
        self.backoff_factor = float(backoff_factor)
        self.jitter = float(jitter)
        self.crash_loop_threshold = int(crash_loop_threshold)
        self.crash_loop_window_s = float(crash_loop_window_s)
        self._rng = rng if rng is not None else random.Random()

    def backoff_s(self, consecutive_crashes: int) -> float:
        delay = min(self.backoff_initial_s
                    * self.backoff_factor ** max(0, consecutive_crashes),
                    self.backoff_max_s)
        if self.jitter:
            delay += delay * self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, delay)

    def quarantine_due(self, death_times, now: float) -> bool:
        recent = [t for t in death_times
                  if now - t <= self.crash_loop_window_s]
        return len(recent) >= self.crash_loop_threshold

    def restart(self, worker: "SupervisedWorker") -> bool:
        return False

    def respawn_command(self, worker: "SupervisedWorker",
                        command: List[str]) -> List[str]:
        """The command a (re)spawn of `worker` runs; called by the
        supervisor's `_spawn_command` on EVERY spawn (inspect
        ``worker.incarnation``/``consecutive_crashes`` to act only on
        respawns).  Base policy: unchanged."""
        return command


def rewrite_replicas(command: List[str], n: int) -> List[str]:
    """Rewrite the `-replicas`/`--replicas` value in a worker command
    line to `n` (appending the flag when absent) — the elastic-restart
    rewrite a `RestartPolicy.respawn_command` applies so a training
    worker saved on N replicas resurrects on an M-replica host.  The
    checkpoint plane makes the count change safe: snapshots restore
    onto any replica count (`runtime.checkpoint` N→M)."""
    out = list(command)
    for i, arg in enumerate(out):
        if arg in ("-replicas", "--replicas") and i + 1 < len(out):
            out[i + 1] = str(int(n))
            return out
        if arg.startswith(("-replicas=", "--replicas=")):
            out[i] = f"{arg.split('=', 1)[0]}={int(n)}"
            return out
    return out + ["--replicas", str(int(n))]


class ElasticRestartPolicy(RestartPolicy):
    """RestartPolicy whose respawns pass a NEW replica count: the first
    respawn (and every one after) runs the worker command with
    `-replicas` rewritten to `replicas_after_crash` — the
    shrunken-host resurrection.  Everything else (backoff, quarantine)
    is inherited."""

    def __init__(self, replicas_after_crash: int, **kwargs):
        super().__init__(**kwargs)
        if replicas_after_crash < 1:
            raise ValueError(f"replicas_after_crash must be >= 1, got "
                             f"{replicas_after_crash}")
        self.replicas_after_crash = int(replicas_after_crash)

    def respawn_command(self, worker: "SupervisedWorker",
                        command: List[str]) -> List[str]:
        if worker.incarnation == 0:      # first spawn: as configured
            return command
        return rewrite_replicas(command, self.replicas_after_crash)


@dataclass
class WorkerSpec:
    """One supervised worker: a URL plus (for workers this supervisor
    spawns) the command to run and where its log goes.  ``command is
    None`` means cross-host attach: probes only, restart delegated to
    the policy."""

    name: str
    url: str
    command: Optional[List[str]] = None
    log_path: Optional[str] = None
    # disaggregated role (ISSUE-14): routing policy the supervisor
    # stamps onto every incarnation's Replica — a resurrected prefill
    # worker comes back AS a prefill worker
    role: str = "both"

    def host_port(self):
        parsed = urllib.parse.urlparse(self.url)
        return parsed.hostname or "127.0.0.1", parsed.port


@dataclass
class SupervisedWorker:
    """Runtime state for one supervised worker (internal mutable record;
    read it via `FleetSupervisor.stats()`)."""

    spec: WorkerSpec
    proc: Optional[object] = None          # subprocess.Popen
    replica: Optional[object] = None       # serving.fleet.Replica
    state: str = WORKER_STARTING
    incarnation: int = 0                   # spawns so far
    attaches: int = 0                      # rotations joined so far
    consecutive_crashes: int = 0           # resets on a healthy attach
    probe_failures: int = 0                # consecutive, while attached
    stop_requested: bool = False
    started_at: float = 0.0
    backoff_until: float = 0.0
    died_at: Optional[float] = None        # pending-restart latency clock
    last_restart_latency_s: Optional[float] = None
    error: Optional[str] = None            # CrashLoopError repr
    death_times: collections.deque = field(
        default_factory=lambda: collections.deque(maxlen=32))
    deaths: List[Dict] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.spec.name


class FleetSupervisor:
    """Own spawned `dl4j serve` worker processes end-to-end: detect
    deaths (exit status + `/readyz` together), classify them, restart
    with backoff, quarantine crash-loops, and re-admit resurrected
    workers through warm-then-attach.  See the module docstring for the
    full lifecycle; `docs/robustness.md` "Process supervision" for the
    state diagram.

    The supervisor runs its own poll loop (`start()`/`stop()`, or
    explicit `poll_once()` for deterministic tests); it installs itself
    as ``router.supervisor`` so `/fleet/stats` carries the supervision
    section.  `clock` is injectable for tests."""

    def __init__(self, router, *, policy: Optional[RestartPolicy] = None,
                 poll_interval_s: float = 0.5,
                 ready_timeout_s: float = 60.0,
                 wedge_threshold: int = 3,
                 probe_timeout_s: float = 2.0,
                 detach_grace_s: float = 0.5,
                 log_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        self.router = router
        router.supervisor = self
        self.policy = policy if policy is not None else RestartPolicy()
        self.poll_interval_s = float(poll_interval_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.wedge_threshold = int(wedge_threshold)
        self.probe_timeout_s = float(probe_timeout_s)
        self.detach_grace_s = float(detach_grace_s)
        self._log_dir = log_dir
        self._clock = clock
        self._lock = threading.RLock()
        self.workers: Dict[str, SupervisedWorker] = {}
        self.counters: Dict[str, int] = {
            "spawns": 0, "restarts": 0, "spawn_retries": 0,
            "quarantines": 0, "restart_delegations": 0,
            "deaths_clean": 0, "deaths_crash": 0, "deaths_wedged": 0,
        }
        self.restart_events: List[Dict] = []
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is not None:
            registry.register_collector(self.collector_samples)

    # ---- membership -------------------------------------------------------

    def log_dir(self) -> str:
        if self._log_dir is None:
            self._log_dir = tempfile.mkdtemp(prefix="dl4j-procfleet-")
        return self._log_dir

    def manage(self, spec: WorkerSpec) -> SupervisedWorker:
        """Take ownership of one worker.  Specs WITH a command are
        spawned immediately (state `starting`, attached once `/readyz`
        goes green); URL-only specs are probed until green, then
        attached."""
        with self._lock:
            if spec.name in self.workers:
                raise ValueError(f"worker {spec.name!r} already managed")
            if spec.command is not None and spec.log_path is None:
                spec.log_path = str(pathlib.Path(self.log_dir())
                                    / f"{spec.name}.log")
            worker = SupervisedWorker(spec=spec,
                                      started_at=self._clock())
            self.workers[spec.name] = worker
        if spec.command is not None:
            self._spawn(worker)
        return worker

    def manage_launcher(self, launcher) -> List[SupervisedWorker]:
        """Supervise every worker of a
        `runtime.launcher.FleetProcessLauncher` (same `worker-{i}`
        names `attach_all` uses; the launcher's `log_dir` is adopted
        when set, the supervisor's own otherwise)."""
        out = []
        for i in range(int(launcher.n_replicas)):
            log_path = launcher.log_path(i)
            out.append(self.manage(WorkerSpec(
                name=f"worker-{i}", url=launcher.url(i),
                command=launcher.command(i),
                log_path=str(log_path) if log_path is not None else None,
                role=(launcher.role(i) if hasattr(launcher, "role")
                      else "both"))))
        return out

    def release(self, name: str) -> SupervisedWorker:
        """Lift a quarantine: clear the crash-loop record and schedule
        an immediate respawn (or, for a URL worker, resume probing)."""
        with self._lock:
            worker = self.workers[name]
            if worker.state != WORKER_QUARANTINED:
                raise ValueError(f"worker {name!r} is {worker.state}, "
                                 f"not quarantined")
            worker.error = None
            worker.death_times.clear()
            worker.consecutive_crashes = 0
            if worker.spec.command is not None:
                worker.state = WORKER_BACKOFF
                worker.backoff_until = self._clock()
            else:
                worker.state = WORKER_DOWN
        return worker

    # ---- spawning ---------------------------------------------------------

    def _spawn_command(self, worker: SupervisedWorker) -> List[str]:
        """The command one spawn runs — a seam `chaos_procfleet` wraps
        to inject boot flakes, and the policy's `respawn_command` hook
        rewrites (e.g. a new `-replicas` count for an elastic
        resurrection on a shrunken host)."""
        return self.policy.respawn_command(worker,
                                           list(worker.spec.command))

    def _count_spawn_retry(self) -> None:
        with self._lock:
            self.counters["spawn_retries"] += 1

    def _spawn(self, worker: SupervisedWorker) -> None:
        from deeplearning4j_tpu.runtime.launcher import (
            WorkerSpawnError,
            spawn_logged,
        )

        host, port = worker.spec.host_port()
        command = self._spawn_command(worker)
        now = self._clock()
        try:
            proc = spawn_logged(command, worker.spec.log_path,
                                host=host, port=port,
                                on_bind_retry=self._count_spawn_retry)
        except (WorkerSpawnError, OSError) as e:
            # an unspawnable worker is a death at incarnation start —
            # same backoff/quarantine path as a boot crash
            self._record_death(worker, DEATH_CRASH,
                               f"spawn failed: {e}", now=now)
            return
        with self._lock:
            worker.proc = proc
            worker.incarnation += 1
            worker.stop_requested = False
            worker.probe_failures = 0
            worker.started_at = now
            worker.state = WORKER_STARTING
            self.counters["spawns"] += 1
            if worker.incarnation > 1:
                self.counters["restarts"] += 1

    # ---- probing / attach -------------------------------------------------

    def _probe(self, url: str) -> bool:
        try:
            with urllib.request.urlopen(url + "/readyz",
                                        timeout=self.probe_timeout_s) as r:
                return r.status == 200
        except (http.client.HTTPException, OSError, ValueError):
            return False

    def _attach(self, worker: SupervisedWorker, now: float) -> None:
        """Warm-then-attach: called only after `/readyz` went green (a
        `dl4j serve` worker warms its buckets BEFORE binding readiness),
        so a resurrected worker joins rotation warm and in-flight
        traffic never lands on a cold port."""
        from deeplearning4j_tpu.serving.fleet import Replica

        with self._lock:
            # incarnation-suffixed replica names: failover exclusion and
            # pick tie-breaks key on the NAME, so the resurrection must
            # not inherit the corpse's exclusion entry
            name = (worker.name if worker.attaches == 0
                    else f"{worker.name}#{worker.attaches}")
            replica = Replica(name, worker.spec.url, process=worker.proc,
                              role=worker.spec.role)
            worker.replica = replica
            worker.state = WORKER_READY
            worker.probe_failures = 0
            worker.consecutive_crashes = 0
            worker.attaches += 1
            if worker.died_at is not None:
                latency = now - worker.died_at
                worker.last_restart_latency_s = latency
                worker.died_at = None
                self.restart_events.append({
                    "worker": worker.name, "replica": name,
                    "incarnation": worker.incarnation,
                    "latency_s": round(latency, 3), "at": time.time()})
        self.router.attach(replica)

    def _detach(self, worker: SupervisedWorker) -> None:
        with self._lock:
            replica = worker.replica
            worker.replica = None
        if replica is not None:
            # remove() folds what counts it can still fetch and reports
            # the rest as retired.lost — a corpse cannot answer
            self.router.remove(replica, grace_s=self.detach_grace_s)

    # ---- death handling ---------------------------------------------------

    def _kill_proc(self, worker: SupervisedWorker) -> None:
        from deeplearning4j_tpu.runtime.launcher import kill_process_tree

        proc = worker.proc
        if proc is not None and proc.poll() is None:
            kill_process_tree(proc)
            proc.wait()

    def _log_tail(self, worker: SupervisedWorker, lines: int = 20) -> str:
        from deeplearning4j_tpu.runtime.launcher import tail_lines

        if worker.spec.log_path is None:
            return "<no log captured>"
        return tail_lines(worker.spec.log_path, lines)

    def _classify_exit(self, worker: SupervisedWorker,
                       rc: int) -> (str, str):
        import signal as _signal

        if rc == 0 or rc == -int(_signal.SIGTERM):
            kind = DEATH_CLEAN
            how = ("exit 0" if rc == 0 else "SIGTERM")
        else:
            kind = DEATH_CRASH
            how = (f"signal {-rc}" if rc < 0 else f"exit {rc}")
        if not worker.stop_requested and kind == DEATH_CLEAN:
            how += " (unrequested)"
        return kind, how

    def _record_death(self, worker: SupervisedWorker, kind: str,
                      detail: str, now: float,
                      exit_code: Optional[int] = None) -> None:
        """One death: classify, count, detach the corpse's replica, and
        decide what happens next — stopped (requested), quarantined
        (crash loop), backoff (local respawn) or down (delegated)."""
        self._detach(worker)
        with self._lock:
            if worker.state == WORKER_STOPPED:
                # terminal: a racing second reporter (stop_worker vs a
                # poll tick that classified the SIGTERM exit first) must
                # not record the same death twice
                return
            worker.proc = None
            worker.deaths.append({
                "kind": kind, "detail": detail, "exit": exit_code,
                "incarnation": worker.incarnation, "at": time.time()})
            del worker.deaths[:-8]          # bounded history
            self.counters[f"deaths_{kind}"] += 1
            if worker.stop_requested or kind == DEATH_CLEAN:
                worker.state = WORKER_STOPPED
                return
            if worker.died_at is None:
                worker.died_at = now        # restart-latency clock
            worker.death_times.append(now)
            worker.consecutive_crashes += 1
            if self.policy.quarantine_due(worker.death_times, now):
                err = CrashLoopError(
                    f"worker {worker.name!r} crash-looped: "
                    f"{len(worker.death_times)} deaths, last "
                    f"{self.policy.crash_loop_threshold} inside "
                    f"{self.policy.crash_loop_window_s}s "
                    f"(last: {kind}: {detail.splitlines()[0][:160]}); "
                    f"quarantined — release() to retry")
                worker.error = repr(err)
                worker.state = WORKER_QUARANTINED
                self.counters["quarantines"] += 1
                return
            if worker.spec.command is not None:
                worker.state = WORKER_BACKOFF
                worker.backoff_until = now + self.policy.backoff_s(
                    worker.consecutive_crashes - 1)
                return
            worker.state = WORKER_DOWN
        # delegation hook OUTSIDE the lock: a policy may do slow I/O
        if self.policy.restart(worker):
            with self._lock:
                self.counters["restart_delegations"] += 1

    # ---- the supervision sweep --------------------------------------------

    def poll_once(self) -> Dict[str, str]:
        """One supervision sweep over every managed worker; returns
        ``{worker: state}`` after the sweep.  Deterministic tests call
        this directly with an injected clock; `start()` runs it on the
        poll loop."""
        with self._lock:
            workers = list(self.workers.values())
        for worker in workers:
            self._tick(worker)
        with self._lock:
            return {w.name: w.state for w in self.workers.values()}

    def _tick(self, worker: SupervisedWorker) -> None:
        now = self._clock()
        with self._lock:
            state = worker.state
            proc = worker.proc
        if state in (WORKER_QUARANTINED, WORKER_STOPPED):
            return
        if state == WORKER_BACKOFF:
            if now >= worker.backoff_until:
                self._spawn(worker)
            return
        # exit status first: a dead process's port may still accept for
        # a beat (TIME_WAIT handoff), and the classification should say
        # "crash: signal 9", not "unreachable"
        if proc is not None:
            rc = proc.poll()
            if rc is not None:
                proc.wait()                # reap — never leave a zombie
                kind, how = self._classify_exit(worker, rc)
                detail = how
                if kind != DEATH_CLEAN:
                    detail += ("; last log lines:\n"
                               + self._log_tail(worker))
                self._record_death(worker, kind, detail, now,
                                   exit_code=rc)
                return
        if state == WORKER_STARTING:
            if self._probe(worker.spec.url):
                self._attach(worker, self._clock())
                return
            if (proc is not None
                    and now - worker.started_at > self.ready_timeout_s):
                # never went green: kill it and report WITH the log tail
                tail = self._log_tail(worker)
                self._kill_proc(worker)
                self._record_death(
                    worker, DEATH_CRASH,
                    f"not ready within {self.ready_timeout_s}s of spawn; "
                    f"killed; last log lines:\n{tail}", now)
            return
        if state == WORKER_DOWN:
            # a delegated/externally-restarted worker coming back on the
            # same URL re-enters through the same warm-then-attach gate
            if self._probe(worker.spec.url):
                self._attach(worker, self._clock())
            return
        # WORKER_READY: liveness = the probe
        if self._probe(worker.spec.url):
            with self._lock:
                worker.probe_failures = 0
            return
        with self._lock:
            worker.probe_failures += 1
            wedged = worker.probe_failures >= self.wedge_threshold
        if not wedged:
            return
        if proc is not None:
            # alive-but-unresponsive (SIGSTOP, deadlock): hard-kill —
            # a wedged port hangs clients; a dead one fails fast and
            # the backoff path brings a working incarnation back
            tail = self._log_tail(worker)
            self._kill_proc(worker)
            self._record_death(
                worker, DEATH_WEDGED,
                f"process alive but /readyz failed "
                f"{worker.probe_failures} consecutive probes; "
                f"hard-killed; last log lines:\n{tail}", now)
        else:
            self._record_death(
                worker, DEATH_CRASH,
                f"endpoint unreachable ({worker.probe_failures} "
                f"consecutive probe failures; not spawned here — "
                f"restart delegated to the policy)", now)

    # ---- lifecycle --------------------------------------------------------

    def start(self, interval_s: Optional[float] = None) -> None:
        if interval_s is not None:
            self.poll_interval_s = float(interval_s)
        if self._thread is not None:
            return
        self._stop_event.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-supervisor")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop_event.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — supervision-loop survival backstop: a bug in one sweep must not end ALL future restarts
                pass

    def stop_loop(self) -> None:
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def stop_worker(self, name: str, grace_s: float = 5.0) -> bool:
        """Clean stop: SIGTERM (the worker's graceful drain), escalate
        to a process-group SIGKILL after `grace_s`, always reap.  The
        death classifies `clean` — `stop_requested` is set BEFORE the
        signal so a racing poll tick agrees."""
        import subprocess

        with self._lock:
            worker = self.workers[name]
            worker.stop_requested = True
            proc = worker.proc
        self._detach(worker)
        drained = True
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=max(0.0, float(grace_s)))
            except subprocess.TimeoutExpired:
                drained = False
                self._kill_proc(worker)
        if proc is None:
            # nothing was running (backoff/quarantined/down/attached):
            # park the worker terminally WITHOUT fabricating a death —
            # there was no process to die (the quarantine error, if
            # any, stays visible in stats)
            with self._lock:
                worker.state = WORKER_STOPPED
            return drained
        rc = proc.wait()
        # _record_death is a no-op if a racing poll tick classified the
        # SIGTERM exit first (stop_requested was set before the signal,
        # so that classification was `clean` too)
        self._record_death(worker, DEATH_CLEAN,
                           "stop requested"
                           + ("" if drained else " (grace expired; "
                              "process group killed)"),
                           self._clock(), exit_code=rc)
        return drained

    def stop(self, grace_s: float = 5.0) -> bool:
        """Stop the loop, then every worker (clean SIGTERM -> reap)."""
        self.stop_loop()
        drained = True
        with self._lock:
            names = [n for n, w in self.workers.items()
                     if w.state not in (WORKER_STOPPED,)]
        for name in names:
            drained &= self.stop_worker(name, grace_s=grace_s)
        return drained

    def wait_all_ready(self, timeout_s: float = 60.0) -> bool:
        """Block until every non-quarantined managed worker is READY
        (attached) or `timeout_s` elapses.  Drives `poll_once` itself
        when the background loop is not running."""
        deadline = time.monotonic() + float(timeout_s)
        while True:
            with self._lock:
                pending = [w for w in self.workers.values()
                           if w.state not in (WORKER_READY,
                                              WORKER_QUARANTINED,
                                              WORKER_STOPPED)]
            if not pending:
                return True
            if time.monotonic() >= deadline:
                return False
            if self._thread is None:
                self.poll_once()
            time.sleep(0.05)

    # ---- observation ------------------------------------------------------

    def stats(self) -> Dict:
        """The `/fleet/stats` supervision section: per-worker state +
        death history, the event counters, recent restart latencies,
        and the quarantine list with its typed errors."""
        with self._lock:
            workers = {}
            for w in self.workers.values():
                workers[w.name] = {
                    "state": w.state, "url": w.spec.url,
                    "managed": w.spec.command is not None,
                    "pid": (w.proc.pid if w.proc is not None else None),
                    "incarnation": w.incarnation,
                    "attaches": w.attaches,
                    "consecutive_crashes": w.consecutive_crashes,
                    "probe_failures": w.probe_failures,
                    "last_restart_latency_s": w.last_restart_latency_s,
                    "error": w.error,
                    "deaths": list(w.deaths[-5:]),
                    "log_path": w.spec.log_path,
                }
            return {
                "workers": workers,
                "counters": dict(self.counters),
                "quarantined": sorted(
                    w.name for w in self.workers.values()
                    if w.state == WORKER_QUARANTINED),
                "restart_events": list(self.restart_events[-20:]),
            }

    def collector_samples(self):
        """`fleet_process_*` samples for an obs `MetricsRegistry`
        collector (`registry.register_collector(sup.collector_samples)`
        — `FleetServer` wires this for the `serve-fleet -processes`
        front)."""
        with self._lock:
            c = dict(self.counters)
            states = collections.Counter(
                w.state for w in self.workers.values())
            # restart_events is append-only in attach order, so its
            # tail IS the most recent restart fleet-wide
            last = (self.restart_events[-1]["latency_s"]
                    if self.restart_events else None)
        plain = (("fleet_process_spawns_total",
                  "worker processes spawned", c["spawns"]),
                 ("fleet_process_restarts_total",
                  "crashed/wedged workers respawned", c["restarts"]),
                 ("fleet_process_spawn_retries_total",
                  "port-bind-collision spawn retries",
                  c["spawn_retries"]),
                 ("fleet_process_quarantines_total",
                  "workers quarantined for crash-looping",
                  c["quarantines"]),
                 ("fleet_process_restart_delegations_total",
                  "restarts delegated to the policy (cross-host)",
                  c["restart_delegations"]))
        for name, help, value in plain:
            yield (name, "counter", help, {}, float(value))
        for kind in (DEATH_CLEAN, DEATH_CRASH, DEATH_WEDGED):
            yield ("fleet_process_deaths_total", "counter",
                   "worker deaths by classification",
                   {"kind": kind}, float(c[f"deaths_{kind}"]))
        for state in (WORKER_STARTING, WORKER_READY, WORKER_BACKOFF,
                      WORKER_QUARANTINED, WORKER_STOPPED, WORKER_DOWN):
            yield ("fleet_process_workers", "gauge",
                   "supervised workers by state",
                   {"state": state}, float(states.get(state, 0)))
        if last is not None:
            yield ("fleet_process_last_restart_latency_seconds", "gauge",
                   "most recent death-to-readmission latency",
                   {}, float(last))


__all__ = [
    "CrashLoopError",
    "DEATH_CLEAN",
    "DEATH_CRASH",
    "DEATH_WEDGED",
    "FleetSupervisor",
    "RestartPolicy",
    "SupervisedWorker",
    "WORKER_BACKOFF",
    "WORKER_DOWN",
    "WORKER_QUARANTINED",
    "WORKER_READY",
    "WORKER_STARTING",
    "WORKER_STOPPED",
    "WorkerSpec",
    "stub_worker_command",
]
