"""High-throughput serving subsystem.

The inference-side counterpart of the fused training driver
(`runtime/fused.py`): where training amortizes dispatch overhead by
scanning K optimizer steps per XLA call, serving amortizes it by
coalescing K concurrent *requests* per device dispatch.

- `MicroBatcher` — request queue coalescing concurrent requests within a
  `max_wait_ms` window into one padded dispatch (`batcher.py`);
- `BucketLadder` — fixed batch/length shape ladder so any traffic
  pattern compiles a bounded, pre-warmable program set (`bucketing.py`);
- `ServingEngine` — a MultiLayerNetwork behind batcher + ladder with an
  explicit `warmup()` and a compile-count guard (`engine.py`);
- `ContinuousLMServer` — slot-based continuous LM decode: finished
  sequences free their slot and queued prompts join mid-flight
  (`lm.py`).  KV state is block-table PAGED by default (ISSUE-7):
  a fixed pool of `[pages, page_size]` KV pages addressed through
  per-slot page lists, pages allocated on admission and refcount-freed
  on completion (`PagePool`), shared prompt prefixes prefilled once and
  radix-cached (`RadixPrefixCache`, copy-on-write at the divergence
  page), long prompts fed up to `prefill_chunk` tokens per dispatch;
  `kv="dense"` keeps the original `[slots, max_len]` cache; with
  `speculate="ngram"`/`"model"` (ISSUE-13) a cheap drafter
  (`draft.py`: prompt-lookup `NgramDrafter`, small-model
  `ModelDrafter`) proposes up to `draft_len` tokens per greedy lane
  per round and the target verifies the whole chunk in ONE wide
  dispatch with in-jit accept/rollback — ~2-4 committed tokens per
  dispatch at byte-identical greedy output, rollback a block-table
  pointer move (docs/performance.md "The speculative decode cost
  model");
- `ServingMetrics` — queue depth, batch occupancy, p50/p95/p99 latency,
  requests/s and tokens/s, plus the resilience ledger (`rejected`,
  `shed`, `deadline_missed`, `poison_isolated`, `breaker_state`)
  (`metrics.py`), surfaced via the UI server's `GET /serving/stats`.
  Since ISSUE-8 the cells are `obs.registry` metric objects: the same
  values render as Prometheus text at `GET /metrics`, end-to-end
  latency is split into queue-wait vs dispatch-compute histograms,
  every request is traced (`GET /trace/recent`, X-Request-Id
  propagated across the fleet), and XLA compiles are first-class
  (`compiles_total{program_key=...}`) — docs/observability.md;
- serving-plane resilience (`resilience.py`, ISSUE-4): typed failures
  (`ServingOverloadError` -> 503 + Retry-After, `DeadlineExceededError`
  -> 504, `ServingUnavailableError` -> 503, `CircuitOpenError`,
  `UnservableShapeError` -> 400) and the `CircuitBreaker`; bounded
  admission, deadline shedding, poison-request bisection and graceful
  drain are enforced in `batcher.py`/`lm.py`;
- the serving fleet (`fleet.py`, ISSUE-6): `FleetRouter` over N replica
  endpoints — least-loaded + prefix-affinity dispatch, `/readyz`-driven
  health ejection with half-open re-admission (one `CircuitBreaker` per
  replica), failover resubmission with an excluded-replica set, rolling
  weight swaps, queue-depth autoscale through graceful drain — plus the
  `FleetServer` HTTP front (`/fleet/stats`) and `spawn_local_replica`
  for thread-hosted replicas (process-per-replica launching lives in
  `runtime.launcher.FleetProcessLauncher`);
- disaggregated prefill/decode serving (`transfer.py` + role routing
  in `fleet.py`, ISSUE-14): `PageExport`/`serialize_export`/
  `deserialize_export` — the SHA-256-checked KV page shipping wire
  format; `ContinuousLMServer(ship=True)` grows
  `prefill_export`/`admit_with_pages` so prefill-role workers chew
  long prompts and ship the finished pages to the decode worker the
  router picked up front (failure ladder: dead prefill worker ->
  resubmit to a peer; corrupt/rejected shipment -> recompute locally;
  zero failed requests); sticky `session_id` rendezvous affinity keeps
  multi-turn chats on the replica holding their pages with spill-over
  served by shipping; SSE token streaming on `/lm/generate`
  (`"stream": true`) makes time-to-first-token a first-class
  measurement (docs/architecture.md "Disaggregated serving");
- overload survival (`pressure.py`, ISSUE-15): per-request `priority`
  (`interactive` > `batch` > `best_effort`) accepted on every front,
  with the LM pool's admission queue priority-ordered; KV lane
  PREEMPTION with host swap-out (`ContinuousLMServer(preempt=True)`) —
  a higher-priority request that would wait on a dry `PagePool`
  preempts the lowest-priority lane, gathers its pages through the
  shipping wire frame into a byte-capped LRU `SwapStore`, and the lane
  resumes BYTE-IDENTICALLY on re-admission (evicted/corrupt swap state
  is a typed `SwapEvictedError`/SHA-256 failure and the lane recomputes
  from its prompt — still byte-identical); and the `BrownoutLadder`
  degradation automaton (`brownout=True`) that degrades speculation,
  prefill width, then best_effort lanes before shedding anything,
  hysteresis both directions, every transition counted
  (docs/robustness.md "The degradation ladder");
- multi-tenant traffic shaping (`tenancy.py`, ISSUE-16): a
  `TenantRegistry` of named `TenantSpec`s (WFQ weight, token-rate
  quota + burst, SLO target), accepted on every front via the
  `tenant` field or `X-Tenant` header (the built-in `default` tenant
  keeps pre-tenancy behavior byte-for-byte); a `TokenBucketMeter`
  whose 429s carry a Retry-After derived from the bucket's own refill
  (floored at the brownout ladder's exit timescale while it is up); a
  `FairQueueClock` stamping virtual finish times so the admission
  queue orders by (priority rank, vft, arrival) — weighted fair
  sharing WITHIN a class, classes still dominate, one tenant == the
  historic FIFO; an `SLOTracker` whose burn rate picks brownout
  victims (a compliant tenant's best_effort admits through L4 while
  an offender exists); per-tenant ledgers that must re-add to the
  plane totals (`check_fleet_ledger` reports drift as a typed
  failure) — docs/robustness.md "Tenancy & SLOs";
- tiered KV state hierarchy (`hibernate.py`, ISSUE-19): device pages →
  host LRU tier → disk tier of checksummed, atomically-written blobs
  behind a `MANIFEST.json`; `TieredStateStore` is the `SwapStore`
  surface with a durable bottom, so preempted-lane swap state spills
  to disk instead of vanishing, and idle sticky sessions HIBERNATE
  (`ContinuousLMServer(hibernate_idle_s=..., state_dir=...)`): their
  pages leave the device entirely, keyed by a digest of the token
  prefix (`prefix_key`), and a later request — even from a FRESH
  process over the same directory — resumes them byte-identically.
  KV travels and rests per-page int8-quantized by default
  (`quantize_export`, ~4x smaller; `swap_quantize=False` keeps exact
  bytes); torn/truncated/corrupt/missing blobs surface as typed
  errors on the victim alone and the session recomputes from its
  prompt (docs/robustness.md "The state hierarchy");
- process supervision (`procfleet.py`, ISSUE-10): `FleetSupervisor`
  owns spawned worker processes end-to-end — exit-status + `/readyz`
  crash detection with clean/crash/wedged classification, exponential
  jittered backoff restarts re-admitted through warm-then-attach,
  crash-loop quarantine behind a typed `CrashLoopError`, cross-host
  attach by URL with restart delegated to a pluggable `RestartPolicy`,
  rotating per-worker log capture with tails on crash reports, and
  `fleet_process_*` obs counters (docs/robustness.md "Process
  supervision").

See docs/performance.md (serving cost model), docs/architecture.md and
docs/robustness.md ("serving plane", "serving fleet").
"""

from deeplearning4j_tpu.serving.batcher import MicroBatcher
from deeplearning4j_tpu.serving.bucketing import (
    BucketLadder,
    DEFAULT_BATCH_BUCKETS,
    pow2_length_buckets,
)
from deeplearning4j_tpu.serving.draft import (
    Drafter,
    ModelDrafter,
    NgramDrafter,
)
from deeplearning4j_tpu.serving.engine import ServingEngine
from deeplearning4j_tpu.serving.fleet import (
    FleetClientError,
    FleetRouter,
    FleetServer,
    ROLE_BOTH,
    ROLE_DECODE,
    ROLE_PREFILL,
    Replica,
    check_fleet_ledger,
    spawn_local_replica,
)
from deeplearning4j_tpu.serving.hibernate import (
    DiskTier,
    TieredStateStore,
    prefix_key,
)
from deeplearning4j_tpu.serving.lm import ContinuousLMServer
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.paged import (
    PageLeakError,
    PagePool,
    RadixPrefixCache,
)
from deeplearning4j_tpu.serving.pressure import (
    BrownoutLadder,
    PRIORITY_CLASSES,
    PressureConfig,
    SwapEvictedError,
    SwapStore,
    normalize_priority,
)
from deeplearning4j_tpu.serving.procfleet import (
    CrashLoopError,
    FleetSupervisor,
    RestartPolicy,
    WorkerSpec,
)
from deeplearning4j_tpu.serving.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    ServingError,
    ServingOverloadError,
    ServingUnavailableError,
    TenantQuotaError,
    UnservableShapeError,
)
from deeplearning4j_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    FairQueueClock,
    SLOTracker,
    TenantRegistry,
    TenantSpec,
    TokenBucketMeter,
)
from deeplearning4j_tpu.serving.transfer import (
    PageExport,
    PageShipError,
    check_compatible,
    deserialize_export,
    quantize_export,
    serialize_export,
)

__all__ = [
    "BrownoutLadder",
    "BucketLadder",
    "CircuitBreaker",
    "CircuitOpenError",
    "ContinuousLMServer",
    "CrashLoopError",
    "DEFAULT_BATCH_BUCKETS",
    "DEFAULT_TENANT",
    "DeadlineExceededError",
    "DiskTier",
    "Drafter",
    "FairQueueClock",
    "FleetClientError",
    "FleetRouter",
    "FleetServer",
    "FleetSupervisor",
    "MicroBatcher",
    "ModelDrafter",
    "NgramDrafter",
    "PageExport",
    "PageShipError",
    "PRIORITY_CLASSES",
    "PressureConfig",
    "RestartPolicy",
    "ROLE_BOTH",
    "ROLE_DECODE",
    "ROLE_PREFILL",
    "PageLeakError",
    "PagePool",
    "RadixPrefixCache",
    "Replica",
    "ServingEngine",
    "ServingError",
    "ServingMetrics",
    "SLOTracker",
    "ServingOverloadError",
    "ServingUnavailableError",
    "SwapEvictedError",
    "SwapStore",
    "TenantQuotaError",
    "TenantRegistry",
    "TenantSpec",
    "TieredStateStore",
    "TokenBucketMeter",
    "UnservableShapeError",
    "WorkerSpec",
    "check_compatible",
    "check_fleet_ledger",
    "deserialize_export",
    "normalize_priority",
    "pow2_length_buckets",
    "prefix_key",
    "quantize_export",
    "serialize_export",
    "spawn_local_replica",
]
