"""High-throughput serving subsystem.

The inference-side counterpart of the fused training driver
(`runtime/fused.py`): where training amortizes dispatch overhead by
scanning K optimizer steps per XLA call, serving amortizes it by
coalescing K concurrent *requests* per device dispatch.

- `MicroBatcher` — request queue coalescing concurrent requests within a
  `max_wait_ms` window into one padded dispatch (`batcher.py`);
- `BucketLadder` — fixed batch/length shape ladder so any traffic
  pattern compiles a bounded, pre-warmable program set (`bucketing.py`);
- `ServingEngine` — a MultiLayerNetwork behind batcher + ladder with an
  explicit `warmup()` and a compile-count guard (`engine.py`);
- `ContinuousLMServer` — slot-based continuous LM decode over one fixed
  `[slots, max_len]` KV cache: finished sequences free their slot and
  queued prompts join mid-flight (`lm.py`);
- `ServingMetrics` — queue depth, batch occupancy, p50/p95/p99 latency,
  requests/s and tokens/s (`metrics.py`), surfaced via the UI server's
  `GET /serving/stats`.

See docs/performance.md (serving cost model) and docs/architecture.md.
"""

from deeplearning4j_tpu.serving.batcher import MicroBatcher
from deeplearning4j_tpu.serving.bucketing import (
    BucketLadder,
    DEFAULT_BATCH_BUCKETS,
    pow2_length_buckets,
)
from deeplearning4j_tpu.serving.engine import ServingEngine
from deeplearning4j_tpu.serving.lm import ContinuousLMServer
from deeplearning4j_tpu.serving.metrics import ServingMetrics

__all__ = [
    "BucketLadder",
    "ContinuousLMServer",
    "DEFAULT_BATCH_BUCKETS",
    "MicroBatcher",
    "ServingEngine",
    "ServingMetrics",
    "pow2_length_buckets",
]
