"""Overload survival for the LM pool: priorities, host swap, brownout.

The paged KV pool (ISSUE-7) made device capacity a refcounted page
economy; the serving plane (ISSUE-4/6) made *request* overload typed
and sheddable.  What was still missing is policy for the pool itself:
under pressure the only behaviors were FIFO head-of-line waiting (a
long low-value lane pins pages while latency-sensitive traffic queues
behind it) and, at the very end, shedding.  This module owns the three
policy pieces the ISSUE-15 overload-survival plane is built from; all
of them are plain host Python (stdlib-only — the HTTP fronts import
the priority vocabulary without touching numpy/jax):

- **Priority classes** — the closed request vocabulary
  (``interactive`` > ``batch`` > ``best_effort``) every front accepts
  and the pool's admission queue is ordered by.  `normalize_priority`
  is THE validation gate: an unknown class is the client's 400, never
  a silent default.

- **`SwapStore`** — a bounded host-side byte store for preempted
  lanes' serialized KV state (`serving/transfer.py` wire frames, so
  restore inherits the SHA-256 integrity check for free).  LRU,
  byte-capped: storing a new victim evicts the least-recently-stored
  ones first; a victim whose state was dropped surfaces as a typed
  `SwapEvictedError` at restore time and the pool falls back to
  recomputing the lane from its prompt — byte-identical by the same
  determinism argument that makes radix sharing sound, never a wrong
  token.  Single-mutator like `PagePool`: the LM worker thread owns
  every mutation (admission/preemption run under the server's
  condition lock); the store itself takes no locks.

- **`BrownoutLadder`** — the pool-pressure automaton that degrades
  gracefully BEFORE shedding.  Inputs are the two pressure signals the
  pool already publishes (pages-free fraction and queue depth per
  slot); output is a level 0..4:

      0 healthy        — nothing degraded
      1 no_spec        — speculation disabled (spec buys throughput,
                         not survival: drafts burn wide-dispatch
                         compute and widen latency jitter)
      2 narrow         — prefill ride-along width shrunk (and any
                         draft budget capped): decode lanes get more
                         frequent commits, admission throughput pays
      3 preempt        — best_effort lanes are preempted proactively
                         whenever higher-class work waits
      4 shed           — best_effort ADMISSIONS are refused with 503 +
                         Retry-After; interactive (and batch) still
                         admit — the ladder never touches interactive

  Hysteresis both directions: a level is entered the moment a signal
  crosses its enter threshold, and left only after the signal has
  stayed below enter-threshold-minus-margin (free pages) / under
  enter-threshold-times-factor (queue) for `down_dwell` consecutive
  updates, one level per step — so a pool hovering at a threshold
  cannot flap the ladder every scheduling round.  Every transition is
  counted and kept in a bounded history for `stats()`/traces.

docs/robustness.md "The degradation ladder" has the state diagram and
the swap-out byte-parity invariant.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Priority classes

# The closed vocabulary, best-first.  Rank is the queue sort key:
# LOWER rank = more important = served first = never preempted by the
# ladder.  Requests default to interactive so existing clients keep
# their exact pre-ISSUE-15 behavior (one class == FIFO by arrival).
PRIORITY_CLASSES = ("interactive", "batch", "best_effort")
PRIORITY_RANK: Dict[str, int] = {c: i for i, c in
                                 enumerate(PRIORITY_CLASSES)}
RANK_INTERACTIVE = PRIORITY_RANK["interactive"]
RANK_BATCH = PRIORITY_RANK["batch"]
RANK_BEST_EFFORT = PRIORITY_RANK["best_effort"]
DEFAULT_PRIORITY = "interactive"


def normalize_priority(priority: Optional[str]) -> str:
    """THE priority-validation gate, shared by the HTTP fronts (as
    400s) and `ContinuousLMServer` (as ValueErrors).  None means the
    client sent nothing: default interactive — a latency-sensitive
    caller that predates priorities must not silently become
    preemptible."""
    if priority is None:
        return DEFAULT_PRIORITY
    p = str(priority)
    if p not in PRIORITY_RANK:
        raise ValueError(
            f"priority must be one of {PRIORITY_CLASSES}, got {p!r}")
    return p


# ---------------------------------------------------------------------------
# Host-side swap store for preempted lanes


class SwapEvictedError(RuntimeError):
    """A preempted lane's swapped-out state is gone: the byte-capped
    store evicted it (LRU) to make room for later victims, or the blob
    never fit the cap at all.  The pool's restore path answers this by
    RECOMPUTING the lane from its prompt — deterministic decode makes
    the recomputed tokens byte-identical to the swapped ones, so the
    client never sees this error, only the accounting does."""


class SwapStore:
    """Bounded LRU byte store: swap_key -> serialized lane state.

    Single-mutator (the LM worker thread, under the server's condition
    lock) like `PagePool` — no locks of its own.  `put` stores a blob,
    evicting least-recently-stored entries until it fits (a blob larger
    than the whole cap is refused outright — counted, not stored);
    `take` removes and returns a blob, raising `SwapEvictedError` for a
    key that is no longer there.  `peak_bytes` is the high-water mark
    the bench's byte-cap gate pins.
    """

    def __init__(self, capacity_bytes: int):
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1, got {capacity_bytes}")
        self.capacity_bytes = int(capacity_bytes)
        self._blobs: "collections.OrderedDict[str, bytes]" = (
            collections.OrderedDict())
        self.bytes_stored = 0
        self.peak_bytes = 0
        self.puts = 0
        self.takes = 0
        self.evicted = 0        # entries dropped to make room
        self.rejected = 0       # blobs larger than the whole cap

    def __len__(self) -> int:
        return len(self._blobs)

    def put(self, key: str, blob: bytes) -> Optional[List[str]]:
        """Store `blob` under `key`.  Returns the list of keys evicted
        to make room (possibly empty), or None when the blob alone
        exceeds the cap and was refused — the caller falls back to
        recompute-from-prompt for that lane instead of silently
        wiping every other victim's state for one oversized lane."""
        size = len(blob)
        if size > self.capacity_bytes:
            self.rejected += 1
            return None
        evicted: List[str] = []
        while self.bytes_stored + size > self.capacity_bytes:
            old_key, old = self._blobs.popitem(last=False)
            self.bytes_stored -= len(old)
            self.evicted += 1
            evicted.append(old_key)
        self._blobs[key] = blob
        self.bytes_stored += size
        self.peak_bytes = max(self.peak_bytes, self.bytes_stored)
        self.puts += 1
        return evicted

    def take(self, key: str) -> bytes:
        """Remove and return the blob under `key`; `SwapEvictedError`
        when it was evicted (or never stored)."""
        blob = self._blobs.pop(key, None)
        if blob is None:
            raise SwapEvictedError(
                f"swapped-out lane state {key!r} is gone (evicted from "
                f"the {self.capacity_bytes}-byte store)")
        self.bytes_stored -= len(blob)
        self.takes += 1
        return blob

    def discard(self, key: str) -> None:
        """Drop a blob without reading it (its request was shed or
        abandoned before restore); a no-op when already evicted."""
        blob = self._blobs.pop(key, None)
        if blob is not None:
            self.bytes_stored -= len(blob)

    def clear(self) -> None:
        self._blobs.clear()
        self.bytes_stored = 0

    def stats(self) -> Dict:
        return {"entries": len(self._blobs),
                "bytes": self.bytes_stored,
                "capacity_bytes": self.capacity_bytes,
                "peak_bytes": self.peak_bytes,
                "puts": self.puts, "takes": self.takes,
                "evicted": self.evicted, "rejected": self.rejected}


# ---------------------------------------------------------------------------
# Brownout degradation ladder

# Level names, index == level (the closed vocabulary stats/docs use)
BROWNOUT_LEVELS = ("healthy", "no_spec", "narrow", "preempt", "shed")


@dataclasses.dataclass(frozen=True)
class PressureConfig:
    """Thresholds for the 4 degraded levels (index k = level k+1).

    A level is ENTERED when pages-free fraction drops to
    ``enter_free_frac[k]`` or queue-depth-per-slot reaches
    ``enter_queue_ratio[k]``; it is LEFT (one step down) only after
    BOTH signals have stayed calm — free fraction above
    enter + ``exit_free_margin`` AND queue ratio below
    enter * ``exit_queue_factor`` — for ``down_dwell`` consecutive
    updates.  The margin/factor gap plus the dwell are the hysteresis:
    a pool hovering at a threshold cannot flap."""

    enter_free_frac: Tuple[float, ...] = (0.5, 0.25, 0.125, 0.05)
    enter_queue_ratio: Tuple[float, ...] = (2.0, 4.0, 8.0, 16.0)
    exit_free_margin: float = 0.125
    exit_queue_factor: float = 0.5
    down_dwell: int = 3

    def __post_init__(self):
        if len(self.enter_free_frac) != len(self.enter_queue_ratio):
            raise ValueError(
                f"enter_free_frac ({len(self.enter_free_frac)}) and "
                f"enter_queue_ratio ({len(self.enter_queue_ratio)}) "
                f"must define the same number of levels")
        if not self.enter_free_frac:
            raise ValueError("at least one degraded level is required")
        if list(self.enter_free_frac) != sorted(self.enter_free_frac,
                                                reverse=True):
            raise ValueError("enter_free_frac must be non-increasing "
                             "(deeper levels = less free)")
        if list(self.enter_queue_ratio) != sorted(self.enter_queue_ratio):
            raise ValueError("enter_queue_ratio must be non-decreasing "
                             "(deeper levels = more queued)")
        if self.down_dwell < 1:
            raise ValueError(f"down_dwell must be >= 1, got "
                             f"{self.down_dwell}")
        if len(self.enter_free_frac) != len(BROWNOUT_LEVELS) - 1:
            # the rung EFFECTS are a closed vocabulary (no_spec /
            # narrow / preempt / shed, hardwired at levels 1-4 in the
            # pool): a shorter ladder would silently drop the preempt
            # and shed rungs, a longer one would add levels that do
            # nothing
            raise ValueError(
                f"exactly {len(BROWNOUT_LEVELS) - 1} degraded levels "
                f"are required (the rung effects "
                f"{BROWNOUT_LEVELS[1:]} are fixed), got "
                f"{len(self.enter_free_frac)}")


class BrownoutLadder:
    """The pool-pressure automaton.  Single-mutator (the LM worker
    thread calls `update` once per admission round); readers take the
    server's lock like every other pool stat."""

    def __init__(self, config: Optional[PressureConfig] = None):
        self.config = config if config is not None else PressureConfig()
        self.level = 0
        self.max_level = len(self.config.enter_free_frac)
        self._calm_updates = 0
        self.transitions_up = 0
        self.transitions_down = 0
        # bounded history of (from, to) level moves, oldest dropped
        self.history: "collections.deque[Tuple[int, int]]" = (
            collections.deque(maxlen=64))
        self.updates = 0

    def _target(self, free_frac: float, queue_ratio: float) -> int:
        cfg, target = self.config, 0
        for k in range(self.max_level):
            if (free_frac <= cfg.enter_free_frac[k]
                    or queue_ratio >= cfg.enter_queue_ratio[k]):
                target = k + 1
        return target

    def update(self, pages_free: int, pages_total: int,
               queue_depth: int, slots: int) -> List[Tuple[int, int]]:
        """One pressure reading -> the transitions it caused (usually
        none).  Upward moves are immediate (pressure is NOW) and may
        jump several levels on a sudden exhaustion; downward moves are
        one level per `down_dwell` consecutive calm updates."""
        self.updates += 1
        cfg = self.config
        free_frac = pages_free / max(1, pages_total)
        queue_ratio = queue_depth / max(1, slots)
        target = self._target(free_frac, queue_ratio)
        moves: List[Tuple[int, int]] = []
        if target > self.level:
            moves.append((self.level, target))
            self.level = target
            self._calm_updates = 0
            self.transitions_up += 1
        elif self.level > 0:
            k = self.level - 1
            calm = (free_frac > cfg.enter_free_frac[k]
                    + cfg.exit_free_margin
                    and queue_ratio < cfg.enter_queue_ratio[k]
                    * cfg.exit_queue_factor)
            if calm:
                self._calm_updates += 1
                if self._calm_updates >= cfg.down_dwell:
                    moves.append((self.level, self.level - 1))
                    self.level -= 1
                    self._calm_updates = 0
                    self.transitions_down += 1
            else:
                self._calm_updates = 0
        for m in moves:
            self.history.append(m)
        return moves

    @property
    def transitions(self) -> int:
        return self.transitions_up + self.transitions_down

    def stats(self) -> Dict:
        return {"level": self.level,
                "level_name": BROWNOUT_LEVELS[
                    min(self.level, len(BROWNOUT_LEVELS) - 1)],
                "transitions_up": self.transitions_up,
                "transitions_down": self.transitions_down,
                "updates": self.updates,
                "recent": [list(m) for m in self.history][-8:]}


__all__ = [
    "BROWNOUT_LEVELS",
    "BrownoutLadder",
    "DEFAULT_PRIORITY",
    "PRIORITY_CLASSES",
    "PRIORITY_RANK",
    "PressureConfig",
    "RANK_BATCH",
    "RANK_BEST_EFFORT",
    "RANK_INTERACTIVE",
    "SwapEvictedError",
    "SwapStore",
    "normalize_priority",
]
