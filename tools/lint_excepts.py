#!/usr/bin/env python
"""Fail on new broad exception handlers in deeplearning4j_tpu/.

A bare ``except:`` / ``except Exception:`` / ``except BaseException:``
swallows real bugs (AttributeError from a typo looks exactly like a
network flake) and is how the NaN-eats-the-run class of failures hides.
The resilience subsystem narrows every handler it owns; this check keeps
the codebase from growing new broad ones.

A broad handler is allowed only when the ``except`` line carries an
explicit ``noqa: BLE001`` pragma (with a justification comment) or the
file has an entry in ALLOWLIST below.  Run directly or via
tests/test_lint_excepts.py (tier-1).

Usage: python tools/lint_excepts.py [root]
"""

from __future__ import annotations

import ast
import pathlib
import sys

# path (relative to repo root) -> max number of un-pragma'd broad handlers
# tolerated.  Keep this EMPTY: new broad handlers should either be
# narrowed or carry a justified `noqa: BLE001` pragma on the except line.
ALLOWLIST: dict = {}

PACKAGE = "deeplearning4j_tpu"
PRAGMA = "noqa: BLE001"


def _is_broad(handler: ast.ExceptHandler) -> bool:
    """True for ``except:``, ``except Exception``, ``except BaseException``,
    including tuple forms that contain either."""
    t = handler.type
    if t is None:
        return True

    def broad_name(node) -> bool:
        return isinstance(node, ast.Name) and node.id in (
            "Exception", "BaseException")

    if isinstance(t, ast.Tuple):
        return any(broad_name(el) for el in t.elts)
    return broad_name(t)


def broad_handlers(path: pathlib.Path):
    """Yield (lineno, line) for each un-pragma'd broad handler in `path`."""
    source = path.read_text()
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        yield (e.lineno or 0, f"<syntax error: {e}>")
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node):
            line = lines[node.lineno - 1]
            if PRAGMA not in line:
                yield (node.lineno, line.strip())


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = pathlib.Path(argv[0]) if argv else \
        pathlib.Path(__file__).resolve().parent.parent
    pkg = root / PACKAGE
    failures = []
    for path in sorted(pkg.rglob("*.py")):
        rel = str(path.relative_to(root))
        found = list(broad_handlers(path))
        allowed = ALLOWLIST.get(rel, 0)
        if len(found) > allowed:
            for lineno, line in found[allowed:]:
                failures.append(f"{rel}:{lineno}: broad except handler "
                                f"without '{PRAGMA}' pragma: {line}")
    if failures:
        print(f"{len(failures)} broad exception handler(s) found — narrow "
              f"the exception types (see resilience/retry.py for the "
              f"transient-failure pattern), or justify with a "
              f"'# {PRAGMA} — <reason>' pragma:")
        for f in failures:
            print(" ", f)
        return 1
    print("lint_excepts: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
