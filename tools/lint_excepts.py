#!/usr/bin/env python
"""Fail on new broad exception handlers in deeplearning4j_tpu/.

Thin shim (ISSUE-11): the pass itself now lives in
``tools/dl4jlint/pass_excepts.py`` (the BLE0xx codes of the dl4jlint
framework), which preserves the original semantics exactly — relaxed
pragma mode package-wide, strict pragma-proof ceilings under serving/,
obs/ and the process launcher.  This module re-exports the historical
surface (`broad_handlers`, `main`, the allowlists) so existing callers
and tests/test_lint_excepts.py keep working unchanged.

Usage: python tools/lint_excepts.py [root]
       python -m tools.dl4jlint --select excepts   (framework form)
"""

from __future__ import annotations

import pathlib
import sys

if not __package__:
    # direct-script mode (`python tools/lint_excepts.py`): make the
    # repo root importable; as `tools.lint_excepts` it already is, and
    # mutating sys.path on import would let repo top-level names shadow
    # installed packages
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dl4jlint.pass_excepts import (  # noqa: E402,F401
    ALLOWLIST,
    LAUNCHER_ALLOWLIST,
    LAUNCHER_PREFIX,
    OBS_ALLOWLIST,
    OBS_PREFIX,
    PACKAGE,
    PRAGMA,
    SERVING_ALLOWLIST,
    SERVING_PREFIX,
    STRICT_PREFIXES,
    BroadExceptPass,
    _is_broad,
    broad_handlers,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
