#!/usr/bin/env python
"""Fail on new broad exception handlers in deeplearning4j_tpu/.

A bare ``except:`` / ``except Exception:`` / ``except BaseException:``
swallows real bugs (AttributeError from a typo looks exactly like a
network flake) and is how the NaN-eats-the-run class of failures hides.
The resilience subsystem narrows every handler it owns; this check keeps
the codebase from growing new broad ones.

A broad handler is allowed only when the ``except`` line carries an
explicit ``noqa: BLE001`` pragma (with a justification comment) or the
file has an entry in ALLOWLIST below.  Run directly or via
tests/test_lint_excepts.py (tier-1).

Usage: python tools/lint_excepts.py [root]
"""

from __future__ import annotations

import ast
import pathlib
import sys

# path (relative to repo root) -> max number of un-pragma'd broad handlers
# tolerated.  Keep this EMPTY: new broad handlers should either be
# narrowed or carry a justified `noqa: BLE001` pragma on the except line.
ALLOWLIST: dict = {}

# Under serving/ the bar is higher (ISSUE-4): the request path is where a
# swallowed AttributeError becomes a silent wrong answer at scale, so a
# `noqa: BLE001` pragma alone is NOT enough — every broad handler,
# pragma'd or not, must be accounted for here with its exact ceiling.
# The documented sites are the group-failure isolators (a dispatch group
# / decode step must fail its OWN requests whatever the device raised)
# and the worker-survival backstops (the worker thread must outlive any
# group failure, or every future submit hangs on a dead queue).
SERVING_ALLOWLIST: dict = {
    "deeplearning4j_tpu/serving/batcher.py": 2,  # _execute bisector +
                                                 # _run survival backstop
    "deeplearning4j_tpu/serving/lm.py": 1,       # _run fail-in-flight
    "deeplearning4j_tpu/serving/fleet.py": 1,    # _FleetHandler.do_POST
                                                 # catch-all: the fleet
                                                 # front must keep
                                                 # serving (500 once,
                                                 # typed stay 4xx/503)
    "deeplearning4j_tpu/serving/procfleet.py": 1,  # supervision-loop
                                                   # survival backstop:
                                                   # a bug in one sweep
                                                   # must not end ALL
                                                   # future restarts
}
SERVING_PREFIX = "deeplearning4j_tpu/serving/"

# The process launcher gets the strict bar too (ISSUE-10): a swallowed
# exception around spawn/reap/kill is how zombies and orphaned worker
# process groups hide — no broad handlers at all, pragma'd or not.
LAUNCHER_ALLOWLIST: dict = {}
LAUNCHER_PREFIX = "deeplearning4j_tpu/runtime/launcher.py"

# The observability plane gets the same strict bar (ISSUE-8): a
# swallowed exception inside a metrics/trace hook silently blinds the
# system right when something is going wrong — no broad handlers at
# all, pragma'd or not.
OBS_ALLOWLIST: dict = {}
OBS_PREFIX = "deeplearning4j_tpu/obs/"

# prefix -> (allowlist, label) for the strict-mode passes
STRICT_PREFIXES = (
    (SERVING_PREFIX, SERVING_ALLOWLIST, "SERVING_ALLOWLIST"),
    (OBS_PREFIX, OBS_ALLOWLIST, "OBS_ALLOWLIST"),
    (LAUNCHER_PREFIX, LAUNCHER_ALLOWLIST, "LAUNCHER_ALLOWLIST"),
)

PACKAGE = "deeplearning4j_tpu"
PRAGMA = "noqa: BLE001"


def _is_broad(handler: ast.ExceptHandler) -> bool:
    """True for ``except:``, ``except Exception``, ``except BaseException``,
    including tuple forms that contain either."""
    t = handler.type
    if t is None:
        return True

    def broad_name(node) -> bool:
        return isinstance(node, ast.Name) and node.id in (
            "Exception", "BaseException")

    if isinstance(t, ast.Tuple):
        return any(broad_name(el) for el in t.elts)
    return broad_name(t)


def broad_handlers(path: pathlib.Path, respect_pragma: bool = True):
    """Yield (lineno, line) for each broad handler in `path`.  With
    `respect_pragma` (the default), handlers whose except line carries
    the `noqa: BLE001` pragma are skipped; `respect_pragma=False` counts
    EVERY broad handler — the serving/ strict mode."""
    source = path.read_text()
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        yield (e.lineno or 0, f"<syntax error: {e}>")
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node):
            line = lines[node.lineno - 1]
            if not respect_pragma or PRAGMA not in line:
                yield (node.lineno, line.strip())


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = pathlib.Path(argv[0]) if argv else \
        pathlib.Path(__file__).resolve().parent.parent
    pkg = root / PACKAGE
    failures = []
    for path in sorted(pkg.rglob("*.py")):
        rel = str(path.relative_to(root))
        strict = next(((allow, label)
                       for prefix, allow, label in STRICT_PREFIXES
                       if rel.startswith(prefix)), None)
        if strict is not None:
            # strict mode subsumes the relaxed pragma check: count EVERY
            # broad handler (pragma'd or not) against the explicit
            # allowlist ceiling, and report each offender once
            allow, label = strict
            every = list(broad_handlers(path, respect_pragma=False))
            ceiling = allow.get(rel, 0)
            if len(every) > ceiling:
                for lineno, line in every[ceiling:]:
                    failures.append(
                        f"{rel}:{lineno}: broad except handler exceeds "
                        f"the {label} ceiling ({ceiling}) — narrow it "
                        f"or (if it really is a group-failure isolator) "
                        f"raise the ceiling with a review: {line}")
            continue
        found = list(broad_handlers(path))
        allowed = ALLOWLIST.get(rel, 0)
        if len(found) > allowed:
            for lineno, line in found[allowed:]:
                failures.append(f"{rel}:{lineno}: broad except handler "
                                f"without '{PRAGMA}' pragma: {line}")
    if failures:
        print(f"{len(failures)} broad exception handler(s) found — narrow "
              f"the exception types (see resilience/retry.py for the "
              f"transient-failure pattern), or justify with a "
              f"'# {PRAGMA} — <reason>' pragma:")
        for f in failures:
            print(" ", f)
        return 1
    print("lint_excepts: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
