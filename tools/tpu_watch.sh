#!/usr/bin/env bash
# TPU-window watcher (VERDICT r4 next-round #1/#2).
#
# The axon tunnel to the one real chip wedges for hours and answers in
# short windows (round 3: 18 minutes).  This script probes cheaply and,
# the moment a real matmul round-trips, runs the harvest sequence in
# strict value order — flagship rows first, long tail after — so even a
# short window banks committed TPU evidence.  Everything is logged to
# EVIDENCE/ and committed with `git commit --only` (never touches the
# builder's staged work).
#
# Usage: nohup tools/tpu_watch.sh >/tmp/tpu_watch.out 2>&1 &
set -u
cd "$(dirname "$0")/.."
REPO=$PWD
LOG_DIR=$REPO/EVIDENCE
mkdir -p "$LOG_DIR"
PROBE_S=${TPU_WATCH_PROBE_TIMEOUT:-180}
SLEEP_S=${TPU_WATCH_INTERVAL:-300}
LOCK=/tmp/dl4j_git.lock
STAMP() { date -u +%Y%m%d_%H%M; }
# Status lines also go to a repo-tracked file: /tmp dies with the
# machine, and the outage record (how long the tunnel was down, how many
# probes it ate) is evidence worth committing (VERDICT r4 weak #3).
STATUS_LOG=$LOG_DIR/tpu_watch_status.log
say() { echo "$*"; echo "$*" >>"$STATUS_LOG"; }

probe() {
    # Fresh process per probe: jax caches a failed backend for process
    # lifetime, and a wedged tunnel HANGS (not errors) in init.
    timeout "$PROBE_S" python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
y = (x @ x).block_until_ready()
assert jax.default_backend() == 'tpu', jax.default_backend()
print('probe ok', jax.devices())
" >/dev/null 2>&1
}

commit_paths() {
    # `git commit --only` errors on untracked paths, which is how the
    # round-5 01:01 UTC window's TPU pin sidecar was lost (it was also
    # gitignored then — both fixed): force-add first.  One flock-held
    # critical section so the add+commit is atomic vs the builder's own
    # git use; `--only` keeps the builder's staged work out of the
    # commit, and a failed commit resets the force-added paths so the
    # shared index is left as found.
    local msg=$1; shift
    local p; local -a have=()
    for p in "$@"; do [ -e "$p" ] && have+=("$p"); done
    [ ${#have[@]} -gt 0 ] || return 0
    flock -w 120 "$LOCK" bash -c '
        msg=$1; shift
        ok=()
        for p in "$@"; do
            if git add -f -- "$p" >/dev/null 2>&1 ||
               git ls-files --error-unmatch -- "$p" >/dev/null 2>&1; then
                ok+=("$p")
            fi
        done
        [ ${#ok[@]} -gt 0 ] || exit 0
        git commit --only -m "$msg" -- "${ok[@]}" >/dev/null 2>&1 ||
            git reset -q -- "${ok[@]}" 2>/dev/null || true
    ' _ "$msg" "${have[@]}" || true
}

stage() {
    # stage <name> <timeout_s> <env...> -- runs bench.py, logs, commits.
    #
    # Re-probe before every stage: when the tunnel wedges mid-harvest, a
    # hung dial never recovers even if the tunnel later does (jax caches
    # the failed backend per process), so without this gate each
    # remaining stage burns its full timeout — hours of missed green
    # windows.  A failed gate costs one probe and hands control back to
    # the main loop, which restarts the whole value-ordered harvest on
    # the next green probe.
    local name=$1 tmo=$2; shift 2
    if ! probe; then
        say "stage $name skipped $(date -u): tunnel wedged (pre-probe)"
        return 125
    fi
    local log="$LOG_DIR/tpu_${name}_$(STAMP).log"
    {
        echo "== $name  $(date -u)  sha=$(git rev-parse --short HEAD)"
        env | grep -E 'BENCH_|XLA_|JAX_' || true
    } >"$log"
    # BENCH_CPU_FALLBACK=0: a TPU-harvest stage must bank a TPU number
    # or an honest failure — never a load-polluted CPU fallback row
    # committed under a "TPU harvest" message.
    timeout "$tmo" env BENCH_CPU_FALLBACK=0 "$@" python bench.py \
        >>"$log" 2>&1
    local rc=$?
    echo "== rc=$rc  $(date -u)" >>"$log"
    commit_paths "TPU harvest: $name (rc=$rc, watcher)" \
        "$log" "$STATUS_LOG" BENCH_full.json BENCH_smoke.json \
        .bench_baseline.json
    return $rc
}

say "watcher armed $(date -u); probing every ${SLEEP_S}s"
FAILED=0
while :; do
    if probe; then
        say "GREEN $(date -u) — harvesting"
        # Value order: flagship transformer (proves the flash kernel fix
        # + MFU row), GPT-2 124M, flash A/B, S=16k long-context, fused
        # LSTM A/B, then the full canonical suite (warm cache makes the
        # already-run rows cheap).
        stage transformer 1800 BENCH_ONLY=transformer BENCH_FORCE_PIN=1
        stage gpt2        2400 BENCH_ONLY=gpt2 BENCH_FORCE_PIN=1
        stage flashab     1800 BENCH_ONLY=flashab BENCH_FORCE_PIN=1
        stage decode      1800 BENCH_ONLY=decode BENCH_FORCE_PIN=1
        stage longctx     1800 BENCH_ONLY=longctx BENCH_FORCE_PIN=1
        stage lstm        1800 BENCH_ONLY=lstm BENCH_FORCE_PIN=1
        stage gpt2mem     2400 BENCH_ONLY=gpt2mem
        stage canonical   5400 BENCH_ATTEMPT_TIMEOUT=5400
        say "harvest complete $(date -u); watcher continues"
        touch /tmp/tpu_harvest_done
        FAILED=0
    else
        # Document the outage: one line per 20 hung probes, so the log
        # itself shows the tunnel was down (not that nobody was watching).
        FAILED=$((FAILED + 1))
        if [ $((FAILED % 20)) -eq 0 ]; then
            say "still wedged $(date -u): $FAILED consecutive probes hung"
        fi
    fi
    sleep "$SLEEP_S"
done
